"""Consensus containers (phase0 + altair), built per-Spec.

The reference monomorphizes containers over the `EthSpec` trait
(consensus/types/src/beacon_state.rs:295, beacon_block.rs, attestation.rs,
superstruct-versioned for forks). Here `types_for(spec)` builds the same
family of SSZ container classes with the spec's sizes baked into List/Vector
limits, cached per spec name. Fork variants are separate classes
(`BeaconStatePhase0` / `BeaconStateAltair`, same for blocks/bodies) with a
shared field prefix, dispatched by `spec.fork_name_at_epoch`.
"""

from types import SimpleNamespace

from lighthouse_tpu import ssz
from lighthouse_tpu.types.spec import (
    DEPOSIT_CONTRACT_TREE_DEPTH,
    JUSTIFICATION_BITS_LENGTH,
    Spec,
)

Root = ssz.bytes32
Hash32 = ssz.bytes32
Slot = ssz.uint64
Epoch = ssz.uint64
CommitteeIndex = ssz.uint64
ValidatorIndex = ssz.uint64
Gwei = ssz.uint64
Version = ssz.bytes4
DomainType = ssz.bytes4
Domain = ssz.bytes32
BLSPubkey = ssz.bytes48
BLSSignature = ssz.bytes96
ParticipationFlags = ssz.uint8
KZGCommitment = ssz.bytes48
KZGProof = ssz.bytes48

_CACHE: dict[str, SimpleNamespace] = {}


def types_for(spec: Spec) -> SimpleNamespace:
    if spec.name in _CACHE:
        return _CACHE[spec.name]

    # ----------------------------------------------------------- fork-free

    class Fork(ssz.Container):
        previous_version: Version
        current_version: Version
        epoch: Epoch

    class ForkData(ssz.Container):
        current_version: Version
        genesis_validators_root: Root

    class Checkpoint(ssz.Container):
        epoch: Epoch
        root: Root

    class SigningData(ssz.Container):
        object_root: Root
        domain: Domain

    class Validator(ssz.Container):
        pubkey: BLSPubkey
        withdrawal_credentials: ssz.bytes32
        effective_balance: Gwei
        slashed: ssz.boolean
        activation_eligibility_epoch: Epoch
        activation_epoch: Epoch
        exit_epoch: Epoch
        withdrawable_epoch: Epoch

    class AttestationData(ssz.Container):
        slot: Slot
        index: CommitteeIndex
        beacon_block_root: Root
        source: Checkpoint
        target: Checkpoint

    class IndexedAttestation(ssz.Container):
        attesting_indices: ssz.List(
            ssz.uint64, spec.MAX_VALIDATORS_PER_COMMITTEE
        )
        data: AttestationData
        signature: BLSSignature

    class PendingAttestation(ssz.Container):
        aggregation_bits: ssz.Bitlist(spec.MAX_VALIDATORS_PER_COMMITTEE)
        data: AttestationData
        inclusion_delay: Slot
        proposer_index: ValidatorIndex

    class Eth1Data(ssz.Container):
        deposit_root: Root
        deposit_count: ssz.uint64
        block_hash: Hash32

    class HistoricalBatch(ssz.Container):
        block_roots: ssz.Vector(Root, spec.SLOTS_PER_HISTORICAL_ROOT)
        state_roots: ssz.Vector(Root, spec.SLOTS_PER_HISTORICAL_ROOT)

    class DepositMessage(ssz.Container):
        pubkey: BLSPubkey
        withdrawal_credentials: ssz.bytes32
        amount: Gwei

    class DepositData(ssz.Container):
        pubkey: BLSPubkey
        withdrawal_credentials: ssz.bytes32
        amount: Gwei
        signature: BLSSignature

    class BeaconBlockHeader(ssz.Container):
        slot: Slot
        proposer_index: ValidatorIndex
        parent_root: Root
        state_root: Root
        body_root: Root

    class SignedBeaconBlockHeader(ssz.Container):
        message: BeaconBlockHeader
        signature: BLSSignature

    class ProposerSlashing(ssz.Container):
        signed_header_1: SignedBeaconBlockHeader
        signed_header_2: SignedBeaconBlockHeader

    class AttesterSlashing(ssz.Container):
        attestation_1: IndexedAttestation
        attestation_2: IndexedAttestation

    class Attestation(ssz.Container):
        aggregation_bits: ssz.Bitlist(spec.MAX_VALIDATORS_PER_COMMITTEE)
        data: AttestationData
        signature: BLSSignature

    class Deposit(ssz.Container):
        proof: ssz.Vector(ssz.bytes32, DEPOSIT_CONTRACT_TREE_DEPTH + 1)
        data: DepositData

    class VoluntaryExit(ssz.Container):
        epoch: Epoch
        validator_index: ValidatorIndex

    class SignedVoluntaryExit(ssz.Container):
        message: VoluntaryExit
        signature: BLSSignature

    class SyncCommittee(ssz.Container):
        pubkeys: ssz.Vector(BLSPubkey, spec.SYNC_COMMITTEE_SIZE)
        aggregate_pubkey: BLSPubkey

    class SyncAggregate(ssz.Container):
        sync_committee_bits: ssz.Bitvector(spec.SYNC_COMMITTEE_SIZE)
        sync_committee_signature: BLSSignature

    # ------------------------------------------------- bellatrix payloads

    Transaction = ssz.ByteList(spec.MAX_BYTES_PER_TRANSACTION)

    class ExecutionPayload(ssz.Container):
        parent_hash: Hash32
        fee_recipient: ssz.bytes20
        state_root: ssz.bytes32
        receipts_root: ssz.bytes32
        logs_bloom: ssz.ByteVector(spec.BYTES_PER_LOGS_BLOOM)
        prev_randao: ssz.bytes32
        block_number: ssz.uint64
        gas_limit: ssz.uint64
        gas_used: ssz.uint64
        timestamp: ssz.uint64
        extra_data: ssz.ByteList(spec.MAX_EXTRA_DATA_BYTES)
        base_fee_per_gas: ssz.uint256
        block_hash: Hash32
        transactions: ssz.List(
            Transaction, spec.MAX_TRANSACTIONS_PER_PAYLOAD
        )

    class ExecutionPayloadHeader(ssz.Container):
        parent_hash: Hash32
        fee_recipient: ssz.bytes20
        state_root: ssz.bytes32
        receipts_root: ssz.bytes32
        logs_bloom: ssz.ByteVector(spec.BYTES_PER_LOGS_BLOOM)
        prev_randao: ssz.bytes32
        block_number: ssz.uint64
        gas_limit: ssz.uint64
        gas_used: ssz.uint64
        timestamp: ssz.uint64
        extra_data: ssz.ByteList(spec.MAX_EXTRA_DATA_BYTES)
        base_fee_per_gas: ssz.uint256
        block_hash: Hash32
        transactions_root: Root

    # -------------------------------------------------------------- bodies

    class BeaconBlockBodyPhase0(ssz.Container):
        randao_reveal: BLSSignature
        eth1_data: Eth1Data
        graffiti: ssz.bytes32
        proposer_slashings: ssz.List(
            ProposerSlashing, spec.MAX_PROPOSER_SLASHINGS
        )
        attester_slashings: ssz.List(
            AttesterSlashing, spec.MAX_ATTESTER_SLASHINGS
        )
        attestations: ssz.List(Attestation, spec.MAX_ATTESTATIONS)
        deposits: ssz.List(Deposit, spec.MAX_DEPOSITS)
        voluntary_exits: ssz.List(
            SignedVoluntaryExit, spec.MAX_VOLUNTARY_EXITS
        )

    class BeaconBlockBodyAltair(ssz.Container):
        randao_reveal: BLSSignature
        eth1_data: Eth1Data
        graffiti: ssz.bytes32
        proposer_slashings: ssz.List(
            ProposerSlashing, spec.MAX_PROPOSER_SLASHINGS
        )
        attester_slashings: ssz.List(
            AttesterSlashing, spec.MAX_ATTESTER_SLASHINGS
        )
        attestations: ssz.List(Attestation, spec.MAX_ATTESTATIONS)
        deposits: ssz.List(Deposit, spec.MAX_DEPOSITS)
        voluntary_exits: ssz.List(
            SignedVoluntaryExit, spec.MAX_VOLUNTARY_EXITS
        )
        sync_aggregate: SyncAggregate

    class BeaconBlockBodyBellatrix(ssz.Container):
        randao_reveal: BLSSignature
        eth1_data: Eth1Data
        graffiti: ssz.bytes32
        proposer_slashings: ssz.List(
            ProposerSlashing, spec.MAX_PROPOSER_SLASHINGS
        )
        attester_slashings: ssz.List(
            AttesterSlashing, spec.MAX_ATTESTER_SLASHINGS
        )
        attestations: ssz.List(Attestation, spec.MAX_ATTESTATIONS)
        deposits: ssz.List(Deposit, spec.MAX_DEPOSITS)
        voluntary_exits: ssz.List(
            SignedVoluntaryExit, spec.MAX_VOLUNTARY_EXITS
        )
        sync_aggregate: SyncAggregate
        execution_payload: ExecutionPayload
        blob_kzg_commitments: ssz.List(
            KZGCommitment, spec.MAX_BLOB_COMMITMENTS_PER_BLOCK
        )

    class BlindedBeaconBlockBodyBellatrix(ssz.Container):
        """Bellatrix body with the payload replaced by its header — the
        builder flow's block shape (reference BlindedPayload,
        consensus/types/src/payload.rs + builder_client/src/lib.rs)."""

        randao_reveal: BLSSignature
        eth1_data: Eth1Data
        graffiti: ssz.bytes32
        proposer_slashings: ssz.List(
            ProposerSlashing, spec.MAX_PROPOSER_SLASHINGS
        )
        attester_slashings: ssz.List(
            AttesterSlashing, spec.MAX_ATTESTER_SLASHINGS
        )
        attestations: ssz.List(Attestation, spec.MAX_ATTESTATIONS)
        deposits: ssz.List(Deposit, spec.MAX_DEPOSITS)
        voluntary_exits: ssz.List(
            SignedVoluntaryExit, spec.MAX_VOLUNTARY_EXITS
        )
        sync_aggregate: SyncAggregate
        execution_payload_header: ExecutionPayloadHeader
        blob_kzg_commitments: ssz.List(
            KZGCommitment, spec.MAX_BLOB_COMMITMENTS_PER_BLOCK
        )

    # ------------------------------------------------------- builder types

    class BuilderBid(ssz.Container):
        """eth2::types::builder_bid::BuilderBid."""

        header: ExecutionPayloadHeader
        value: ssz.uint256
        pubkey: BLSPubkey

    class SignedBuilderBid(ssz.Container):
        message: BuilderBid
        signature: BLSSignature

    class ValidatorRegistrationData(ssz.Container):
        """SignedValidatorRegistrationData message
        (consensus/types/src/validator_registration_data.rs)."""

        fee_recipient: ssz.bytes20
        gas_limit: ssz.uint64
        timestamp: ssz.uint64
        pubkey: BLSPubkey

    class SignedValidatorRegistrationData(ssz.Container):
        message: ValidatorRegistrationData
        signature: BLSSignature

    def _make_block(body_cls, name):
        cls = type(
            name,
            (ssz.Container,),
            {
                "__annotations__": {
                    "slot": Slot,
                    "proposer_index": ValidatorIndex,
                    "parent_root": Root,
                    "state_root": Root,
                    "body": body_cls,
                }
            },
        )
        return cls

    BeaconBlockPhase0 = _make_block(BeaconBlockBodyPhase0, "BeaconBlockPhase0")
    BeaconBlockAltair = _make_block(BeaconBlockBodyAltair, "BeaconBlockAltair")
    BeaconBlockBellatrix = _make_block(
        BeaconBlockBodyBellatrix, "BeaconBlockBellatrix"
    )

    def _make_signed(block_cls, name):
        return type(
            name,
            (ssz.Container,),
            {
                "__annotations__": {
                    "message": block_cls,
                    "signature": BLSSignature,
                }
            },
        )

    SignedBeaconBlockPhase0 = _make_signed(
        BeaconBlockPhase0, "SignedBeaconBlockPhase0"
    )
    SignedBeaconBlockAltair = _make_signed(
        BeaconBlockAltair, "SignedBeaconBlockAltair"
    )
    SignedBeaconBlockBellatrix = _make_signed(
        BeaconBlockBellatrix, "SignedBeaconBlockBellatrix"
    )
    BlindedBeaconBlockBellatrix = _make_block(
        BlindedBeaconBlockBodyBellatrix, "BlindedBeaconBlockBellatrix"
    )
    SignedBlindedBeaconBlockBellatrix = _make_signed(
        BlindedBeaconBlockBellatrix, "SignedBlindedBeaconBlockBellatrix"
    )

    # --------------------------------------------------------------- state

    _state_prefix = {
        "genesis_time": ssz.uint64,
        "genesis_validators_root": Root,
        "slot": Slot,
        "fork": Fork,
        "latest_block_header": BeaconBlockHeader,
        "block_roots": ssz.Vector(Root, spec.SLOTS_PER_HISTORICAL_ROOT),
        "state_roots": ssz.Vector(Root, spec.SLOTS_PER_HISTORICAL_ROOT),
        "historical_roots": ssz.List(Root, spec.HISTORICAL_ROOTS_LIMIT),
        "eth1_data": Eth1Data,
        "eth1_data_votes": ssz.List(
            Eth1Data,
            spec.EPOCHS_PER_ETH1_VOTING_PERIOD * spec.SLOTS_PER_EPOCH,
        ),
        "eth1_deposit_index": ssz.uint64,
        "validators": ssz.List(Validator, spec.VALIDATOR_REGISTRY_LIMIT),
        "balances": ssz.List(Gwei, spec.VALIDATOR_REGISTRY_LIMIT),
        "randao_mixes": ssz.Vector(
            ssz.bytes32, spec.EPOCHS_PER_HISTORICAL_VECTOR
        ),
        "slashings": ssz.Vector(Gwei, spec.EPOCHS_PER_SLASHINGS_VECTOR),
    }
    _state_suffix = {
        "justification_bits": ssz.Bitvector(JUSTIFICATION_BITS_LENGTH),
        "previous_justified_checkpoint": Checkpoint,
        "current_justified_checkpoint": Checkpoint,
        "finalized_checkpoint": Checkpoint,
    }

    BeaconStatePhase0 = type(
        "BeaconStatePhase0",
        (ssz.Container,),
        {
            "__annotations__": {
                **_state_prefix,
                "previous_epoch_attestations": ssz.List(
                    PendingAttestation,
                    spec.MAX_ATTESTATIONS * spec.SLOTS_PER_EPOCH,
                ),
                "current_epoch_attestations": ssz.List(
                    PendingAttestation,
                    spec.MAX_ATTESTATIONS * spec.SLOTS_PER_EPOCH,
                ),
                **_state_suffix,
            }
        },
    )

    _altair_fields = {
        **_state_prefix,
        "previous_epoch_participation": ssz.List(
            ParticipationFlags, spec.VALIDATOR_REGISTRY_LIMIT
        ),
        "current_epoch_participation": ssz.List(
            ParticipationFlags, spec.VALIDATOR_REGISTRY_LIMIT
        ),
        **_state_suffix,
        "inactivity_scores": ssz.List(
            ssz.uint64, spec.VALIDATOR_REGISTRY_LIMIT
        ),
        "current_sync_committee": SyncCommittee,
        "next_sync_committee": SyncCommittee,
    }

    BeaconStateAltair = type(
        "BeaconStateAltair",
        (ssz.Container,),
        {"__annotations__": dict(_altair_fields)},
    )

    BeaconStateBellatrix = type(
        "BeaconStateBellatrix",
        (ssz.Container,),
        {
            "__annotations__": {
                **_altair_fields,
                "latest_execution_payload_header": ExecutionPayloadHeader,
            }
        },
    )

    # ------------------------------------------------- light-client types

    # Generalized indices computed from the SAME state descriptors the
    # codec merkleizes with (ssz/gindex), so branch depths can never
    # drift from hash_tree_root. On this Altair shape the classic spec
    # constants fall out: finalized root 105 (depth 6), current/next
    # sync committee 54/55 (depth 5) — and the Bellatrix state (25
    # fields, same 32-chunk pad) shares them, asserted below.
    from lighthouse_tpu.ssz.gindex import floorlog2, gindex_for_path

    FINALIZED_ROOT_GINDEX = gindex_for_path(
        BeaconStateAltair, ("finalized_checkpoint", "root")
    )
    CURRENT_SYNC_COMMITTEE_GINDEX = gindex_for_path(
        BeaconStateAltair, ("current_sync_committee",)
    )
    NEXT_SYNC_COMMITTEE_GINDEX = gindex_for_path(
        BeaconStateAltair, ("next_sync_committee",)
    )
    assert FINALIZED_ROOT_GINDEX == gindex_for_path(
        BeaconStateBellatrix, ("finalized_checkpoint", "root")
    ), "fork state shapes disagree on the finalized-root gindex"

    class LightClientHeader(ssz.Container):
        """Altair light-client header (capella adds execution fields —
        the wrapper shape is kept so that extension is additive)."""

        beacon: BeaconBlockHeader

    class LightClientBootstrap(ssz.Container):
        header: LightClientHeader
        current_sync_committee: SyncCommittee
        current_sync_committee_branch: ssz.Vector(
            ssz.bytes32, floorlog2(CURRENT_SYNC_COMMITTEE_GINDEX)
        )

    class LightClientUpdate(ssz.Container):
        attested_header: LightClientHeader
        next_sync_committee: SyncCommittee
        next_sync_committee_branch: ssz.Vector(
            ssz.bytes32, floorlog2(NEXT_SYNC_COMMITTEE_GINDEX)
        )
        finalized_header: LightClientHeader
        finality_branch: ssz.Vector(
            ssz.bytes32, floorlog2(FINALIZED_ROOT_GINDEX)
        )
        sync_aggregate: SyncAggregate
        signature_slot: Slot

    class LightClientFinalityUpdate(ssz.Container):
        attested_header: LightClientHeader
        finalized_header: LightClientHeader
        finality_branch: ssz.Vector(
            ssz.bytes32, floorlog2(FINALIZED_ROOT_GINDEX)
        )
        sync_aggregate: SyncAggregate
        signature_slot: Slot

    class LightClientOptimisticUpdate(ssz.Container):
        attested_header: LightClientHeader
        sync_aggregate: SyncAggregate
        signature_slot: Slot

    # ------------------------------------------------- gossip/VC envelopes

    class AggregateAndProof(ssz.Container):
        aggregator_index: ValidatorIndex
        aggregate: Attestation
        selection_proof: BLSSignature

    class SignedAggregateAndProof(ssz.Container):
        message: AggregateAndProof
        signature: BLSSignature

    class SyncCommitteeMessage(ssz.Container):
        slot: Slot
        beacon_block_root: Root
        validator_index: ValidatorIndex
        signature: BLSSignature

    class SyncCommitteeContribution(ssz.Container):
        slot: Slot
        beacon_block_root: Root
        subcommittee_index: ssz.uint64
        aggregation_bits: ssz.Bitvector(max(spec.SYNC_COMMITTEE_SIZE // 4, 1))
        signature: BLSSignature

    class ContributionAndProof(ssz.Container):
        aggregator_index: ValidatorIndex
        contribution: SyncCommitteeContribution
        selection_proof: BLSSignature

    class SignedContributionAndProof(ssz.Container):
        message: ContributionAndProof
        signature: BLSSignature

    class SyncAggregatorSelectionData(ssz.Container):
        """Signed by a sync-committee aggregator's selection proof
        (consensus/types/src/sync_selection_proof.rs)."""

        slot: Slot
        subcommittee_index: ssz.uint64

    class DepositEvent(ssz.Container):
        """Deposit log entry as cached by the eth1 service
        (reference beacon_node/eth1/src/deposit_cache.rs)."""

        deposit_data: DepositData
        block_number: ssz.uint64
        index: ssz.uint64

    # ------------------------------------------------ blob data availability

    Blob = ssz.ByteVector(
        spec.FIELD_ELEMENTS_PER_BLOB * spec.BYTES_PER_FIELD_ELEMENT
    )

    class BlobSidecar(ssz.Container):
        """Deneb-shaped blob sidecar (consensus/types/src/blob_sidecar.rs):
        one blob + its KZG commitment/proof, bound to a block by the
        signed header. Gossiped on `blob_sidecar_{subnet}` topics and
        gated through the DataAvailabilityChecker before the block it
        belongs to may import."""

        index: ssz.uint64
        blob: Blob
        kzg_commitment: KZGCommitment
        kzg_proof: KZGProof
        signed_block_header: SignedBeaconBlockHeader

    class BlobIdentifier(ssz.Container):
        """(block_root, index) — the by-root RPC request key for a
        sidecar (deneb p2p spec BlobIdentifier)."""

        block_root: Root
        index: ssz.uint64

    # -------------------------------------------- column data availability

    Cell = ssz.ByteVector(
        spec.FIELD_ELEMENTS_PER_CELL * spec.BYTES_PER_FIELD_ELEMENT
    )

    class DataColumnSidecar(ssz.Container):
        """PeerDAS-shaped column sidecar (consensus/types/src/
        data_column_sidecar.rs): one vertical slice of the extended blob
        matrix — cell `index` of EVERY blob the block commits to — plus
        the per-cell KZG proofs and the signed header binding it to the
        block. Gossiped on `data_column_sidecar_{subnet}` topics; any
        50% of a block's columns reconstruct the full matrix
        (da.erasure)."""

        index: ssz.uint64
        column: ssz.List(Cell, spec.MAX_BLOB_COMMITMENTS_PER_BLOCK)
        kzg_commitments: ssz.List(
            KZGCommitment, spec.MAX_BLOB_COMMITMENTS_PER_BLOCK
        )
        kzg_proofs: ssz.List(
            KZGProof, spec.MAX_BLOB_COMMITMENTS_PER_BLOCK
        )
        signed_block_header: SignedBeaconBlockHeader

    class DataColumnIdentifier(ssz.Container):
        """(block_root, index) — the by-root request key for a column
        sidecar (PeerDAS p2p DataColumnIdentifier)."""

        block_root: Root
        index: ssz.uint64

    ns = SimpleNamespace(**{
        k: v
        for k, v in locals().items()
        if isinstance(v, type) and issubclass(v, ssz.Container)
    })
    ns.spec = spec
    ns.Blob = Blob
    ns.Cell = Cell
    # light-client generalized-index constants (state-shape-derived)
    ns.FINALIZED_ROOT_GINDEX = FINALIZED_ROOT_GINDEX
    ns.CURRENT_SYNC_COMMITTEE_GINDEX = CURRENT_SYNC_COMMITTEE_GINDEX
    ns.NEXT_SYNC_COMMITTEE_GINDEX = NEXT_SYNC_COMMITTEE_GINDEX

    # fork dispatch tables
    ns.block_body_classes = {
        "phase0": BeaconBlockBodyPhase0,
        "altair": BeaconBlockBodyAltair,
        "bellatrix": BeaconBlockBodyBellatrix,
    }
    ns.block_classes = {
        "phase0": BeaconBlockPhase0,
        "altair": BeaconBlockAltair,
        "bellatrix": BeaconBlockBellatrix,
    }
    ns.signed_block_classes = {
        "phase0": SignedBeaconBlockPhase0,
        "altair": SignedBeaconBlockAltair,
        "bellatrix": SignedBeaconBlockBellatrix,
    }
    ns.state_classes = {
        "phase0": BeaconStatePhase0,
        "altair": BeaconStateAltair,
        "bellatrix": BeaconStateBellatrix,
    }
    # builder/blinded flow (bellatrix onward)
    ns.blinded_body_classes = {
        "bellatrix": BlindedBeaconBlockBodyBellatrix,
    }
    ns.blinded_block_classes = {
        "bellatrix": BlindedBeaconBlockBellatrix,
    }
    ns.signed_blinded_block_classes = {
        "bellatrix": SignedBlindedBeaconBlockBellatrix,
    }

    _CACHE[spec.name] = ns
    return ns
