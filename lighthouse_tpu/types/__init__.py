from lighthouse_tpu.types.spec import (  # noqa: F401
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
    GENESIS_SLOT,
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
    Spec,
    mainnet_spec,
    minimal_spec,
)
from lighthouse_tpu.types.containers import types_for  # noqa: F401
from lighthouse_tpu.types.helpers import (  # noqa: F401
    compute_domain,
    compute_fork_data_root,
    compute_fork_digest,
    compute_signing_root,
)
