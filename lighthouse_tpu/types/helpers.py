"""Signing-domain helpers (spec `compute_domain` / `compute_signing_root`).

Role of the reference's consensus/types signing machinery (`SignedRoot`
trait, `ChainSpec::get_domain`, chain_spec.rs:596 area): every signature in
the system signs `hash_tree_root(SigningData(object_root, domain))` where
the domain binds the 4-byte domain type, fork version, and genesis
validators root.
"""

from lighthouse_tpu.ssz.hashing import hash_concat


def compute_fork_data_root(
    current_version: bytes, genesis_validators_root: bytes
) -> bytes:
    # ForkData container root: two 32-byte leaves
    leaf0 = current_version.ljust(32, b"\x00")
    return hash_concat(leaf0, genesis_validators_root)


def compute_fork_digest(
    current_version: bytes, genesis_validators_root: bytes
) -> bytes:
    return compute_fork_data_root(current_version, genesis_validators_root)[:4]


def compute_domain(
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes,
) -> bytes:
    fork_data_root = compute_fork_data_root(
        fork_version, genesis_validators_root
    )
    return domain_type + fork_data_root[:28]


def compute_signing_root(object_root: bytes, domain: bytes) -> bytes:
    """hash_tree_root(SigningData): container of two bytes32 leaves."""
    return hash_concat(object_root, domain)


def state_anchor_block_root(state) -> bytes:
    """The block root a state commits to: its latest_block_header with
    the state_root filled in (zero inside a state that is the header's
    own post-state). Shared by the chain's genesis/anchor rooting and
    the checkpoint-sync client's block lookup."""
    from lighthouse_tpu.ssz.cached_hash import cached_state_root
    from lighthouse_tpu.ssz.hashing import ZERO_BYTES32

    header = state.latest_block_header
    if bytes(header.state_root) == ZERO_BYTES32:
        header = header.copy()
        header.state_root = cached_state_root(state)
    return type(header).hash_tree_root(header)
