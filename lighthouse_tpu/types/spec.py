"""Chain specification: runtime constants + size presets.

Merges the reference's two-tier constant system — compile-time `EthSpec`
presets (consensus/types/src/eth_spec.rs:51,238,281) and runtime `ChainSpec`
values (consensus/types/src/chain_spec.rs:32,431,596) — into one `Spec`
object. In Rust the split exists to monomorphize SSZ array sizes; in Python
container classes are built per-spec by `lighthouse_tpu.types.containers`,
so a single object carries both tiers (fields are grouped and documented to
preserve the mapping).

Values are the published Ethereum consensus-spec mainnet/minimal constants
(phase0 + altair).
"""

from dataclasses import dataclass, field, fields, replace

FAR_FUTURE_EPOCH = 2**64 - 1
GENESIS_EPOCH = 0
GENESIS_SLOT = 0

# BLS signature/pubkey byte lengths
PUBKEY_BYTES = 48
SIGNATURE_BYTES = 96

# Participation flag indices / weights (altair)
TIMELY_SOURCE_FLAG_INDEX = 0
TIMELY_TARGET_FLAG_INDEX = 1
TIMELY_HEAD_FLAG_INDEX = 2
TIMELY_SOURCE_WEIGHT = 14
TIMELY_TARGET_WEIGHT = 26
TIMELY_HEAD_WEIGHT = 14
SYNC_REWARD_WEIGHT = 2
PROPOSER_WEIGHT = 8
WEIGHT_DENOMINATOR = 64
PARTICIPATION_FLAG_WEIGHTS = [
    TIMELY_SOURCE_WEIGHT,
    TIMELY_TARGET_WEIGHT,
    TIMELY_HEAD_WEIGHT,
]

DEPOSIT_CONTRACT_TREE_DEPTH = 32
JUSTIFICATION_BITS_LENGTH = 4


@dataclass(frozen=True)
class Spec:
    name: str

    # ---- preset tier (EthSpec analog: fixed container sizes) ----
    SLOTS_PER_EPOCH: int
    MAX_COMMITTEES_PER_SLOT: int
    TARGET_COMMITTEE_SIZE: int
    MAX_VALIDATORS_PER_COMMITTEE: int
    SHUFFLE_ROUND_COUNT: int
    EPOCHS_PER_ETH1_VOTING_PERIOD: int
    SLOTS_PER_HISTORICAL_ROOT: int
    EPOCHS_PER_HISTORICAL_VECTOR: int
    EPOCHS_PER_SLASHINGS_VECTOR: int
    HISTORICAL_ROOTS_LIMIT: int
    VALIDATOR_REGISTRY_LIMIT: int
    MAX_PROPOSER_SLASHINGS: int
    MAX_ATTESTER_SLASHINGS: int
    MAX_ATTESTATIONS: int
    MAX_DEPOSITS: int
    MAX_VOLUNTARY_EXITS: int
    SYNC_COMMITTEE_SIZE: int
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD: int
    MIN_SYNC_COMMITTEE_PARTICIPANTS: int

    # ---- runtime tier (ChainSpec analog) ----
    SECONDS_PER_SLOT: int
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT: int
    MIN_GENESIS_TIME: int
    GENESIS_DELAY: int
    GENESIS_FORK_VERSION: bytes
    ALTAIR_FORK_VERSION: bytes
    ALTAIR_FORK_EPOCH: int
    BELLATRIX_FORK_VERSION: bytes
    BELLATRIX_FORK_EPOCH: int

    MIN_DEPOSIT_AMOUNT: int
    MAX_EFFECTIVE_BALANCE: int
    EFFECTIVE_BALANCE_INCREMENT: int
    EJECTION_BALANCE: int

    MIN_ATTESTATION_INCLUSION_DELAY: int
    MIN_SEED_LOOKAHEAD: int
    MAX_SEED_LOOKAHEAD: int
    MIN_EPOCHS_TO_INACTIVITY_PENALTY: int
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY: int
    SHARD_COMMITTEE_PERIOD: int
    ETH1_FOLLOW_DISTANCE: int
    SECONDS_PER_ETH1_BLOCK: int

    MIN_PER_EPOCH_CHURN_LIMIT: int
    CHURN_LIMIT_QUOTIENT: int

    BASE_REWARD_FACTOR: int
    WHISTLEBLOWER_REWARD_QUOTIENT: int
    PROPOSER_REWARD_QUOTIENT: int
    HYSTERESIS_QUOTIENT: int
    HYSTERESIS_DOWNWARD_MULTIPLIER: int
    HYSTERESIS_UPWARD_MULTIPLIER: int

    # slashing penalties (phase0 / altair variants)
    INACTIVITY_PENALTY_QUOTIENT: int
    MIN_SLASHING_PENALTY_QUOTIENT: int
    PROPORTIONAL_SLASHING_MULTIPLIER: int
    INACTIVITY_PENALTY_QUOTIENT_ALTAIR: int
    MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR: int
    PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR: int
    INACTIVITY_SCORE_BIAS: int
    INACTIVITY_SCORE_RECOVERY_RATE: int

    PROPOSER_SCORE_BOOST: int
    TARGET_AGGREGATORS_PER_COMMITTEE: int

    # sync-committee gossip plane (altair p2p spec; reference
    # consensus/types/src/consts.rs SYNC_COMMITTEE_SUBNET_COUNT and
    # sync_selection_proof.rs TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE)
    SYNC_COMMITTEE_SUBNET_COUNT: int = 4
    TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE: int = 16

    # attestation gossip plane (phase0 p2p spec ATTESTATION_SUBNET_COUNT;
    # reference consensus/types/src/subnet_id.rs — committees shard onto
    # 64 `beacon_attestation_{id}` topics)
    ATTESTATION_SUBNET_COUNT: int = 64

    # blob data-availability plane (EIP-4844 / deneb-shaped, served by
    # the in-repo KZG subsystem — lighthouse_tpu.kzg). Blob size must be
    # a power of two; the dev trusted setup is built lazily per size.
    FIELD_ELEMENTS_PER_BLOB: int = 4096
    BYTES_PER_FIELD_ELEMENT: int = 32
    MAX_BLOBS_PER_BLOCK: int = 6
    MAX_BLOB_COMMITMENTS_PER_BLOCK: int = 4096
    BLOB_SIDECAR_SUBNET_COUNT: int = 6
    # retention window: sidecars older than this many epochs behind the
    # finalized slot are pruned from the store (deneb
    # MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS)
    MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS: int = 4096

    # column data-availability sampling plane (PeerDAS-shaped, served
    # by lighthouse_tpu.da): each blob polynomial is Reed-Solomon
    # extended 2x and split into cells of FIELD_ELEMENTS_PER_CELL
    # evaluations; column k = cell k of every blob in the block. Cell
    # size must divide the extended domain (2 * FIELD_ELEMENTS_PER_BLOB)
    # and the subnet count must divide NUMBER_OF_COLUMNS.
    FIELD_ELEMENTS_PER_CELL: int = 64
    DATA_COLUMN_SIDECAR_SUBNET_COUNT: int = 128
    CUSTODY_REQUIREMENT: int = 4
    SAMPLES_PER_SLOT: int = 8

    # bellatrix (merge) — execution payload sizes + penalty variants
    # (consensus/types/src/eth_spec.rs MaxBytesPerTransaction etc.,
    # chain_spec.rs *_bellatrix fields)
    MAX_BYTES_PER_TRANSACTION: int = 2**30
    MAX_TRANSACTIONS_PER_PAYLOAD: int = 2**20
    BYTES_PER_LOGS_BLOOM: int = 256
    MAX_EXTRA_DATA_BYTES: int = 32
    INACTIVITY_PENALTY_QUOTIENT_BELLATRIX: int = 2**24
    MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX: int = 32
    PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX: int = 3
    TERMINAL_TOTAL_DIFFICULTY: int = 2**256 - 2**10
    TERMINAL_BLOCK_HASH: bytes = b"\x00" * 32
    TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH: int = FAR_FUTURE_EPOCH

    # domains (4-byte little-endian type tags)
    DOMAIN_BEACON_PROPOSER: bytes = b"\x00\x00\x00\x00"
    DOMAIN_BEACON_ATTESTER: bytes = b"\x01\x00\x00\x00"
    DOMAIN_RANDAO: bytes = b"\x02\x00\x00\x00"
    DOMAIN_DEPOSIT: bytes = b"\x03\x00\x00\x00"
    DOMAIN_VOLUNTARY_EXIT: bytes = b"\x04\x00\x00\x00"
    DOMAIN_SELECTION_PROOF: bytes = b"\x05\x00\x00\x00"
    DOMAIN_AGGREGATE_AND_PROOF: bytes = b"\x06\x00\x00\x00"
    DOMAIN_SYNC_COMMITTEE: bytes = b"\x07\x00\x00\x00"
    DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF: bytes = b"\x08\x00\x00\x00"
    DOMAIN_CONTRIBUTION_AND_PROOF: bytes = b"\x09\x00\x00\x00"
    # builder specs (not an in-protocol domain): signs BuilderBid and
    # ValidatorRegistrationData against the GENESIS fork version with a
    # zero genesis_validators_root (compute_builder_domain in the
    # reference, consensus/types/src/chain_spec.rs)
    DOMAIN_APPLICATION_BUILDER: bytes = b"\x00\x00\x00\x01"

    # ---- derived helpers ----

    @property
    def NUMBER_OF_COLUMNS(self) -> int:
        """Cells per extended blob — derived, so presets cannot drift:
        the 2x-extended domain split into FIELD_ELEMENTS_PER_CELL
        chunks (mainnet: 2*4096/64 = 128)."""
        return (
            2 * self.FIELD_ELEMENTS_PER_BLOB // self.FIELD_ELEMENTS_PER_CELL
        )

    def slot_to_epoch(self, slot: int) -> int:
        return slot // self.SLOTS_PER_EPOCH

    def epoch_start_slot(self, epoch: int) -> int:
        return epoch * self.SLOTS_PER_EPOCH

    def fork_name_at_epoch(self, epoch: int) -> str:
        if epoch >= self.BELLATRIX_FORK_EPOCH:
            return "bellatrix"
        if epoch >= self.ALTAIR_FORK_EPOCH:
            return "altair"
        return "phase0"

    def fork_version_at_epoch(self, epoch: int) -> bytes:
        return {
            "phase0": self.GENESIS_FORK_VERSION,
            "altair": self.ALTAIR_FORK_VERSION,
            "bellatrix": self.BELLATRIX_FORK_VERSION,
        }[self.fork_name_at_epoch(epoch)]

    # fork-keyed penalty parameters (chain_spec.rs *_altair/*_bellatrix)

    def inactivity_penalty_quotient_for(self, fork: str) -> int:
        return {
            "phase0": self.INACTIVITY_PENALTY_QUOTIENT,
            "altair": self.INACTIVITY_PENALTY_QUOTIENT_ALTAIR,
            "bellatrix": self.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX,
        }[fork]

    def min_slashing_penalty_quotient_for(self, fork: str) -> int:
        return {
            "phase0": self.MIN_SLASHING_PENALTY_QUOTIENT,
            "altair": self.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR,
            "bellatrix": self.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX,
        }[fork]

    def proportional_slashing_multiplier_for(self, fork: str) -> int:
        return {
            "phase0": self.PROPORTIONAL_SLASHING_MULTIPLIER,
            "altair": self.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR,
            "bellatrix": self.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX,
        }[fork]


def mainnet_spec(**overrides) -> Spec:
    """Mainnet preset + config (chain_spec.rs:431 `ChainSpec::mainnet` and
    eth_spec.rs:238 `MainnetEthSpec` in the reference)."""
    base = Spec(
        name="mainnet",
        SLOTS_PER_EPOCH=32,
        MAX_COMMITTEES_PER_SLOT=64,
        TARGET_COMMITTEE_SIZE=128,
        MAX_VALIDATORS_PER_COMMITTEE=2048,
        SHUFFLE_ROUND_COUNT=90,
        EPOCHS_PER_ETH1_VOTING_PERIOD=64,
        SLOTS_PER_HISTORICAL_ROOT=8192,
        EPOCHS_PER_HISTORICAL_VECTOR=65536,
        EPOCHS_PER_SLASHINGS_VECTOR=8192,
        HISTORICAL_ROOTS_LIMIT=2**24,
        VALIDATOR_REGISTRY_LIMIT=2**40,
        MAX_PROPOSER_SLASHINGS=16,
        MAX_ATTESTER_SLASHINGS=2,
        MAX_ATTESTATIONS=128,
        MAX_DEPOSITS=16,
        MAX_VOLUNTARY_EXITS=16,
        SYNC_COMMITTEE_SIZE=512,
        EPOCHS_PER_SYNC_COMMITTEE_PERIOD=256,
        MIN_SYNC_COMMITTEE_PARTICIPANTS=1,
        SECONDS_PER_SLOT=12,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=16384,
        MIN_GENESIS_TIME=1606824000,
        GENESIS_DELAY=604800,
        GENESIS_FORK_VERSION=bytes.fromhex("00000000"),
        ALTAIR_FORK_VERSION=bytes.fromhex("01000000"),
        ALTAIR_FORK_EPOCH=74240,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02000000"),
        BELLATRIX_FORK_EPOCH=FAR_FUTURE_EPOCH,
        MIN_DEPOSIT_AMOUNT=10**9,
        MAX_EFFECTIVE_BALANCE=32 * 10**9,
        EFFECTIVE_BALANCE_INCREMENT=10**9,
        EJECTION_BALANCE=16 * 10**9,
        MIN_ATTESTATION_INCLUSION_DELAY=1,
        MIN_SEED_LOOKAHEAD=1,
        MAX_SEED_LOOKAHEAD=4,
        MIN_EPOCHS_TO_INACTIVITY_PENALTY=4,
        MIN_VALIDATOR_WITHDRAWABILITY_DELAY=256,
        SHARD_COMMITTEE_PERIOD=256,
        ETH1_FOLLOW_DISTANCE=2048,
        SECONDS_PER_ETH1_BLOCK=14,
        MIN_PER_EPOCH_CHURN_LIMIT=4,
        CHURN_LIMIT_QUOTIENT=65536,
        BASE_REWARD_FACTOR=64,
        WHISTLEBLOWER_REWARD_QUOTIENT=512,
        PROPOSER_REWARD_QUOTIENT=8,
        HYSTERESIS_QUOTIENT=4,
        HYSTERESIS_DOWNWARD_MULTIPLIER=1,
        HYSTERESIS_UPWARD_MULTIPLIER=5,
        INACTIVITY_PENALTY_QUOTIENT=2**26,
        MIN_SLASHING_PENALTY_QUOTIENT=128,
        PROPORTIONAL_SLASHING_MULTIPLIER=1,
        INACTIVITY_PENALTY_QUOTIENT_ALTAIR=3 * 2**24,
        MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR=64,
        PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR=2,
        INACTIVITY_SCORE_BIAS=4,
        INACTIVITY_SCORE_RECOVERY_RATE=16,
        PROPOSER_SCORE_BOOST=40,
        TARGET_AGGREGATORS_PER_COMMITTEE=16,
    )
    return replace(base, **overrides) if overrides else base


def minimal_spec(**overrides) -> Spec:
    """Minimal preset (eth_spec.rs:281 `MinimalEthSpec`): small committees
    and short vectors for fast in-process testing."""
    base = replace(
        mainnet_spec(),
        name="minimal",
        SLOTS_PER_EPOCH=8,
        MAX_COMMITTEES_PER_SLOT=4,
        TARGET_COMMITTEE_SIZE=4,
        SHUFFLE_ROUND_COUNT=10,
        EPOCHS_PER_ETH1_VOTING_PERIOD=4,
        SLOTS_PER_HISTORICAL_ROOT=64,
        EPOCHS_PER_HISTORICAL_VECTOR=64,
        EPOCHS_PER_SLASHINGS_VECTOR=64,
        HISTORICAL_ROOTS_LIMIT=2**24,
        SYNC_COMMITTEE_SIZE=32,
        EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
        ETH1_FOLLOW_DISTANCE=16,
        MIN_VALIDATOR_WITHDRAWABILITY_DELAY=256,
        SHARD_COMMITTEE_PERIOD=64,
        MIN_GENESIS_TIME=1578009600,
        GENESIS_DELAY=300,
        GENESIS_FORK_VERSION=bytes.fromhex("00000001"),
        ALTAIR_FORK_VERSION=bytes.fromhex("01000001"),
        BELLATRIX_FORK_VERSION=bytes.fromhex("02000001"),
        # tiny blobs keep the dev trusted setup and the KZG data plane
        # fast enough for in-process testing (minimal-preset role)
        FIELD_ELEMENTS_PER_BLOB=4,
        MAX_BLOBS_PER_BLOCK=4,
        MAX_BLOB_COMMITMENTS_PER_BLOCK=16,
        BLOB_SIDECAR_SUBNET_COUNT=4,
        MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS=4,
        # tiny DAS geometry: 4-element blobs extend to 8 evaluations,
        # split into 4 columns of 2-element cells over 4 subnets
        FIELD_ELEMENTS_PER_CELL=2,
        DATA_COLUMN_SIDECAR_SUBNET_COUNT=4,
        CUSTODY_REQUIREMENT=2,
        SAMPLES_PER_SLOT=2,
    )
    return replace(base, **overrides) if overrides else base


def gnosis_spec(**overrides) -> Spec:
    """Gnosis chain preset + config (eth_spec.rs:327 `GnosisEthSpec`,
    chain_spec.rs:637 `ChainSpec::gnosis`): mainnet container sizes with
    5 s slots, xDai-denominated deposits kept at the same gwei values,
    faster eth1 follow, and gnosis fork versions."""
    base = replace(
        mainnet_spec(),
        name="gnosis",
        SLOTS_PER_EPOCH=16,
        SECONDS_PER_SLOT=5,
        MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=4096,
        MIN_GENESIS_TIME=1638968400,
        GENESIS_DELAY=6000,
        GENESIS_FORK_VERSION=bytes.fromhex("00000064"),
        ALTAIR_FORK_VERSION=bytes.fromhex("01000064"),
        ALTAIR_FORK_EPOCH=256,
        BELLATRIX_FORK_VERSION=bytes.fromhex("02000064"),
        ETH1_FOLLOW_DISTANCE=1024,
        SECONDS_PER_ETH1_BLOCK=6,
        CHURN_LIMIT_QUOTIENT=4096,
        BASE_REWARD_FACTOR=25,
    )
    return replace(base, **overrides) if overrides else base


def spec_from_config_yaml(text: str, base: Spec | None = None) -> Spec:
    """Build a Spec from a consensus config.yaml (the runtime-tier override
    file every network directory carries — eth2_network_config's
    config.yaml + config_and_preset.rs). Minimal YAML subset: `KEY: value`
    lines, comments, 0x-hex and decimal scalars, named presets via
    PRESET_BASE."""
    values: dict[str, object] = {}
    preset_base = "mainnet"
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line or ":" not in line:
            continue
        key, _, val = line.partition(":")
        key, val = key.strip(), val.strip().strip("'\"")
        if key == "PRESET_BASE":
            preset_base = val
            continue
        if val.startswith("0x"):
            values[key] = bytes.fromhex(val[2:])
        elif val.isdigit():
            values[key] = int(val)
        else:
            values[key] = val
    if base is None:
        base = {
            "mainnet": mainnet_spec,
            "minimal": minimal_spec,
            "gnosis": gnosis_spec,
        }.get(preset_base, mainnet_spec)()
    known = {f.name for f in fields(Spec)}
    overrides = {k: v for k, v in values.items() if k in known}
    if "CONFIG_NAME" in values:
        overrides["name"] = str(values["CONFIG_NAME"])
    return replace(base, **overrides)


def spec_to_config_yaml(spec: Spec) -> str:
    """Serialize a Spec as a consensus config.yaml — the exact inverse of
    `spec_from_config_yaml` (every field is emitted, so the named
    PRESET_BASE only seeds defaults the override lines then pin). This is
    what `lcli new-testnet` writes into a --testnet-dir and what the
    embedded network-config assets are generated from
    (eth2_network_config's config.yaml role)."""
    preset = spec.name if spec.name in ("mainnet", "minimal", "gnosis") \
        else "mainnet"
    lines = [
        f"# {spec.name} — generated by lighthouse_tpu "
        "(spec_to_config_yaml)",
        f"PRESET_BASE: '{preset}'",
        f"CONFIG_NAME: '{spec.name}'",
    ]
    for f in fields(Spec):
        if f.name == "name":
            continue
        v = getattr(spec, f.name)
        if isinstance(v, bytes):
            lines.append(f"{f.name}: 0x{v.hex()}")
        else:
            lines.append(f"{f.name}: {v}")
    return "\n".join(lines) + "\n"
