"""Multi-chip batched BLS verification via shard_map over a ("sets","keys") mesh.

Parallel decomposition (TPU-native replacement for the reference's rayon
map-reduce over chunks of signature sets,
consensus/state_processing/.../block_signature_verifier.rs:348-376):

  "sets" axis (data parallel): each device verifies S/n_sets signature sets:
      local pubkey aggregation -> RLC scalar-mul -> Miller loops -> local
      Fp12 product. The per-shard products are all_gathered and folded —
      the collective analog of rayon's `.all()` reduction — and ONE final
      exponentiation runs (replicated) per batch.

  "keys" axis (model parallel): the padded per-set pubkey axis is split
      across devices; each computes a partial G1 sum, then an all_gather +
      point-fold over the axis reduces the partials (the MSM partial-sum
      reduction over ICI).

The RLC-combined signature (sum_i r_i sig_i) needs a global G2 sum over the
"sets" axis: computed as local partial sums + all_gather + fold, then the
single extra pair e(-G1, S) is multiplied in exactly once (replicated).
"""

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _shard_map(f, mesh, in_specs, out_specs):
    """Version-tolerant shard_map with replication checking off (our outputs
    are replicated by construction via all_gathers)."""
    try:
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except (TypeError, AttributeError):
        # TypeError: newer jax without the check_vma kwarg;
        # AttributeError: jax builds with no top-level jax.shard_map at
        # all — both fall back to the experimental entry point
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

import time

import numpy as np

from lighthouse_tpu.common import device_attribution as attribution
from lighthouse_tpu.common.compile_ledger import LEDGER
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.device_plane import GUARD
from lighthouse_tpu.ops import batch_verify, curve, pairing, tower
from lighthouse_tpu.ops import window_ladder as wl

# trace-time observability: which reduction strategy each sharded
# program was built with (fires once per trace, not per dispatch) and
# how many sharded verify programs this process has constructed
_REDUCTIONS = REGISTRY.counter_vec(
    "lighthouse_tpu_sharded_reductions_total",
    "collective reductions traced into sharded verify programs, "
    "by strategy",
    ("kind",),
)
_SHARDED_BUILDS = REGISTRY.counter_vec(
    "lighthouse_tpu_sharded_verify_builds_total",
    "sharded verify program constructions, by layout",
    ("layout",),
)


def _gather_fold_points(group, pt, axis_name):
    """all_gather Jacobian partial sums over `axis_name` and tree-fold."""
    gathered = jax.lax.all_gather(pt, axis_name)  # leading new axis
    return group.sum_axis(gathered, axis=0)


def _butterfly_reduce(val, combine, axis_name, axis_size: int):
    """All-reduce a per-device partial with a log2(n) recursive-doubling
    butterfly of ppermute exchanges + `combine` steps — the ICI-native
    reduction for values whose combine is a GROUP law, not a
    componentwise add (SURVEY §2.6 TP row: MSM partial-sum reduction
    over ICI; psum cannot express point addition or Fp12
    multiplication). Each step exchanges with the device 2^k away;
    after log2(n) steps every device holds the total, replicated —
    exactly what all_gather + local fold produces, without
    materializing n copies per device. Axis size must be a power of
    two (callers fall back to gather+fold otherwise)."""
    assert axis_size & (axis_size - 1) == 0, axis_size
    step = 1
    while step < axis_size:
        perm = [(i, i ^ step) for i in range(axis_size)]
        other = jax.tree_util.tree_map(
            lambda c: jax.lax.ppermute(c, axis_name, perm), val
        )
        val = combine(val, other)
        step *= 2
    return val


def _reduce_points_over(mesh, ring, group, pt, axis_name):
    """Point reduction over a mesh axis: butterfly when ring mode is on
    and the axis is a power of two, all_gather+fold otherwise."""
    n = mesh.shape[axis_name]
    if ring and n & (n - 1) == 0:
        _REDUCTIONS.labels("butterfly").inc()
        return _butterfly_reduce(pt, group.add, axis_name, n)
    _REDUCTIONS.labels("gather_fold").inc()
    return _gather_fold_points(group, pt, axis_name)


def _finish_multi_pairing(
    mesh, ring, local_sig, g1_pairs, g2_pairs, pair_mask,
    reduce_axis="sets",
):
    """The shared batch-closing tail of every sharded verify: reduce the
    G2 RLC signature sum over the mesh, run this shard's Miller loops,
    fold the Fp12 products across devices, multiply in the SINGLE
    signature pair (replicated), final exponentiation."""
    sig_acc = _reduce_points_over(
        mesh, ring, curve.PG2, local_sig, reduce_axis
    )
    s_x, s_y, s_inf = curve.PG2.to_affine(
        jax.tree_util.tree_map(lambda t: t[None], sig_acc)
    )

    f_local = pairing.miller_loop(g1_pairs, g2_pairs, valid_mask=pair_mask)
    prod_local = tower.fp12_product_axis(f_local, axis=0)

    n_axis = mesh.shape[reduce_axis]
    if ring and n_axis & (n_axis - 1) == 0:
        _REDUCTIONS.labels("butterfly").inc()
        prod = _butterfly_reduce(
            prod_local, tower.fp12_mul, reduce_axis, n_axis
        )
    else:
        _REDUCTIONS.labels("gather_fold").inc()
        gathered = jax.lax.all_gather(prod_local, reduce_axis)
        prod = tower.fp12_product_axis(gathered, axis=0)

    neg_g1 = (
        jnp.asarray(batch_verify.NEG_G1_AFFINE[0])[None],
        jnp.asarray(batch_verify.NEG_G1_AFFINE[1])[None],
    )
    f_sig = pairing.miller_loop(neg_g1, (s_x, s_y), valid_mask=~s_inf)
    prod = tower.fp12_mul(prod, tower.fp12_product_axis(f_sig, axis=0))
    return pairing.final_exp_is_one(prod)


def _wrap_attributed(inner, fn_name: str, layout: str, consumer):
    """Attribution wrapper over a built sharded program: each dispatch
    counts a `sharded`-plane batch with lane/waste economics read from
    the set_mask argument (index 5 in both the flat and grouped
    signatures — (..., rand_bits, set_mask[, group_mask])), and lands a
    compile-ledger entry classified cold/warm from the jit trace
    cache. The wrapper does NOT force the device value — callers keep
    the async-dispatch contract.

    Guard coverage here is NARROWER than the other device entry points
    by design: the dispatch is async (the returned value is unforced),
    so flip injection cannot be applied without forcing, and the
    sharded program's inputs are pre-encoded per-mesh field bundles
    with no host oracle at this boundary — there is no fallback tier.
    What the guard still buys: stall/error injection, breaker
    accounting, and fail-fast DeviceFaultError when the `sharded`
    plane's breaker is open, instead of a hang. The watchdog is opted
    OUT per-dispatch: the synchronous portion here is dominated by the
    mesh graphs' legitimate multi-minute cold compiles (the repo's
    largest), and the device result is an unforced async value — a
    timeout would abandon healthy compiles while measuring a wall that
    cannot wedge."""
    def dispatch(*args):
        set_mask = np.asarray(args[5])
        t0 = time.perf_counter()
        out = GUARD.dispatch(
            "sharded",
            f"lanes{set_mask.size}",
            lambda plan: inner(*args),
            watchdog=False,
        )
        dt = time.perf_counter() - t0
        LEDGER.note_dispatch(
            fn_name, inner, (layout,), f"lanes{set_mask.size}", dt
        )
        attribution.note_batch(
            consumer,
            "sharded",
            lanes=set_mask.size,
            live=int(set_mask.sum()),
            duration_s=dt,
        )
        return out

    dispatch._inner = inner
    return dispatch


def sharded_verify_signature_sets(
    mesh, ring: bool = False, consumer: str | None = None
):
    """Build the jitted multi-chip verify step for a given mesh.

    Returns fn(msgs, sigs, pubkeys, key_mask, rand_bits, set_mask) -> bool.
    Global shapes: S divisible by mesh 'sets' size, K by 'keys' size.

    ring=True replaces every all_gather+fold reduction with the
    recursive-doubling ppermute butterfly (_butterfly_reduce) — point
    sums over "keys"/"sets" and the Fp12 product over "sets" — when the
    axis is a power of two (gather+fold otherwise).

    `consumer` labels every dispatch through the returned program on
    the `sharded` device plane (device_attribution).
    """
    bundle = P("sets", None, None)        # (S, slots, NB)
    pk_leaf = P("sets", "keys", None, None)  # (S, K, 1, NB)

    in_specs = (
        (bundle, bundle),              # msgs (x, y) Fp2 bundles
        (bundle, bundle),              # sigs
        (pk_leaf, pk_leaf),            # pubkeys (x, y) Fp bundles
        P("sets", "keys"),             # key_mask
        P("sets", None),               # rand_bits (S, 64)
        P("sets"),                     # set_mask
    )
    out_specs = P()

    def step(msgs, sigs, pubkeys, key_mask, rand_bits, set_mask):
        # ---- keys-axis: partial pubkey aggregation + reduction
        partial_pk = batch_verify.aggregate_pubkeys(pubkeys, key_mask)
        agg_pk = _reduce_points_over(
            mesh, ring, curve.PG1, partial_pk, "keys"
        )

        # ---- per-set RLC scale + affinize (the shared window kernel)
        agg_pk_r = wl.ladder(curve.PG1, agg_pk, rand_bits)
        pk_x, pk_y, pk_inf = curve.PG1.to_affine(agg_pk_r)

        # ---- sets-axis: global RLC-combined signature partial
        local_sig = batch_verify.rlc_combined_signature(
            sigs, rand_bits, set_mask
        )
        # Fp12 fold over "sets" only: every keys-row computed the same
        # sets product, so the values are already identical along "keys"
        return _finish_multi_pairing(
            mesh, ring, local_sig,
            (pk_x, pk_y), msgs, set_mask & ~pk_inf,
        )

    _SHARDED_BUILDS.labels("flat").inc()
    return _wrap_attributed(
        jax.jit(_shard_map(step, mesh, in_specs, out_specs)),
        "sharded_verify", "flat", consumer,
    )


def sharded_verify_signature_sets_grouped(
    mesh, ring: bool = False, consumer: str | None = None
):
    """Multi-chip MESSAGE-GROUPED verify: shard the GROUP axis over the
    mesh's "sets" dimension — each device owns G/n whole groups
    (their per-set ladders, the group MSM fold, and their Miller
    loops are message-local, so no cross-device traffic until the
    final reductions). Two collectives close the batch: the global
    RLC signature sum (G2 point reduction) and the Fp12 pair-product
    fold; ONE final exponentiation runs replicated.

    Returns fn(group_msgs, sigs, pubkeys, key_mask, rand_bits,
    set_mask, group_mask) -> bool with the (G, Sg[, K]) grid shapes of
    ops.batch_verify.verify_signature_sets_grouped; the mesh's "sets"
    axis size must divide G (each device takes G/n groups)."""
    g_leaf = P("sets", None, None)              # (G, 2/1, NB) bundles
    grid2 = P("sets", None, None, None)         # (G, Sg, 2, NB)
    pk_leaf = P("sets", None, None, None, None)  # (G, Sg, K, 1, NB)

    in_specs = (
        (g_leaf, g_leaf),               # group msgs (x, y)
        (grid2, grid2),                 # sigs
        (pk_leaf, pk_leaf),             # pubkeys
        P("sets", None, None),          # key_mask (G, Sg, K)
        P("sets", None, None),          # rand_bits (G, Sg, 64)
        P("sets", None),                # set_mask (G, Sg)
        P("sets"),                      # group_mask (G,)
    )
    out_specs = P()

    def step(
        group_msgs, sigs, pubkeys, key_mask, rand_bits, set_mask,
        group_mask,
    ):
        # ---- message-local: per-set aggregate + RLC + group fold
        agg = curve.PG1.sum_axis(
            curve.PG1.from_affine(pubkeys, key_mask), axis=2
        )
        agg_r = wl.ladder(curve.PG1, agg, rand_bits)
        grp_pk = curve.PG1.sum_axis(agg_r, axis=1)  # local (G/n,)
        pk_x, pk_y, pk_inf = curve.PG1.to_affine(grp_pk)

        # ---- global RLC signature sum partial (both grid axes local)
        sig_r = wl.ladder(
            curve.PG2,
            curve.PG2.from_affine(sigs, set_mask), rand_bits
        )
        local_sig = curve.PG2.sum_axis(
            curve.PG2.sum_axis(sig_r, axis=1), axis=0
        )
        return _finish_multi_pairing(
            mesh, ring, local_sig,
            (pk_x, pk_y), group_msgs, group_mask & ~pk_inf,
        )

    _SHARDED_BUILDS.labels("grouped").inc()
    return _wrap_attributed(
        jax.jit(_shard_map(step, mesh, in_specs, out_specs)),
        "sharded_verify_grouped", "grouped", consumer,
    )
