"""Device-mesh construction for the crypto data plane.

The reference client's only data parallelism is rayon chunking over CPU
cores (consensus/state_processing/.../block_signature_verifier.rs:367-375).
The TPU-native equivalent is a 2-D `jax.sharding.Mesh`:

  axis "sets": data-parallel over signature sets (the rayon-chunk analog;
               collectives: all_gather of per-shard Fp12 Miller products).
  axis "keys": model-parallel over the padded per-set pubkey axis (the MSM
               partial-sum reduction; collectives: all_gather + point-fold
               over ICI).

Multi-host later rides the same mesh (DCN for "sets", ICI for "keys").
"""

import math

import jax
from jax.sharding import Mesh
import numpy as np


def make_mesh(n_sets: int | None = None, n_keys: int = 1, devices=None) -> Mesh:
    """Build a ("sets", "keys") mesh over the given (or all) devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if n_sets is None:
        n_sets = n // n_keys
    if n_sets * n_keys != n:
        raise ValueError(
            f"mesh {n_sets}x{n_keys} != {n} devices"
        )
    arr = np.asarray(devices).reshape(n_sets, n_keys)
    return Mesh(arr, axis_names=("sets", "keys"))


def default_split(n: int) -> tuple[int, int]:
    """Factor n devices into (sets, keys): keys = largest power of two that
    divides n and is <= sqrt(n); data parallelism gets the rest."""
    keys = 1
    while n % (keys * 2) == 0 and (keys * 2) ** 2 <= n:
        keys *= 2
    return n // keys, keys
