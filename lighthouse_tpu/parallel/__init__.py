from lighthouse_tpu.parallel.mesh import make_mesh  # noqa: F401
from lighthouse_tpu.parallel.sharded_verify import (  # noqa: F401
    sharded_verify_signature_sets,
    sharded_verify_signature_sets_grouped,
)
