from lighthouse_tpu.verification_bus.bus import (  # noqa: F401
    DEFAULT_CLASS_BUDGETS,
    DEFAULT_FILL_TARGET,
    DEFAULT_TPU_HOLD_MS,
    VerificationBus,
)
from lighthouse_tpu.verification_bus.wall_model import (  # noqa: F401
    PredictedWallModel,
)
