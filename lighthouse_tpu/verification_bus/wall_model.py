"""Predicted batch-wall model: what will this dispatch cost?

The bus flushes when the earliest queued deadline's slack falls below
the predicted wall of the batch it would form — so the prediction IS
the scheduling policy. Three information sources, best-first:

  1. **Observed walls** (learned): every bus dispatch feeds the model
     its (live sets, wall seconds); an EMA per pow2 lane bucket tracks
     the measured cost of exactly the shapes this process dispatches.
     The same numbers land in `lighthouse_tpu_device_seconds` — the
     model is the scheduler-side view of that histogram family.
  2. **The compile ledger** (cold risk): a lane bucket this process has
     never dispatched will TRACE + COMPILE on first use
     (common/compile_ledger). The model asks the ledger whether the
     bucket's shape class has been seen; unseen buckets add the
     ledger's observed cold wall (or a conservative default) so a
     deadline-tight submission is not flushed into a 100x compile
     stall when a warm smaller bucket would have served it.
  3. **The measured scaling model** (seed): p50 ~= 90 ms + 97 us/sig
     (PERF_NOTES round 5, the Pallas scaling fit) — the prior before
     any observation, and the source of FIXED_DEVICE_COST_MS that the
     amortization accounting shares.

Host backends (ref/fake) observe their verify walls through the same
interface, so the model stays meaningful off-hardware: it predicts
whatever boundary it watched.
"""

import threading

from lighthouse_tpu.common.device_attribution import FIXED_DEVICE_COST_MS

# the measured per-signature marginal cost (PERF_NOTES: 97 us/sig)
PER_SET_COST_S = 97e-6
# EMA smoothing for per-bucket observed walls
EMA_ALPHA = 0.3
# cold-compile penalty when the ledger has no cold wall to report yet:
# conservative seconds added for a never-seen bucket (PR 8 brought the
# worst verify compile to ~7 s; stay below that but well above warm)
DEFAULT_COLD_PENALTY_S = 2.0


def _bucket(n: int) -> int:
    """Pow2 lane bucket — the same bucketing the tpu marshal applies,
    so model buckets and compiled shape classes line up."""
    b = 1
    n = max(1, int(n))
    while b < n:
        b <<= 1
    return b


class PredictedWallModel:
    """EMA-per-bucket wall predictor seeded from the measured scaling
    model, with compile-ledger cold-risk lookup."""

    def __init__(
        self,
        fixed_s: float = FIXED_DEVICE_COST_MS / 1e3,
        per_set_s: float = PER_SET_COST_S,
    ):
        self.fixed_s = fixed_s
        self.per_set_s = per_set_s
        self._lock = threading.Lock()
        self._ema: dict[int, float] = {}
        self._seen: set[int] = set()
        self.observations = 0

    def observe(self, live: int, wall_s: float):
        """Feed one completed dispatch's (live sets, wall seconds)."""
        if wall_s is None or wall_s < 0:
            return
        b = _bucket(live)
        with self._lock:
            prev = self._ema.get(b)
            self._ema[b] = (
                wall_s
                if prev is None
                else prev + EMA_ALPHA * (wall_s - prev)
            )
            self._seen.add(b)
            self.observations += 1

    def _cold_penalty(self, bucket: int) -> float:
        """Extra seconds when `bucket`'s BLS lane shape was never
        dispatched in this process. The bus's own observations clear it
        first; otherwise the compile ledger decides: ANY verify-plane
        entry whose shape bucket matches (cold OR warm — a warm entry
        proves the graph is compiled, even if it was dispatched outside
        the bus) clears the penalty, and an unseen bucket is charged
        the worst cold wall the VERIFY plane has shown — never another
        plane's compile (a 7 s KZG cold must not make every gossip
        deadline look unmeetable). No verify evidence at all falls
        back to the conservative default."""
        with self._lock:
            if bucket in self._seen:
                return 0.0
        try:
            from lighthouse_tpu.common.compile_ledger import LEDGER

            entries = LEDGER.entries()
        # lint: allow(except-swallow): ledger read is advisory — prediction falls back to the default penalty
        except Exception:
            entries = []
        shape_prefix = f"s{bucket}k"
        colds = []
        for e in entries:
            fn = e.get("fn") or ""
            if not fn.startswith("verify"):
                continue
            if (e.get("shape") or "").startswith(shape_prefix):
                return 0.0
            if e.get("event") == "cold":
                colds.append(e.get("duration_s") or 0.0)
        return max(colds) if colds else DEFAULT_COLD_PENALTY_S

    def predict_s(self, live: int, cold_risk: bool = False) -> float:
        """Predicted wall seconds for a batch of `live` sets. With
        `cold_risk` the never-seen-bucket compile penalty is added —
        the deadline-flush decision uses it, the amortization math
        never does."""
        b = _bucket(live)
        with self._lock:
            ema = self._ema.get(b)
        base = (
            ema
            if ema is not None
            else self.fixed_s + self.per_set_s * max(1, int(live))
        )
        if cold_risk:
            base += self._cold_penalty(b)
        return base

    def stats(self) -> dict:
        with self._lock:
            return {
                "observations": self.observations,
                "buckets": {
                    str(b): round(v, 6)
                    for b, v in sorted(self._ema.items())
                },
                "seed_fixed_ms": round(self.fixed_s * 1e3, 3),
                "seed_per_set_us": round(self.per_set_s * 1e6, 3),
            }
