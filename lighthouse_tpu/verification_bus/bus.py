"""Cross-subsystem verification bus: deadline-aware batch coalescing.

The whole design funnels every BLS signature through ONE batch boundary
(`verify_signature_sets`, PAPER.md / blst.rs) — but the consumers
(gossip singles, sync segments, sidecar headers, op-pool packing, the
slasher) each used to call the device plane independently, so small
batches paid the ~90 ms fixed device cost ALONE: PR 11's flight
recorder measures `device_amortized_fixed_ms` at 90 ms/set for every
N=1 gossip verification while the asymptote sits at 97 us/sig. The
committee cost model of "Performance of EdDSA and BLS Signatures in
Committee-Based Consensus" (PAPERS.md) says batch amortization — not
kernel speed — is the dominant lever at production message rates. This
module is that lever.

Consumers submit `SignatureSet` batches tagged with their PR 11
consumer label and a deadline (the PR 10 `Deadline` shape — anything
with `.remaining()` — or a float budget; gossip paths derive theirs
from the slot clock's 1/3-slot attestation deadline, sync/op-pool get
lenient per-class budgets). The scheduler coalesces pending
submissions across subsystems into shared device batches on the
existing bucketed-pow2 lanes, flushing when:

  * **deadline** — the earliest queued deadline's slack falls below
    the predicted batch wall (`wall_model.PredictedWallModel`, seeded
    from the measured scaling model + compile ledger and LEARNED from
    every dispatch this bus performs);
  * **fill** — pending live sets reach the bucket fill target (a
    bigger batch would only pad into the next pow2 bucket);
  * **pressure** — the beacon processor's queue-depth/shedding signals
    say the node is loaded (big batches then form naturally from the
    backlog; holding would add latency exactly when it hurts);
  * **hold** — the oldest submission has waited its maximum hold (the
    knob that bounds worst-case added latency; on host backends the
    default hold is ZERO — there is no fixed device cost to amortize,
    so the bus degrades to an attributed passthrough and test/sim
    behavior is latency-identical).

Verdicts fan back per submission. A mixed batch failing falls back to
per-consumer sub-batches, so one consumer's invalid signature can
never fail a coterminous consumer's verdict — each caller keeps its
existing error semantics (including exceptions: a submission whose
sets raise re-raises in ITS caller only). Every formed batch keeps
consumer attribution: `bls.verify_signature_sets_shared` counts each
contributor's sets in the registry, and the bus emits one
`signature_batch` journal event per contributing submission with a
shared `bus_batch` id plus the batch's lanes/waste/amortized economics
— so the sim's `attribution_complete` invariant and byte-identical
replay survive coalescing (`signature_batch` stays off the canonical
projection).
"""

import threading
import time

from lighthouse_tpu.common import device_attribution as attribution
from lighthouse_tpu.common import slot_budget
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.verification_bus.wall_model import PredictedWallModel

_SUBMITTED = REGISTRY.counter_vec(
    "lighthouse_tpu_bus_submissions_total",
    "signature-set submissions entering the verification bus, by "
    "consumer",
    ("consumer",),
)
_BATCHES_FORMED = REGISTRY.counter_vec(
    "lighthouse_tpu_bus_batches_formed_total",
    "device batches formed by the bus, by flush trigger "
    "(passthrough|hold|deadline|fill|bulk|pressure|fallback)",
    ("trigger",),
)
_BATCH_LIVE = REGISTRY.histogram(
    "lighthouse_tpu_bus_batch_live_sets",
    "live signature sets per bus-formed batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 256, 1024, 4096, 16384),
)
_BATCH_SUBMISSIONS = REGISTRY.histogram(
    "lighthouse_tpu_bus_batch_submissions",
    "submissions coalesced into one bus-formed batch",
    buckets=(1, 2, 4, 8, 16, 32, 64, 256),
)
_WAIT_SECONDS = REGISTRY.histogram_vec(
    "lighthouse_tpu_bus_wait_seconds",
    "submit-to-verdict wall time per submission, by consumer",
    ("consumer",),
    buckets=(
        0.0005, 0.001, 0.002, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
        0.5, 1.0, 2.5, 10.0,
    ),
)
_DEADLINE_MISSES = REGISTRY.counter_vec(
    "lighthouse_tpu_bus_deadline_misses_total",
    "submissions whose verdict landed after their deadline expired "
    "(each got an immediate small-batch flush, never a silent drop)",
    ("consumer",),
)

# default per-class deadline budgets (seconds) when the caller passes
# no Deadline: gossip classes are tight (the 1/3-slot attestation
# deadline is the real currency — the chain overrides these from its
# slot clock), sync/op-pool/slasher are lenient bulk work
DEFAULT_CLASS_BUDGETS = {
    "gossip_single": 2.0,
    "sidecar_header": 2.0,
    "sync_segment": 10.0,
    "oppool": 10.0,
    "slasher": 30.0,
    "kzg": 5.0,
    "da_cells": 5.0,
    "bench": 10.0,
}
DEFAULT_BUDGET_S = 5.0

# how many pending live sets close a batch: one pow2 bucket's worth —
# beyond this, coalescing more only pads into the next bucket while
# every queued deadline keeps aging
DEFAULT_FILL_TARGET = 64

# a submission at least this large flushes IMMEDIATELY (trigger
# "bulk"): it already amortizes the fixed cost well on its own, so
# holding it only adds latency — and flushing it carries every pending
# single along for free co-amortization. This is what keeps
# sync_segment p99 hold-free while gossip singles ride its batches.
DEFAULT_BULK_FLUSH_LIVE = 8

# default maximum hold on the tpu backend: worth waiting this long for
# co-riders when the dispatch itself costs ~90 ms fixed. Host backends
# default to zero hold (no fixed cost to amortize).
DEFAULT_TPU_HOLD_MS = 25.0


class _Submission:
    __slots__ = (
        "sets", "consumer", "journal", "slot", "attrs", "backend",
        "budget_s", "submitted_at", "expires_at", "event", "result",
        "exc", "done", "claimed", "dispatch_t0", "kind", "extra",
    )

    def __init__(
        self, sets, consumer, journal, slot, attrs, backend, budget_s,
        kind="bls", extra=None,
    ):
        # `kind` selects the shared-dispatch plane ("bls" signature
        # sets | "da_cells" cell-proof items); the queue, flush
        # triggers, deadline handling, and mixed-fail isolation are
        # kind-agnostic — only the dispatch and journal event differ.
        self.kind = kind
        # kind-specific dispatch context (da_cells: geometry + setup)
        self.extra = extra
        self.sets = sets
        self.consumer = consumer
        self.journal = journal
        self.slot = slot
        self.attrs = attrs
        self.backend = backend
        self.budget_s = budget_s
        self.submitted_at = time.monotonic()
        self.expires_at = self.submitted_at + budget_s
        self.event = threading.Event()
        self.result = None
        self.exc = None
        self.done = False
        self.claimed = False
        # monotonic timestamp stamped when a flush claims this
        # submission into a dispatch group — the slot-budget recorder's
        # queue-wait/dispatch split on the submitter side
        self.dispatch_t0 = None


class VerificationBus:
    """One per chain (chain.verification_bus): the submit boundary every
    consumer subsystem reaches the BLS device plane through (the
    bus-submit lint pass enforces it)."""

    def __init__(
        self,
        backend: str | None = None,
        journal=None,
        max_hold_ms: float | None = None,
        fill_target: int = DEFAULT_FILL_TARGET,
        class_budgets: dict | None = None,
        seed: int | None = None,
    ):
        self.backend = backend
        self.journal = journal
        # None = backend-derived default (tpu: DEFAULT_TPU_HOLD_MS,
        # host: 0 == attributed passthrough); a float is an explicit
        # override (the cli knob / bench A/B)
        self.max_hold_ms = max_hold_ms
        self.fill_target = int(fill_target)
        self.bulk_flush_live = DEFAULT_BULK_FLUSH_LIVE
        self.class_budgets = dict(DEFAULT_CLASS_BUDGETS)
        if class_budgets:
            self.class_budgets.update(class_budgets)
        # consumer -> zero-arg callable returning a budget in seconds;
        # the chain wires slot-clock-derived gossip budgets here
        self.budget_fns: dict = {}
        # zero-arg callable -> bool: the beacon processor's
        # queue-depth/shedding pressure signal
        self.pressure_fn = None
        self.seed = seed
        self.wall_model = PredictedWallModel()
        self._lock = threading.Lock()
        # thread-local slot-program staging: the chain stages an
        # import's deferred DA settle here so the SAME submit that
        # carries the import's signature sets becomes one chained
        # slot-program (one dispatch for fold + settle)
        self._tls = threading.local()
        self._pending: list[_Submission] = []
        self._batch_seq = 0
        # counters (under _lock)
        self._submitted = 0
        self._completed = 0
        self._batches_formed = 0
        self._coalesced_batches = 0
        self._live_dispatched = 0
        self._deadline_misses = 0
        self._fallback_batches = 0
        self._triggers: dict[str, int] = {}

    # ------------------------------------------------------------- submit

    def submit(
        self,
        sets,
        consumer: str,
        deadline=None,
        journal=None,
        slot=None,
        journal_attrs: dict | None = None,
        backend: str | None = None,
    ) -> bool:
        """Verify `sets` as one unit (the `verify_signature_sets`
        contract: True iff every set verifies), possibly coalesced with
        other consumers' concurrent submissions. Blocks until the
        verdict; never drops — a submission whose deadline expires
        while queued gets an immediate small-batch flush.

        An EMPTY submission is vacuously true and returns immediately:
        it must never occupy a coalescing slot or join a device batch
        (it would distort live/batch stats and could hold a flush
        decision open for zero work). Callers that need the raw
        `verify_signature_sets` empty-is-False semantics check
        emptiness themselves before submitting.

        `deadline` is a PR 10 Deadline (anything with `.remaining()`)
        or a float budget in seconds; None derives the class budget
        (slot-clock-wired for gossip classes when available).

        When the chain staged a deferred DA settle on this thread
        (`stage_program_work`), the submission becomes a CHAINED
        slot-program: the settle rides the same single dispatch as the
        signature fold (`ops/slot_program.py`), with per-submission
        verdict isolation preserved — the settle verdict fans back
        through the staged work, never through this return value."""
        sets = list(sets)
        if not sets:
            # still validate the label — a typo'd consumer must fail
            # loudly here like it would on the non-empty path. A staged
            # settle stays staged: the chain's finalize fallback settles
            # it serially if no non-empty submit follows.
            attribution.normalize(consumer)
            return True
        work = self.pop_staged_work()
        consumer = attribution.normalize(consumer)
        _SUBMITTED.labels(consumer).inc()
        budget_s = self._budget_for(consumer, deadline)
        sub = _Submission(
            sets,
            consumer,
            journal if journal is not None else self.journal,
            slot,
            journal_attrs,
            backend or self.backend,
            budget_s,
            kind="slot_program" if work is not None else "bls",
            extra={"work": work} if work is not None else None,
        )
        return self._submit_and_wait(sub)

    def stage_program_work(self, work):
        """Stage one import's deferred device work (a DA checker
        `PendingSettle`) on THIS thread: the next `submit` from the
        same thread folds it into a chained slot-program. Thread-local
        by design — the staging site and the signature-collector
        submit run on the import thread back to back."""
        self._tls.staged_work = work

    def pop_staged_work(self):
        """Claim (and clear) this thread's staged program work."""
        work = getattr(self._tls, "staged_work", None)
        if work is not None:
            self._tls.staged_work = None
        return work

    def submit_program(
        self,
        work,
        consumer: str = "kzg",
        deadline=None,
        journal=None,
        slot=None,
        backend: str | None = None,
    ) -> bool:
        """Submit a settle-only chained slot-program (the sync import
        path: NO_VERIFICATION skips the signature fold, but the
        deferred DA settle still wants the guarded one-dispatch
        boundary). Blocks until the program ran; the settle verdict
        fans back through `work.deliver`, and the caller reads it via
        `work.finalize()` — the boolean returned here is the program's
        group verdict, vacuously True for a healthy settle-only run."""
        consumer = attribution.normalize(consumer)
        _SUBMITTED.labels(consumer).inc()
        budget_s = self._budget_for(consumer, deadline)
        sub = _Submission(
            [],
            consumer,
            journal if journal is not None else self.journal,
            slot,
            None,
            backend or self.backend,
            budget_s,
            kind="slot_program",
            extra={"work": work},
        )
        return self._submit_and_wait(sub)

    def submit_cells(
        self,
        items,
        geometry,
        consumer: str = "da_cells",
        deadline=None,
        journal=None,
        slot=None,
        journal_attrs: dict | None = None,
        backend: str | None = None,
        setup=None,
    ) -> bool:
        """Verify DA cell-proof items (commitment, cell_index, cell,
        proof) as one unit, coalesced with other pending CELL
        submissions into one folded pairing batch (`da.cells
        .verify_cell_proof_batch`). Same queue/deadline/mixed-fail
        contract as `submit`; cell batches never merge with signature
        batches — the flush groups by (backend, kind) because the two
        planes fold over different device kernels. Empty submissions
        verify vacuously, like `submit`."""
        items = list(items)
        if not items:
            attribution.normalize(consumer)
            return True
        consumer = attribution.normalize(consumer)
        _SUBMITTED.labels(consumer).inc()
        budget_s = self._budget_for(consumer, deadline)
        sub = _Submission(
            items,
            consumer,
            journal if journal is not None else self.journal,
            slot,
            journal_attrs,
            backend or self.backend,
            budget_s,
            kind="da_cells",
            extra={"geometry": geometry, "setup": setup},
        )
        return self._submit_and_wait(sub)

    def _submit_and_wait(self, sub: _Submission) -> bool:
        hold_s = self._hold_s(sub.backend)
        # the pressure signal only matters when a hold could actually
        # be taken — on zero-hold (host-backend passthrough) paths the
        # flush is immediate either way, and probing would couple every
        # verification to the beacon processor's hottest locks.
        # Evaluated OUTSIDE the bus lock (it takes the processor's own).
        pressure = hold_s > 0 and self._pressure()
        # caller-side slot-budget interval: the submit-to-verdict span
        # IS the import's causal device round trip (the flush may run
        # on another submitter's thread — this thread still blocks for
        # exactly that long). The queue-wait/dispatch split comes from
        # the flush's dispatch_t0 stamp at close. Chained slot-programs
        # mark kind "fused" so the dispatch ledger can count fused vs
        # serial round trips per import.
        _budget_tok = slot_budget.open_dispatch(
            sub.consumer,
            kind="fused" if sub.kind == "slot_program" else "bus",
        )
        try:
            with self._lock:
                self._pending.append(sub)
                self._submitted += 1
                trigger = self._flush_trigger_locked(pressure)
            if trigger:
                self._flush(trigger)
            while not sub.done:
                if sub.claimed:
                    # another thread's flush took this submission; its
                    # _dispatch_group completes every claimed submission
                    # even on an escaping BaseException (finally), so
                    # this wait always terminates
                    sub.event.wait(1.0)
                    continue
                now = time.monotonic()
                pred = self.wall_model.predict_s(
                    len(sub.sets), cold_risk=sub.backend == "tpu"
                )
                wake = min(
                    sub.submitted_at + hold_s, sub.expires_at - pred
                )
                timeout = wake - now
                if timeout > 0:
                    sub.event.wait(timeout)
                    continue
                reason = (
                    "deadline" if now >= sub.expires_at - pred
                    else "hold"
                )
                self._flush(reason)
        finally:
            slot_budget.close_dispatch(
                _budget_tok,
                queue_wait_s=(
                    max(0.0, sub.dispatch_t0 - sub.submitted_at)
                    if sub.dispatch_t0 is not None
                    else None
                ),
            )
        if sub.exc is not None:
            raise sub.exc
        return bool(sub.result)

    def submit_individual(
        self,
        sets,
        consumer: str,
        journal=None,
        slot=None,
        backend: str | None = None,
    ) -> list:
        """Per-set verdicts — the exact-fallback half of the batch
        semantics consumers run AFTER their batch verdict came back
        False. No coalescing (it is the rare recovery path, and its
        callers need the answer now); attribution and journal emission
        ride the normal api path."""
        from lighthouse_tpu import bls

        return bls.verify_signature_sets_individually(
            list(sets),
            backend=backend or self.backend,
            consumer=consumer,
            journal=journal if journal is not None else self.journal,
            slot=slot,
        )

    # ---------------------------------------------------------- scheduling

    def _budget_for(self, consumer: str, deadline) -> float:
        if deadline is not None:
            remaining = getattr(deadline, "remaining", None)
            if callable(remaining):
                return max(0.0, float(remaining()))
            return max(0.0, float(deadline))
        fn = self.budget_fns.get(consumer)
        if fn is not None:
            try:
                return max(0.0, float(fn()))
            # lint: allow(except-swallow): a broken budget source must not fail verification — fall back to the class default
            except Exception:
                pass
        return self.class_budgets.get(consumer, DEFAULT_BUDGET_S)

    def _hold_s(self, backend) -> float:
        if self.max_hold_ms is not None:
            return max(0.0, float(self.max_hold_ms)) / 1e3
        return (DEFAULT_TPU_HOLD_MS / 1e3) if backend == "tpu" else 0.0

    def _pressure(self) -> bool:
        if self.pressure_fn is None:
            return False
        try:
            return bool(self.pressure_fn())
        # lint: allow(except-swallow): a broken pressure source must not fail verification — treat as no pressure
        except Exception:
            return False

    def _flush_trigger_locked(self, pressure: bool):
        """The submit-time flush decision (caller holds the lock):
        returns the trigger name or None (keep holding)."""
        pending = [s for s in self._pending if not s.claimed]
        if not pending:
            return None
        if any(s.kind == "slot_program" for s in pending):
            # a chained slot-program IS an import's critical path
            # carrying its own co-resident settle — holding it for
            # co-riders only delays the import it was fused for
            return "bulk"
        live = sum(len(s.sets) for s in pending)
        if live >= self.fill_target:
            return "fill"
        if any(
            len(s.sets) >= self.bulk_flush_live for s in pending
        ):
            return "bulk"
        if pressure:
            return "pressure"
        if all(self._hold_s(s.backend) <= 0 for s in pending):
            return "passthrough"
        now = time.monotonic()
        pred = self.wall_model.predict_s(
            live,
            cold_risk=any(s.backend == "tpu" for s in pending),
        )
        if min(s.expires_at for s in pending) - now <= pred:
            return "deadline"
        return None

    # ------------------------------------------------------------ dispatch

    def _flush(self, trigger: str):
        """Form one (or, with mixed backend overrides, one per
        backend) shared batch from everything pending and deliver
        verdicts. Runs on whichever submitter thread hit the trigger;
        the device dispatch happens OUTSIDE the bus lock so new
        submissions keep queueing behind it."""
        with self._lock:
            batch = [s for s in self._pending if not s.claimed]
            self._pending = []
            for s in batch:
                s.claimed = True
        if not batch:
            return
        groups: dict = {}
        for s in batch:
            groups.setdefault((s.backend, s.kind), []).append(s)
        for (backend, _kind), subs in groups.items():
            self._dispatch_group(subs, backend, trigger)

    def _dispatch_group(self, subs, backend, trigger: str):
        """Dispatch one backend group, guaranteeing every claimed
        submission completes: even a BaseException escaping the
        dispatch (operator interrupt mid-compile, thread kill) must not
        strand the other submitters in their wait loops — the finally
        fails any straggler loudly instead."""
        now = time.monotonic()
        for s in subs:
            s.dispatch_t0 = now
        try:
            self._dispatch_group_inner(subs, backend, trigger)
        finally:
            stragglers = [s for s in subs if not s.done]
            for s in stragglers:
                if s.exc is None:
                    s.exc = RuntimeError(
                        "verification bus flush aborted before this "
                        "submission's verdict"
                    )
                s.done = True
                s.event.set()
            if stragglers:
                with self._lock:
                    self._completed += len(stragglers)

    def _shared_verify(self, subs, backend):
        """Kind dispatch: one group is homogeneous by construction
        (the flush groups by (backend, kind))."""
        if subs[0].kind == "da_cells":
            return self._cells_shared_verify(subs, backend)
        if subs[0].kind == "slot_program":
            return self._program_shared_verify(subs, backend)
        return self._guarded_shared_verify(subs, backend)

    def _program_shared_verify(self, subs, backend):
        """Chained slot-program dispatch: the group's signature sets
        AND each submission's staged DA settle run as ONE guarded
        device program (`ops/slot_program.py`) — one upload, one
        scheduled program, one verdict bundle. The returned (ok,
        record) is the signature verdict (the group contract the
        mixed-batch retry isolates per submission); settle verdicts
        fan back through each work's `deliver`, so one import's
        invalid blob can never fail a coterminous import's fold. The
        program dispatches on the same "bls" plane as the plain path:
        same breaker, same canary sentinels, same deterministic
        injection, same serial host failover tiers."""
        from lighthouse_tpu import bls
        from lighthouse_tpu.ops.slot_program import SlotProgram

        program = SlotProgram(seed=self.seed)
        for s in subs:
            if s.sets:
                program.add_signatures(s.sets, s.consumer)
            work = (s.extra or {}).get("work")
            if work is not None:
                program.add_settle(work)
        effective = backend or bls.default_backend()
        journal = next(
            (s.journal for s in subs if s.journal is not None), None
        )
        slot = next((s.slot for s in subs if s.slot is not None), None)
        return program.run(
            backend=backend,
            journal=journal,
            slot=slot,
            predicted_s=self.wall_model.predict_s(
                max(1, program.total_live()),
                cold_risk=effective == "tpu",
            ),
        )

    def _cells_shared_verify(self, subs, backend):
        """Shared DA cell-proof dispatch: concatenate every
        submission's items into ONE folded pairing batch.
        `da.cells.verify_cell_proof_batch` owns the tier walk (tpu ->
        xla-host -> ref through the guarded executor, plane
        "da_cells"), slot-budget marking, and per-consumer attribution
        (`note_batch`), so the bus adds only queueing + coalescing
        here. The wall model is shared with the signature plane —
        both are two-pair folded pairings whose wall is dominated by
        the same fixed dispatch cost, and the model only gates flush
        timing. Returns (ok, None): cell batches carry no
        lanes/waste record (the tpu marshal reports its own)."""
        from lighthouse_tpu import bls
        from lighthouse_tpu.da import cells as da_cells

        items = [it for s in subs for it in s.sets]
        geo = subs[0].extra["geometry"]
        setup = next(
            (
                s.extra.get("setup")
                for s in subs
                if s.extra.get("setup") is not None
            ),
            None,
        )
        effective = backend or bls.default_backend()
        ok = da_cells.verify_cell_proof_batch(
            items,
            geo,
            backend=effective,
            setup=setup,
            seed=self.seed,
            consumer="da_cells",
        )
        return bool(ok), None

    def _guarded_shared_verify(self, subs, backend):
        """The shared dispatch, routed through the device-plane guard
        (`device_plane.GUARD`): watchdog + circuit breaker + host
        failover (tpu -> xla-host -> ref) around the device backend,
        deterministic fault injection on EVERY backend (the sim arms
        faults against host backends to exercise the whole guard with
        zero compiles), and — when the canary is active — the
        known-answer sentinel contract: the valid sentinel rides the
        batch as an attribution-free extra set, and the (valid,
        invalid) pair is checked per-set BEFORE the batch verify inside
        the same guarded attempt. Ordering matters twice over: a lying
        verdict plane is caught before it can mis-verify the batch, and
        the registry side of attribution_complete is still untouched
        when the violation raises, so the host failover re-counts each
        contributor exactly once."""
        from lighthouse_tpu import bls
        from lighthouse_tpu.device_plane import (
            GUARD,
            DeviceFaultError,
            canary,
            host_device_scope,
            pow2_bucket,
        )

        submissions = [(s.sets, s.consumer) for s in subs]
        effective = backend or bls.default_backend()
        total_live = sum(len(s.sets) for s in subs)
        journal = next(
            (s.journal for s in subs if s.journal is not None), None
        )
        slot = next((s.slot for s in subs if s.slot is not None), None)
        canary_on = GUARD.canary_active(effective)
        extra = [canary.bls_sentinels()[0]] if canary_on else None

        def attempt(plan):
            if canary_on:
                canary.check_pair(effective, plan)
            ok, record = bls.verify_signature_sets_shared(
                submissions, backend=backend, seed=self.seed,
                extra_sets=extra,
            )
            return plan.verdict(bool(ok)), record

        def host_tier(tier_backend, scoped=False):
            def run():
                if scoped:
                    with host_device_scope():
                        return bls.verify_signature_sets_shared(
                            submissions, backend=tier_backend,
                            seed=self.seed,
                        )
                return bls.verify_signature_sets_shared(
                    submissions, backend=tier_backend, seed=self.seed,
                )

            return run

        if effective == "tpu":
            fallbacks = [
                ("xla-host", host_tier("tpu", scoped=True)),
                ("ref", host_tier("ref")),
            ]
            fault_types = None  # any escape from a device dispatch
        else:
            fallbacks = [("ref", host_tier("ref"))]
            # host backends cross no device boundary: only the guard's
            # own fault taxonomy (injected faults, canary violations)
            # fails over — data-dependent exceptions keep their
            # caller-visible semantics
            fault_types = (DeviceFaultError,)
        return GUARD.dispatch(
            "bls",
            pow2_bucket(total_live),
            attempt,
            fallbacks=fallbacks,
            journal=journal,
            slot=slot,
            predicted_s=self.wall_model.predict_s(
                total_live, cold_risk=effective == "tpu"
            ),
            fault_types=fault_types,
        )

    def _dispatch_group_inner(self, subs, backend, trigger: str):
        with self._lock:
            self._batch_seq += 1
            batch_id = self._batch_seq
            self._batches_formed += 1
            if len(subs) > 1:
                self._coalesced_batches += 1
            self._live_dispatched += sum(len(s.sets) for s in subs)
            self._triggers[trigger] = (
                self._triggers.get(trigger, 0) + 1
            )
        total_live = sum(len(s.sets) for s in subs)
        _BATCHES_FORMED.labels(trigger).inc()
        _BATCH_LIVE.observe(total_live)
        _BATCH_SUBMISSIONS.observe(len(subs))
        t0 = time.perf_counter()
        exc = None
        record = None
        try:
            ok, record = self._shared_verify(subs, backend)
        except Exception as e:
            ok = False
            exc = e
        wall_s = time.perf_counter() - t0
        self.wall_model.observe(total_live, wall_s)
        if ok or len(subs) == 1:
            self._journal_group(
                subs, [ok] * len(subs), batch_id, trigger, backend,
                total_live, wall_s, record, exc=exc,
            )
            self._complete(subs, [ok] * len(subs), exc_all=exc)
            return
        # mixed batch failed (or raised): isolate per submission so one
        # consumer's bad set cannot fail — or crash — a coterminous
        # consumer's verdict. Each sub-batch re-dispatches through the
        # same shared boundary (counted again on BOTH the registry and
        # journal sides, so attribution equality holds).
        self._journal_group(
            subs, [False] * len(subs), batch_id, trigger, backend,
            total_live, wall_s, record, exc=exc, mixed_retry=True,
        )
        verdicts = []
        for s in subs:
            with self._lock:
                self._batch_seq += 1
                sub_id = self._batch_seq
                self._batches_formed += 1
                self._fallback_batches += 1
                self._live_dispatched += len(s.sets)
                self._triggers["fallback"] = (
                    self._triggers.get("fallback", 0) + 1
                )
            _BATCHES_FORMED.labels("fallback").inc()
            _BATCH_LIVE.observe(len(s.sets))
            _BATCH_SUBMISSIONS.observe(1)
            t1 = time.perf_counter()
            sub_exc = None
            sub_record = None
            try:
                ok_i, sub_record = self._shared_verify([s], backend)
            except Exception as e:
                ok_i = False
                sub_exc = e
            sub_wall = time.perf_counter() - t1
            self.wall_model.observe(len(s.sets), sub_wall)
            self._journal_group(
                [s], [ok_i], sub_id, "fallback", backend,
                len(s.sets), sub_wall, sub_record, exc=sub_exc,
            )
            s.exc = sub_exc
            verdicts.append(ok_i)
        self._complete(subs, verdicts)

    def _journal_group(
        self,
        subs,
        verdicts,
        batch_id: int,
        trigger: str,
        backend,
        total_live: int,
        wall_s: float,
        record,
        exc=None,
        mixed_retry: bool = False,
    ):
        """One `signature_batch` event per contributing submission,
        sharing the batch id and economics — the journal side of the
        attribution_complete equality (registry counted each
        contributor's sets in verify_signature_sets_shared). DA cell
        submissions emit `cell_batch` instead: they attribute through
        `note_batch` (not `note_sets`), so they live outside the
        signature-side equality and the canonical replay hash."""
        now = time.monotonic()
        for s, ok_i in zip(subs, verdicts):
            journal = s.journal
            if journal is None:
                continue
            if s.kind == "slot_program" and not s.sets:
                # settle-only program (sync path): no signature sets
                # were counted on the registry side, so no
                # signature_batch event either — the settle's own
                # sidecar/da_settle events are its forensic record,
                # exactly like the serial path
                continue
            attrs = {
                "consumer": s.consumer,
                "n_sets": len(s.sets),
                "backend": backend or "default",
                "bus_batch": batch_id,
                "batch_live": total_live,
                "n_submissions": len(subs),
                "trigger": trigger,
                "wait_s": round(now - s.submitted_at, 6),
                "budget_s": round(s.budget_s, 6),
                "wall_s": round(wall_s, 6),
            }
            if record is not None:
                if record.get("lanes") is not None:
                    attrs["lanes"] = record["lanes"]
                    attrs["waste"] = record.get("waste", 0)
                if record.get("amortized_fixed_ms") is not None:
                    attrs["amortized_fixed_ms"] = record[
                        "amortized_fixed_ms"
                    ]
            if mixed_retry:
                attrs["mixed_retry"] = True
            if s.attrs:
                attrs.update(s.attrs)
            outcome = (
                "error" if exc is not None
                else ("ok" if ok_i else "failed")
            )
            if s.kind == "da_cells":
                journal.emit(
                    "cell_batch",
                    slot=s.slot,
                    outcome=outcome,
                    **attrs,
                )
            else:
                journal.emit(
                    "signature_batch",
                    slot=s.slot,
                    outcome=outcome,
                    **attrs,
                )

    def _complete(self, subs, verdicts, exc_all=None):
        now = time.monotonic()
        missed = 0
        for s, ok_i in zip(subs, verdicts):
            _WAIT_SECONDS.labels(s.consumer).observe(
                now - s.submitted_at
            )
            if now > s.expires_at:
                _DEADLINE_MISSES.labels(s.consumer).inc()
                missed += 1
            if exc_all is not None:
                s.exc = exc_all
            s.result = ok_i
            s.done = True
            s.event.set()
        with self._lock:
            self._completed += len(subs)
            self._deadline_misses += missed

    # --------------------------------------------------------------- reads

    def stats(self) -> dict:
        """The health-plane / bench view: knobs, queue state, batch
        formation counters, and the learned wall model."""
        with self._lock:
            batches = self._batches_formed
            return {
                "backend": self.backend,
                "max_hold_ms": (
                    self.max_hold_ms
                    if self.max_hold_ms is not None
                    else (
                        DEFAULT_TPU_HOLD_MS
                        if self.backend == "tpu"
                        else 0.0
                    )
                ),
                "fill_target": self.fill_target,
                "bulk_flush_live": self.bulk_flush_live,
                "class_budgets": dict(self.class_budgets),
                "pending": len(self._pending),
                "submitted": self._submitted,
                "completed": self._completed,
                "batches_formed": batches,
                "coalesced_batches": self._coalesced_batches,
                "live_dispatched": self._live_dispatched,
                "mean_live_per_batch": round(
                    self._live_dispatched / batches, 3
                )
                if batches
                else 0.0,
                "deadline_misses": self._deadline_misses,
                "fallback_batches": self._fallback_batches,
                "triggers": dict(self._triggers),
                "wall_model": self.wall_model.stats(),
            }
