"""BENCH_CONFIG=lcserve / lcproof: the light-client serving plane.

Two configs ride this module:

  * ``lcserve`` — read-flood phase against ONE live node: drive the
    chain to finality with full-participation sync aggregates, then
    flood the hot light-client reads (bootstrap by trusted root +
    per-period update ranges + finality/optimistic documents, SSZ
    streaming responses) with concurrent clients. Reports p50/p99 per
    admission class from the existing `http_class_seconds` histogram
    (phase-diffed), asserts cache misses <= TTL windows (the per-import
    invalidated TTL cache converting the flood into one producer
    lookup per window), and carries the streamed-bytes/chunks totals.
  * ``lcproof`` — the batched device Merkle-proof kernel
    (ops/merkle_proof) at BENCH_NSETS query shapes (the watcher sweeps
    1k/16k): deterministic (leaf, branch, gindex) queries at the
    light-client finality depth, device results cross-checked
    byte-identical against the hashlib host oracle every iteration.

Crypto runs on the fake backend in lcserve (it measures the SERVING
edge); lcproof measures a real device kernel and is the entry the
hardware sweep replays. Neither line is ever `valid_for_headline`.
"""

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request

N_VALIDATORS = 8
# enough slots past the third epoch boundary that the chain finalizes
# and the producer holds bootstrap + finality/optimistic documents
CHAIN_SLOTS = 33

_FLOOD_PATHS = (
    "/eth/v1/beacon/light_client/finality_update",
    "/eth/v1/beacon/light_client/optimistic_update",
    "/eth/v1/beacon/light_client/updates?start_period=0&count=4",
)


def _build_node():
    from lighthouse_tpu.harness import Harness
    from lighthouse_tpu.node import BeaconNode
    from lighthouse_tpu.types.spec import minimal_spec

    spec = minimal_spec(ALTAIR_FORK_EPOCH=0)
    h = Harness(spec, N_VALIDATORS, backend="fake")
    node = BeaconNode("lcbench0", h.state, spec, backend="fake")
    for slot in range(1, CHAIN_SLOTS + 1):
        block = h.advance_slot_with_block(slot, consumer="bench")
        node.on_slot(slot)
        node.chain.process_block(block)
    return h, node


def _request(base: str, path: str, ssz: bool) -> int:
    req = urllib.request.Request(
        base + path,
        headers=(
            {"Accept": "application/octet-stream"} if ssz else {}
        ),
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            r.read()
        return 200
    except urllib.error.HTTPError as e:
        return e.code
    except OSError:
        return -1


def measure(jax, platform):
    """The lcserve read-flood line."""
    from lighthouse_tpu.bench_serve import _histogram_quantiles, _parse_family
    from lighthouse_tpu.common.metrics import REGISTRY

    if platform == "cpu":
        n_threads, reads_per_thread = 4, 60
    else:
        n_threads, reads_per_thread = 8, 120

    h, node = _build_node()
    api = node.start_http_api()
    base = f"http://127.0.0.1:{api.port}"
    producer = node.chain.light_client_producer
    bootstrap_roots = ["0x" + r.hex() for r in producer.bootstraps]
    if not bootstrap_roots:
        raise RuntimeError(
            "lcserve: chain never finalized — no bootstrap to flood"
        )

    def _served_bytes_total():
        fam = REGISTRY.get("lighthouse_tpu_lc_served_bytes_total")
        if fam is None:
            return 0.0
        return sum(c.value for c in fam.children().values())

    class_before = _parse_family(
        "lighthouse_tpu_http_class_seconds", "cls"
    )
    bytes_before = _served_bytes_total()
    cache = api._hot_caches["light_client"]
    cache.invalidate()
    misses_before = cache.misses
    statuses = []
    t0 = time.perf_counter()

    def flood(seed: int):
        paths = list(_FLOOD_PATHS) + [
            "/eth/v1/beacon/light_client/bootstrap/"
            + bootstrap_roots[seed % len(bootstrap_roots)]
        ]
        for i in range(reads_per_thread):
            # alternate SSZ streaming and JSON renderings of the same
            # hot documents — both ride the TTL cache
            statuses.append(
                _request(
                    base, paths[i % len(paths)], ssz=(i % 2 == 0)
                )
            )

    threads = [
        threading.Thread(target=flood, args=(i,), daemon=True)
        for i in range(n_threads)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    wall_s = time.perf_counter() - t0

    cache_misses = cache.misses - misses_before
    # distinct hot keys: each (path, rendering) pair occupies one slot
    hot_keys = (len(_FLOOD_PATHS) + len(bootstrap_roots)) * 2
    cache_windows = (int(wall_s / cache.ttl_s) + 1) * hot_keys
    served_bytes = _served_bytes_total() - bytes_before
    classes = _histogram_quantiles(
        "lighthouse_tpu_http_class_seconds",
        "cls",
        before=class_before,
    )
    api.stop()

    ok = sum(1 for s in statuses if s == 200)
    total = len(statuses)
    return {
        "metric": "lc_serve_read_throughput",
        "value": round(total / wall_s, 2),
        "unit": "requests/sec",
        "vs_baseline": 0.0,
        "platform": platform,
        "impl": "lc_ttl_stream",
        "n_sets": total,
        "flood_ok": ok,
        "flood_shed": sum(1 for s in statuses if s in (429, 503)),
        "classes": classes,
        "cache_misses": cache_misses,
        "cache_windows": cache_windows,
        "cache_ok": bool(cache_misses <= cache_windows),
        "served_bytes": int(served_bytes),
        "producer": producer.stats(),
        "valid_for_headline": False,
    }


# ----------------------------------------------------------- proof kernel


def _proof_queries(n: int, depth: int):
    """Deterministic (leaf, branch, gindex) fixtures at `depth`."""
    queries = []
    for i in range(n):
        leaf = hashlib.sha256(b"lcproof-leaf-%d" % i).digest()
        branch = [
            hashlib.sha256(b"lcproof-sib-%d-%d" % (i, d)).digest()
            for d in range(depth)
        ]
        gindex = (1 << depth) + (i * 2654435761 % (1 << depth))
        queries.append((leaf, branch, gindex))
    return queries


def measure_proofs(jax, platform):
    """The lcproof line: batched branch folds at BENCH_NSETS lanes,
    device byte-identical to the host oracle each iteration."""
    from lighthouse_tpu.ops import merkle_proof as mp

    n = int(os.environ.get("BENCH_NSETS", "1024"))
    depth = 6  # the light-client finality-branch depth
    queries = _proof_queries(n, depth)
    expected = mp.fold_branches_host(queries)

    t0 = time.perf_counter()
    got = mp.batch_merkle_roots(queries, consumer="bench")
    compile_s = time.perf_counter() - t0
    if got != expected:
        raise RuntimeError("device fold diverged from the host oracle")

    iters = 5
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        got = mp.batch_merkle_roots(queries, consumer="bench")
        times.append(time.perf_counter() - t0)
        if got != expected:
            raise RuntimeError(
                "device fold diverged from the host oracle"
            )
    times.sort()
    p50 = times[len(times) // 2]
    return {
        "metric": "lc_proof_batch_throughput",
        "value": round(n / p50, 1),
        "unit": "proofs/sec",
        "vs_baseline": 0.0,
        "platform": platform,
        "impl": "merkle_fold",
        "n_sets": n,
        "depth": depth,
        "p50_s": round(p50, 5),
        "compile_s": round(compile_s, 3),
        "byte_identical": True,
        "valid_for_headline": False,
    }


if __name__ == "__main__":
    print(json.dumps(measure(None, "cpu"), indent=2))
