"""Socket transport: TCP gossip + req/resp RPC, UDP discovery pings.

Role of the reference's real network edge
(lighthouse_network/src/behaviour/mod.rs:148 gossipsub over TCP,
rpc/codec/ssz_snappy.rs length-prefixed ssz_snappy req/resp,
discovery/mod.rs discv5 UDP): bytes actually cross OS sockets between
processes. `SocketNet` exposes the SAME surface as the in-process
`GossipHub` (join/subscribe/publish/report) plus RPC client proxies with
the `RpcServer` method surface, so `BeaconNode` and `SyncManager` run
unchanged over either transport.

Wire format (all little-endian):
  frame   := [u32 len][u8 kind][body]
  HELLO   (1): JSON {node_id, topics}           — handshake + interests
  GOSSIP  (2): [u16 tlen][topic][payload]       — payload is ssz_snappy
  RPC_REQ (3): [u32 req_id][u8 mlen][method][ssz_snappy payload]
  RPC_RSP (4): [u32 req_id][u8 status][chunks]  — chunk := [u32 len][data]
  SUB     (5): JSON {topics}                    — interest update

Gossip propagation is MESH-based (gossipsub's GRAFT/PRUNE control plane,
behaviour/mod.rs:148): a per-topic mesh of target degree D is maintained
by a heartbeat (graft under-degree, prune over-degree), messages forward
to mesh peers only once the mesh has formed (flood-to-interested is the
bootstrap fallback below D_lo so delivery never stalls), with message-id
dedup; scores accumulate per peer and a banned peer's connection is
dropped and un-meshed (peer_manager ban semantics).

UDP discovery: PING {node_id, tcp_port} answered by PONG {node_id,
tcp_port, known: [[host, tcp, udp], ...]} — and `discover` walks the
known-lists breadth-first over MULTIPLE hops (the discv5
FINDNODE/NODES iteration, discovery/mod.rs), so a node knowing only a
bootstrap address learns the whole reachable topology.
"""

import random

import json
import socket
import struct
import threading
import time

from lighthouse_tpu.common.locks import TimedLock
from lighthouse_tpu.common.logging import TimeLatch, get_logger

from lighthouse_tpu.network.gossip import (
    BAN_THRESHOLD,
    GOSSIP_MAX_SIZE,
    message_id,
)
from lighthouse_tpu.network.rpc import (
    BlobSidecarsByRangeRequest,
    BlobSidecarsByRootRequest,
    BlocksByRangeRequest,
    DataColumnSidecarsByRootRequest,
    Goodbye,
    MetaData,
    Ping,
    RateLimitExceeded,
    RpcError,
    StatusMessage,
)

# wire status codes for RPC responses: 0 ok, 1 server error, 2 is used
# client-side for timeouts, 3 rate-limited. 3 must survive the wire as
# a TYPED RateLimitExceeded — the sync manager treats "you are over
# budget" (rotate penalty-free) very differently from "server error"
# (downscore), and flattening it would punish honest servers for the
# client's own polling.
RPC_STATUS_RATE_LIMITED = 3
from lighthouse_tpu.network.snappy_codec import (
    frame_compress,
    frame_decompress,
)

_LOG = get_logger("socket_net")

KIND_HELLO = 1
KIND_GOSSIP = 2
KIND_RPC_REQ = 3
KIND_RPC_RSP = 4
KIND_SUB = 5
KIND_GRAFT = 6
KIND_PRUNE = 7

# gossipsub mesh parameters (behaviour/mod.rs:148 config: D/D_lo/D_hi)
MESH_D = 4
MESH_D_LO = 2
MESH_D_HI = 8
HEARTBEAT_INTERVAL = 1.0

# Dedup-cache generation size: at mainnet gossip rates (~tens of msgs/s)
# one generation covers several minutes — comfortably past the reference
# duplicate-cache TTL — while bounding the cache at 2 generations.
SEEN_CACHE_PER_GENERATION = 65_536

FORK_ORDER = ["phase0", "altair", "bellatrix"]


def _send_frame(sock, lock, kind: int, body: bytes):
    frame = struct.pack("<IB", len(body) + 1, kind) + body
    with lock:
        sock.sendall(frame)


def _recv_exact(sock, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class _PeerConn:
    def __init__(self, sock, node_id=None):
        self.sock = sock
        self.node_id = node_id
        self.topics: set[str] = set()
        self.score = 0.0
        self.lock = TimedLock("socket_net.peer_send")
        self.alive = True
        self.listen_port = None
        self.udp_port = None
        # conditioner hold queue: [remaining_sends, kind, body] entries —
        # a delayed/reordered frame waits here until `remaining_sends`
        # later frames have passed on this directed pair (deterministic
        # "delay by k sends", no wall-clock dependence)
        self.held: list = []
        self.held_lock = TimedLock("socket_net.peer_held")

    def close(self):
        self.alive = False
        try:
            self.sock.close()
        except OSError:
            pass


class RpcClientProxy:
    """RpcServer-shaped methods over the socket (the reference's
    outbound substream half of rpc/handler.rs)."""

    def __init__(self, net, peer_id: str, timeout: float = 10.0):
        self.net = net
        self.peer_id = peer_id
        self.timeout = timeout

    def _call(self, method: str, payload: bytes):
        return self.net._rpc_call(
            self.peer_id, method, payload, self.timeout
        )

    def status(self, caller: str) -> StatusMessage:
        chunks = self._call("status", b"")
        return StatusMessage.decode(frame_decompress(chunks[0]))

    def ping(self, caller: str, data: int) -> int:
        chunks = self._call("ping", frame_compress(Ping(data=data).to_bytes()))
        return Ping.decode(frame_decompress(chunks[0])).data

    def metadata(self, caller: str) -> MetaData:
        chunks = self._call("metadata", b"")
        return MetaData.decode(frame_decompress(chunks[0]))

    def blocks_by_range(self, caller: str, req: BlocksByRangeRequest):
        chunks = self._call(
            "blocks_by_range", frame_compress(req.to_bytes())
        )
        return [self.net._decode_block(c) for c in chunks]

    def blocks_by_root(self, caller: str, roots):
        payload = frame_compress(b"".join(bytes(r) for r in roots))
        chunks = self._call("blocks_by_root", payload)
        return [self.net._decode_block(c) for c in chunks]

    def goodbye(self, caller: str, reason: int = 0):
        self._call(
            "goodbye", frame_compress(Goodbye(reason=reason).to_bytes())
        )

    def blob_sidecars_by_range(self, caller: str, req):
        chunks = self._call(
            "blob_sidecars_by_range", frame_compress(req.to_bytes())
        )
        return [
            self.net.t.BlobSidecar.decode(frame_decompress(c))
            for c in chunks
        ]

    def blob_sidecars_by_root(self, caller: str, identifiers):
        req = BlobSidecarsByRootRequest(identifiers=list(identifiers))
        chunks = self._call(
            "blob_sidecars_by_root", frame_compress(req.to_bytes())
        )
        return [
            self.net.t.BlobSidecar.decode(frame_decompress(c))
            for c in chunks
        ]

    def data_column_sidecars_by_root(self, caller: str, identifiers):
        req = DataColumnSidecarsByRootRequest(
            identifiers=list(identifiers)
        )
        chunks = self._call(
            "data_column_sidecars_by_root",
            frame_compress(req.to_bytes()),
        )
        return [
            self.net.t.DataColumnSidecar.decode(frame_decompress(c))
            for c in chunks
        ]


class SocketNet:
    def __init__(
        self,
        node_id: str,
        types,
        spec,
        host: str = "127.0.0.1",
        rpc_server=None,
        on_peer_connected=None,
        on_peer_disconnected=None,
        conditioner=None,
        mesh_enabled: bool = True,
        forward_gate=None,
    ):
        """`conditioner` (sim/conditioner.NetworkConditioner) sits on the
        OUTBOUND edge of every gossip frame and RPC call: seeded
        per-directed-peer-pair drop/delay/reorder/duplicate decisions
        plus schedulable partition masks, so a multi-node simulation
        replays byte-identically from one seed. `mesh_enabled=False`
        forces flood-to-interested fanout (the deterministic topology
        simulations need — the mesh heartbeat samples an RNG on a timer
        thread). `on_peer_disconnected` fires when a peer's connection
        drops for ANY reason (read EOF, send failure, ban), so the sync
        manager's peer table cannot hold a dead proxy forever."""
        self.node_id = node_id
        self.t = types
        self.spec = spec
        self.host = host
        self.rpc_server = rpc_server
        self.on_peer_connected = on_peer_connected
        self.on_peer_disconnected = on_peer_disconnected
        self.conditioner = conditioner
        self.mesh_enabled = mesh_enabled
        # gossipsub propagation gating (behaviour validation mode): a
        # message failing the node's CHEAP structural validation is
        # delivered locally (for scoring) but NEVER forwarded — invalid
        # spam must not ride honest nodes deeper into the mesh, and the
        # penalty must land on the ORIGINAL sender, not on whichever
        # honest forwarder's frame won a thread race. The gate returns
        # (forward, decoded); `decoded` rides into the local delivery
        # so gate + deliver share ONE decode per message.
        self.forward_gate = forward_gate
        self.deliver = None  # set by join()
        self.local_topics: set[str] = set()
        self.peers: dict[str, _PeerConn] = {}
        # Gossip message-id dedup: two rotating generations so the cache
        # is bounded for the life of the process (the reference's
        # gossipsub duplicate cache is time-bounded; size-bounded
        # rotation gives the same no-leak property without a timer
        # thread). Membership = either generation; rotation drops ids
        # older than one full generation.
        self._seen: set[bytes] = set()
        self._seen_prev: set[bytes] = set()
        self._seen_lock = TimedLock("socket_net.seen")
        self._pending: dict[int, tuple[threading.Event, list]] = {}
        self._req_id = 0
        self._req_lock = TimedLock("socket_net.rpc_req")
        self._stopping = False
        self._heartbeat_latch = TimeLatch(30.0)
        # per-topic gossip mesh (gossipsub GRAFT/PRUNE control plane)
        self._mesh: dict[str, set[str]] = {}
        self._mesh_lock = TimedLock("socket_net.mesh")
        self._rng = random.Random(node_id)

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(
            socket.SOL_SOCKET, socket.SO_REUSEADDR, 1
        )
        self._listener.bind((host, 0))
        self._listener.listen(16)
        self.tcp_port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()

        # UDP discovery endpoint
        self._udp = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._udp.bind((host, 0))
        self.udp_port = self._udp.getsockname()[1]
        threading.Thread(target=self._udp_loop, daemon=True).start()
        threading.Thread(target=self._heartbeat_loop, daemon=True).start()

    # -------------------------------------------------- GossipHub surface

    def join(self, node_id: str, deliver):
        self.deliver = deliver
        return self

    def subscribe(self, node_id: str, topic_str: str):
        self.local_topics.add(topic_str)
        body = json.dumps({"topics": [topic_str]}).encode()
        for conn in list(self.peers.values()):
            try:
                _send_frame(conn.sock, conn.lock, KIND_SUB, body)
            except OSError:
                self._drop(conn)

    def unsubscribe(self, node_id: str, topic_str: str):
        self.local_topics.discard(topic_str)

    def publish(self, from_peer: str, topic_str: str, data: bytes) -> int:
        if len(data) > GOSSIP_MAX_SIZE:
            return 0
        mid = message_id(topic_str.encode() + data)
        if self._seen_check_and_add(mid):
            return 0
        return self._fanout(topic_str, data, exclude=None, mid=mid)

    def _seen_check_and_add(self, mid: bytes) -> bool:
        """True if `mid` was already seen; otherwise records it and
        rotates the generations when the current one fills."""
        with self._seen_lock:
            if mid in self._seen or mid in self._seen_prev:
                return True
            self._seen.add(mid)
            if len(self._seen) >= SEEN_CACHE_PER_GENERATION:
                self._seen_prev = self._seen
                self._seen = set()
            return False

    def report(self, peer_id: str, delta: float):
        conn = self.peers.get(peer_id)
        if conn is None:
            return
        conn.score += delta
        if conn.score <= BAN_THRESHOLD:
            self._drop(conn)  # ban == disconnect (peer_manager)

    # ------------------------------------------------------------- dialing

    def connect(self, host: str, port: int):
        """Dial a peer's TCP listener; returns its node_id."""
        sock = socket.create_connection((host, port), timeout=10)
        conn = _PeerConn(sock)
        self._handshake_out(conn)
        threading.Thread(
            target=self._read_loop, args=(conn,), daemon=True
        ).start()
        return conn.node_id

    def rpc_client(self, peer_id: str) -> RpcClientProxy:
        return RpcClientProxy(self, peer_id)

    def _udp_ping(self, host: str, udp_port: int):
        """One PING/PONG exchange; returns the parsed pong or None."""
        ping = json.dumps(
            {
                "op": "ping",
                "node_id": self.node_id,
                "tcp_port": self.tcp_port,
                "udp_port": self.udp_port,
            }
        ).encode()
        # a throwaway socket: the bound listener's recvfrom loop would
        # race us for the pong datagram
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.settimeout(5.0)
        try:
            probe.sendto(ping, (host, udp_port))
            data, _addr = probe.recvfrom(65536)
            return json.loads(data)
        except (OSError, ValueError):
            return None
        finally:
            probe.close()

    def discover(
        self, host: str, udp_port: int, max_hops: int = 3,
        max_peers: int = 32,
    ):
        """Breadth-first multi-hop discovery from a bootstrap address
        (discv5's iterative FINDNODE/NODES, discovery/mod.rs): ping the
        frontier, learn each pong's known peers, dial every new TCP
        listener, and keep walking until the topology is exhausted,
        `max_hops` rings out, or `max_peers` connections."""
        connected = []
        seen_udp = {(host, udp_port)}
        frontier = [(host, udp_port)]
        # never re-dial peers we already hold a connection to: a
        # duplicate HELLO would replace the peers entry and orphan the
        # old socket + its reader thread
        dialed_tcp = {
            (self.host, c.listen_port)
            for c in list(self.peers.values())
            if c.alive and c.listen_port
        }
        for _hop in range(max_hops):
            if not frontier or len(connected) >= max_peers:
                break
            next_frontier = []
            for ping_host, ping_udp in frontier:
                pong = self._udp_ping(ping_host, ping_udp)
                if pong is None:
                    continue
                entries = [[ping_host, pong.get("tcp_port"), None]]
                for entry in pong.get("known", []):
                    # tolerate both [host, tcp] and [host, tcp, udp]
                    e = list(entry) + [None] * (3 - len(entry))
                    entries.append(e[:3])
                for peer_host, tcp_port, peer_udp in entries:
                    if tcp_port is None:
                        continue
                    key = (peer_host, tcp_port)
                    if key not in dialed_tcp and tcp_port != self.tcp_port:
                        dialed_tcp.add(key)
                        if len(connected) < max_peers:
                            try:
                                connected.append(
                                    self.connect(peer_host, tcp_port)
                                )
                            except OSError:
                                pass
                    if peer_udp and (peer_host, peer_udp) not in seen_udp:
                        seen_udp.add((peer_host, peer_udp))
                        next_frontier.append((peer_host, peer_udp))
            frontier = next_frontier
        return connected

    def close(self):
        self._stopping = True
        for conn in list(self.peers.values()):
            conn.close()
        try:
            self._listener.close()
            self._udp.close()
        except OSError:
            pass

    # ----------------------------------------------------------- internals

    def _hello_body(self) -> bytes:
        return json.dumps(
            {
                "node_id": self.node_id,
                "topics": sorted(self.local_topics),
                "tcp_port": self.tcp_port,
                "udp_port": self.udp_port,
            }
        ).encode()

    def _handshake_out(self, conn: _PeerConn):
        _send_frame(conn.sock, conn.lock, KIND_HELLO, self._hello_body())
        frame = self._read_frame(conn)
        if frame is None or frame[0] != KIND_HELLO:
            conn.close()
            raise OSError("handshake failed")
        self._apply_hello(conn, frame[1])

    def _apply_hello(self, conn: _PeerConn, body: bytes):
        doc = json.loads(body)
        conn.node_id = doc["node_id"]
        conn.topics.update(doc.get("topics", []))
        conn.listen_port = doc.get("tcp_port")
        conn.udp_port = doc.get("udp_port")
        self.peers[conn.node_id] = conn
        if self.on_peer_connected is not None:
            self.on_peer_connected(conn.node_id)

    def _accept_loop(self):
        while not self._stopping:
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            conn = _PeerConn(sock)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: _PeerConn):
        frame = self._read_frame(conn)
        if frame is None or frame[0] != KIND_HELLO:
            conn.close()
            return
        self._apply_hello(conn, frame[1])
        _send_frame(conn.sock, conn.lock, KIND_HELLO, self._hello_body())
        self._read_loop(conn)

    def _read_frame(self, conn: _PeerConn):
        header = _recv_exact(conn.sock, 5)
        if header is None:
            return None
        length, kind = struct.unpack("<IB", header)
        body = _recv_exact(conn.sock, length - 1)
        if body is None:
            return None
        return kind, body

    def _read_loop(self, conn: _PeerConn):
        try:
            while conn.alive:
                frame = self._read_frame(conn)
                if frame is None:
                    break
                self._handle_frame(conn, *frame)
        except OSError:
            pass
        finally:
            self._drop(conn)

    def _handle_frame(self, conn: _PeerConn, kind: int, body: bytes):
        if kind == KIND_GOSSIP:
            (tlen,) = struct.unpack_from("<H", body)
            topic_str = body[2 : 2 + tlen].decode()
            payload = body[2 + tlen :]
            mid = message_id(topic_str.encode() + payload)
            if self._seen_check_and_add(mid):
                return
            # the gate runs FIRST and returns (forward, decoded): an
            # invalid message is not propagated (gossipsub's validate-
            # before-forward contract), and whatever the gate decoded
            # is threaded into this message's local delivery — each
            # message is decoded exactly once per node
            forward, decoded = True, None
            if self.forward_gate is not None:
                forward, decoded = self.forward_gate(topic_str, payload)
            if topic_str in self.local_topics and self.deliver is not None:
                if decoded is None:
                    # legacy 3-arg deliver callbacks (tests, external
                    # consumers) keep working when the gate decoded
                    # nothing — the common case for every non-sidecar
                    # topic
                    self.deliver(topic_str, payload, conn.node_id)
                else:
                    self.deliver(
                        topic_str, payload, conn.node_id, decoded
                    )
            if forward:
                self._fanout(
                    topic_str, payload, exclude=conn.node_id, mid=mid
                )
        elif kind == KIND_SUB:
            conn.topics.update(json.loads(body).get("topics", []))
        elif kind == KIND_GRAFT:
            self._handle_graft(conn, json.loads(body).get("topics", []))
        elif kind == KIND_PRUNE:
            self._handle_prune(conn, json.loads(body).get("topics", []))
        elif kind == KIND_RPC_REQ:
            threading.Thread(
                target=self._serve_rpc,
                args=(conn, body),
                daemon=True,
            ).start()
        elif kind == KIND_RPC_RSP:
            (req_id,) = struct.unpack_from("<I", body)
            status = body[4]
            chunks, pos = [], 5
            while pos + 4 <= len(body):
                (clen,) = struct.unpack_from("<I", body, pos)
                chunks.append(body[pos + 4 : pos + 4 + clen])
                pos += 4 + clen
            waiter = self._pending.pop(req_id, None)
            if waiter is not None:
                event, out = waiter
                out.append((status, chunks))
                event.set()

    def _fanout(
        self, topic_str: str, payload: bytes, exclude, mid: bytes = None
    ) -> int:
        body = (
            struct.pack("<H", len(topic_str))
            + topic_str.encode()
            + payload
        )
        with self._mesh_lock:
            mesh = set(self._mesh.get(topic_str, ()))
        mesh.discard(exclude)
        use_mesh = self.mesh_enabled and len(mesh) >= MESH_D_LO
        sent = 0
        for conn in list(self.peers.values()):
            if not conn.alive or conn.node_id == exclude:
                continue
            if topic_str not in conn.topics:
                continue
            # mesh-formed: forward along mesh links only; pre-mesh
            # bootstrap: flood to every interested peer so delivery
            # never stalls while grafting catches up
            if use_mesh and conn.node_id not in mesh:
                continue
            try:
                if self._conditioned_send(conn, KIND_GOSSIP, body, mid):
                    sent += 1
            except OSError:
                self._drop(conn)
        return sent

    def _conditioned_send(
        self, conn: _PeerConn, kind: int, body: bytes, mid
    ) -> bool:
        """Send one gossip frame through the conditioner (when present):
        the per-(src, dst, message-id) plan decides copies (0 = drop,
        2 = duplicate) and a hold count (deliver only after that many
        LATER frames pass on this pair — delay/reorder without wall
        clocks). Decisions key on the message id, not a call counter, so
        thread interleaving between pairs cannot shift the fault
        sequence — the same (seed, pair, message) always gets the same
        fate."""
        cnd = self.conditioner
        if cnd is None or mid is None:
            _send_frame(conn.sock, conn.lock, kind, body)
            return True
        plan = cnd.plan_gossip(
            self.node_id, conn.node_id, mid, size=len(body)
        )
        sent = False
        ready = []
        with conn.held_lock:
            # age PRE-EXISTING holds by this send opportunity first —
            # a frame held in THIS call must wait for LATER frames,
            # not release against itself
            still = []
            for item in conn.held:
                item[0] -= 1
                if item[0] <= 0:
                    ready.append((item[1], item[2]))
                else:
                    still.append(item)
            conn.held = still
            if plan.copies:
                for _ in range(plan.copies):
                    if plan.hold > 0:
                        conn.held.append([plan.hold, kind, body])
                    else:
                        ready.append((kind, body))
        for k, b in ready:
            _send_frame(conn.sock, conn.lock, k, b)
            sent = True
        return sent

    def flush_conditioned(self) -> int:
        """Force-deliver every held (delayed/reordered) frame — the
        simulator calls this at its slot barrier so a held frame never
        dangles past the step that produced it. Returns the number of
        frames released."""
        flushed = 0
        for conn in list(self.peers.values()):
            if not conn.alive:
                continue
            with conn.held_lock:
                ready = [(k, b) for _, k, b in conn.held]
                conn.held = []
            try:
                for k, b in ready:
                    _send_frame(conn.sock, conn.lock, k, b)
                    flushed += 1
            except OSError:
                self._drop(conn)
        return flushed

    # ------------------------------------------------------------ mesh

    def mesh_peers(self, topic_str: str) -> set:
        with self._mesh_lock:
            return set(self._mesh.get(topic_str, ()))

    def _heartbeat_loop(self):
        while not self._stopping:
            time.sleep(HEARTBEAT_INTERVAL)
            if not self.mesh_enabled:
                continue
            try:
                self._maintain_mesh()
            except Exception as e:
                # the heartbeat must survive transient peer churn —
                # visibly: a REPEATING failure here means the mesh is
                # not being maintained, so it warns (rate-latched)
                if self._heartbeat_latch.elapsed():
                    _LOG.warning(
                        "heartbeat mesh maintenance failing: %s", e
                    )

    def _maintain_mesh(self):
        """Gossipsub heartbeat: graft under-degree topics up toward D,
        prune over-degree ones down from D_hi."""
        for topic in list(self.local_topics):
            interested = {
                pid
                for pid, c in list(self.peers.items())
                if c.alive and topic in c.topics
            }
            graft_to, prune_from = [], []
            with self._mesh_lock:
                mesh = self._mesh.setdefault(topic, set())
                mesh &= interested  # forget dead/unsubscribed peers
                if len(mesh) < MESH_D:
                    candidates = list(interested - mesh)
                    self._rng.shuffle(candidates)
                    take = candidates[: MESH_D - len(mesh)]
                    mesh.update(take)
                    graft_to = take
                elif len(mesh) > MESH_D_HI:
                    extras = list(mesh)
                    self._rng.shuffle(extras)
                    prune_from = extras[: len(mesh) - MESH_D]
                    mesh.difference_update(prune_from)
            for pid in graft_to:
                self._send_control(pid, KIND_GRAFT, topic)
            for pid in prune_from:
                self._send_control(pid, KIND_PRUNE, topic)

    def _send_control(self, peer_id: str, kind: int, topic: str):
        conn = self.peers.get(peer_id)
        if conn is None or not conn.alive:
            return
        try:
            _send_frame(
                conn.sock,
                conn.lock,
                kind,
                json.dumps({"topics": [topic]}).encode(),
            )
        except OSError:
            self._drop(conn)

    def _handle_graft(self, conn: _PeerConn, topics):
        for topic in topics:
            if topic not in self.local_topics:
                self._send_control(conn.node_id, KIND_PRUNE, topic)
                continue
            with self._mesh_lock:
                mesh = self._mesh.setdefault(topic, set())
                if len(mesh) >= MESH_D_HI and conn.node_id not in mesh:
                    over = True
                else:
                    mesh.add(conn.node_id)
                    over = False
            if over:
                self._send_control(conn.node_id, KIND_PRUNE, topic)

    def _handle_prune(self, conn: _PeerConn, topics):
        with self._mesh_lock:
            for topic in topics:
                self._mesh.get(topic, set()).discard(conn.node_id)

    # ---------------------------------------------------------------- rpc

    def _rpc_call(self, peer_id, method, payload, timeout):
        conn = self.peers.get(peer_id)
        if conn is None or not conn.alive:
            raise RpcError(2, f"peer {peer_id} not connected")
        if self.conditioner is not None:
            # partition masks read as unreachability (the wire timeout
            # shape, immediately — no real waiting); seeded per-pair
            # stalls ride the same check
            self.conditioner.check_rpc(self.node_id, peer_id, method)
        with self._req_lock:
            self._req_id += 1
            req_id = self._req_id
        event, out = threading.Event(), []
        self._pending[req_id] = (event, out)
        body = (
            struct.pack("<IB", req_id, len(method))
            + method.encode()
            + payload
        )
        _send_frame(conn.sock, conn.lock, KIND_RPC_REQ, body)
        if not event.wait(timeout):
            self._pending.pop(req_id, None)
            raise RpcError(2, f"rpc {method} timed out")
        status, chunks = out[0]
        if status == RPC_STATUS_RATE_LIMITED:
            raise RateLimitExceeded
        if status != 0:
            raise RpcError(status, chunks[0].decode() if chunks else "")
        return chunks

    def _serve_rpc(self, conn: _PeerConn, body: bytes):
        (req_id,) = struct.unpack_from("<I", body)
        mlen = body[4]
        method = body[5 : 5 + mlen].decode()
        payload = body[5 + mlen :]
        try:
            chunks = self._dispatch_rpc(conn.node_id, method, payload)
            status = 0
        except RateLimitExceeded:
            status, chunks = RPC_STATUS_RATE_LIMITED, [b"rate limited"]
        except RpcError as e:
            status, chunks = e.args[0] or 1, [str(e.args[1]).encode()]
        except Exception as e:
            status, chunks = 1, [str(e).encode()]
        resp = struct.pack("<IB", req_id, status) + b"".join(
            struct.pack("<I", len(c)) + c for c in chunks
        )
        try:
            _send_frame(conn.sock, conn.lock, KIND_RPC_RSP, resp)
        except OSError:
            self._drop(conn)

    def _dispatch_rpc(self, peer_id, method, payload):
        srv = self.rpc_server
        if srv is None:
            raise RpcError(1, "no rpc server")
        if method == "status":
            return [frame_compress(srv.status(peer_id).to_bytes())]
        if method == "ping":
            data = Ping.decode(frame_decompress(payload)).data
            return [
                frame_compress(
                    Ping(data=srv.ping(peer_id, data)).to_bytes()
                )
            ]
        if method == "metadata":
            return [frame_compress(srv.metadata(peer_id).to_bytes())]
        if method == "blocks_by_range":
            req = BlocksByRangeRequest.decode(frame_decompress(payload))
            blocks = srv.blocks_by_range(peer_id, req)
            return [self._encode_block(b) for b in blocks]
        if method == "blocks_by_root":
            raw = frame_decompress(payload)
            roots = [raw[i : i + 32] for i in range(0, len(raw), 32)]
            blocks = srv.blocks_by_root(peer_id, roots)
            return [self._encode_block(b) for b in blocks]
        if method == "goodbye":
            reason = Goodbye.decode(frame_decompress(payload)).reason
            srv.goodbye(peer_id, int(reason))
            return []
        if method == "blob_sidecars_by_range":
            req = BlobSidecarsByRangeRequest.decode(
                frame_decompress(payload)
            )
            sidecars = srv.blob_sidecars_by_range(peer_id, req)
            return [frame_compress(sc.to_bytes()) for sc in sidecars]
        if method == "blob_sidecars_by_root":
            req = BlobSidecarsByRootRequest.decode(
                frame_decompress(payload)
            )
            sidecars = srv.blob_sidecars_by_root(
                peer_id, req.identifiers
            )
            return [frame_compress(sc.to_bytes()) for sc in sidecars]
        if method == "data_column_sidecars_by_root":
            req = DataColumnSidecarsByRootRequest.decode(
                frame_decompress(payload)
            )
            sidecars = srv.data_column_sidecars_by_root(
                peer_id, req.identifiers
            )
            return [frame_compress(sc.to_bytes()) for sc in sidecars]
        raise RpcError(1, f"unknown method {method}")

    def _encode_block(self, signed_block) -> bytes:
        fork = self.spec.fork_name_at_epoch(
            self.spec.slot_to_epoch(signed_block.message.slot)
        )
        return bytes([FORK_ORDER.index(fork)]) + frame_compress(
            signed_block.to_bytes()
        )

    def _decode_block(self, chunk: bytes):
        fork = FORK_ORDER[chunk[0]]
        cls = self.t.signed_block_classes[fork]
        return cls.decode(frame_decompress(chunk[1:]))

    def _drop(self, conn: _PeerConn):
        conn.close()
        if conn.node_id and self.peers.get(conn.node_id) is conn:
            del self.peers[conn.node_id]
            with self._mesh_lock:
                for mesh in self._mesh.values():
                    mesh.discard(conn.node_id)
            if self.on_peer_disconnected is not None:
                try:
                    self.on_peer_disconnected(conn.node_id)
                except Exception as e:
                    # the disconnect hook must not break the read loop
                    _LOG.warning(
                        "on_peer_disconnected(%s) failed: %s",
                        conn.node_id, e,
                    )

    # ---------------------------------------------------------- discovery

    def _udp_loop(self):
        while not self._stopping:
            try:
                data, addr = self._udp.recvfrom(65536)
            except OSError:
                return
            try:
                doc = json.loads(data)
            except ValueError:
                continue
            if doc.get("op") == "ping":
                # advertise peers by the LISTEN ports learned in HELLO
                # ([host, tcp, udp] — udp lets the pinger keep walking)
                known = [
                    [self.host, c.listen_port, c.udp_port]
                    for c in list(self.peers.values())
                    if c.alive and c.listen_port
                ]
                pong = json.dumps(
                    {
                        "op": "pong",
                        "node_id": self.node_id,
                        "tcp_port": self.tcp_port,
                        "known": known,
                    }
                ).encode()
                try:
                    self._udp.sendto(pong, addr)
                except OSError:
                    pass

