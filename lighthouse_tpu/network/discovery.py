"""Peer discovery: bootstrap registry + peer records.

Role of the reference's discv5 integration (lighthouse_network/src/
discovery/mod.rs, boot_node crate): nodes register ENR-like records with a
bootstrap registry and query it for peers matching subnet predicates. The
transport-level Kademlia DHT of discv5 is out of scope for the in-process
topology; this preserves the discovery SURFACE (records, queries, subnet
predicates, liveness) so node wiring and tests exercise the same flow.
"""

import time
from dataclasses import dataclass, field


@dataclass
class PeerRecord:
    node_id: str
    seq: int = 1
    attnets: list = field(default_factory=lambda: [False] * 64)
    last_seen: float = field(default_factory=time.monotonic)

    def matches_subnets(self, subnets) -> bool:
        return any(self.attnets[s] for s in subnets)


class BootstrapRegistry:
    """The boot node: holds peer records, answers queries."""

    def __init__(self, liveness_timeout: float = 300.0):
        self.records: dict[str, PeerRecord] = {}
        self.liveness_timeout = liveness_timeout

    def register(self, record: PeerRecord):
        existing = self.records.get(record.node_id)
        if existing is None or record.seq > existing.seq:
            record.last_seen = time.monotonic()
            self.records[record.node_id] = record

    def refresh(self, node_id: str):
        rec = self.records.get(node_id)
        if rec:
            rec.last_seen = time.monotonic()

    def _alive(self):
        cutoff = time.monotonic() - self.liveness_timeout
        return [r for r in self.records.values() if r.last_seen >= cutoff]

    def find_peers(self, exclude: str, limit: int = 16):
        return [r for r in self._alive() if r.node_id != exclude][:limit]

    def find_subnet_peers(self, subnets, exclude: str, limit: int = 16):
        """Subnet-predicate peer search (discovery/mod.rs subnet
        queries)."""
        return [
            r
            for r in self._alive()
            if r.node_id != exclude and r.matches_subnets(subnets)
        ][:limit]
