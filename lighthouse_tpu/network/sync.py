"""Sync manager: range sync from peers ahead of us, parent lookups.

Role of the reference's `SyncManager` (network/src/sync/manager.rs:1-34):
peer Status reveals a distant finalized/head slot; range sync pulls
`BlocksByRange` batches (EPOCHS_PER_BATCH epochs per request, per-peer
chains) and feeds them through `process_chain_segment` (one bulk signature
batch per segment — the device-friendly path); single-block parent lookups
resolve unknown-parent gossip blocks via `BlocksByRoot`.
"""

EPOCHS_PER_BATCH = 2


class SyncManager:
    def __init__(self, chain, spec):
        self.chain = chain
        self.spec = spec
        self.peers: dict[str, object] = {}  # peer_id -> RpcServer handle
        self.metrics = {"batches": 0, "blocks_synced": 0}

    def add_peer(self, peer_id: str, rpc_server):
        self.peers.setdefault(peer_id, rpc_server)

    def remove_peer(self, peer_id: str):
        self.peers.pop(peer_id, None)

    def _best_peer(self):
        best, best_slot = None, -1
        for pid, rpc in self.peers.items():
            try:
                st = rpc.status(self.chain.genesis_root.hex()[:8])
                if st.head_slot > best_slot:
                    best, best_slot = (pid, rpc), st.head_slot
            except Exception:
                continue
        return best, best_slot

    def run_range_sync(self, max_batches: int = 64) -> int:
        """Pull batches until caught up with the best peer. Returns blocks
        imported."""
        from lighthouse_tpu.network.rpc import BlocksByRangeRequest

        imported = 0
        batch_slots = EPOCHS_PER_BATCH * self.spec.SLOTS_PER_EPOCH
        for _ in range(max_batches):
            best, best_slot = self._best_peer()
            if best is None or best_slot <= self.chain.head_state.slot:
                break
            pid, rpc = best
            start = self.chain.head_state.slot + 1
            req = BlocksByRangeRequest(
                start_slot=start, count=batch_slots, step=1
            )
            blocks = rpc.blocks_by_range(
                self.chain.genesis_root.hex()[:8], req
            )
            if not blocks:
                break
            roots = self.chain.process_chain_segment(blocks)
            imported += len(roots)
            self.metrics["batches"] += 1
            self.metrics["blocks_synced"] += len(roots)
        return imported

    def run_backfill(self, batch_slots: int | None = None) -> int:
        """Backfill history behind a checkpoint anchor
        (network/src/sync/backfill_sync/mod.rs): fetch blocks BACKWARDS
        from the anchor, verify the parent-root hash chain plus one bulk
        proposer-signature batch per batch (no state transitions), and
        store them."""
        from lighthouse_tpu import bls
        from lighthouse_tpu.network.rpc import BlocksByRangeRequest
        from lighthouse_tpu.state_processing import signature_sets as ss

        anchor = getattr(self.chain, "anchor_slot", None)
        if not anchor:
            return 0
        batch_slots = batch_slots or (
            EPOCHS_PER_BATCH * self.spec.SLOTS_PER_EPOCH
        )
        stored = 0
        # expected parent of the lowest block we hold
        lowest = self.chain.store.get_canonical_block_root(anchor)
        expected_parent = bytes(
            self.chain.store.get_block(lowest).message.parent_root
        )
        next_end = anchor  # exclusive
        while next_end > 1:
            start = max(1, next_end - batch_slots)
            best, _ = self._best_peer()
            if best is None:
                break
            _, rpc = best
            req = BlocksByRangeRequest(
                start_slot=start, count=next_end - start, step=1
            )
            blocks = rpc.blocks_by_range(
                self.chain.genesis_root.hex()[:8], req
            )
            if not blocks:
                break
            state = self.chain.head_state
            self.chain.pubkey_cache.import_new(state)
            sets = []
            for sb in blocks:
                sets.append(
                    ss.block_proposal_set(
                        state, sb, self.chain.pubkey_cache.get, self.spec
                    )
                )
            if not bls.verify_signature_sets(
                sets, backend=self.chain.backend
            ):
                break
            # hash-chain check backwards
            ok = True
            for sb in reversed(blocks):
                root = type(sb.message).hash_tree_root(sb.message)
                if root != expected_parent:
                    ok = False
                    break
                self.chain.store.put_block(root, sb)
                self.chain.store.set_canonical_block_root(
                    sb.message.slot, root
                )
                expected_parent = bytes(sb.message.parent_root)
                stored += 1
            if not ok:
                break
            next_end = start
        return stored

    def lookup_parent(self, parent_root: bytes) -> bool:
        """Single-block lookup for an unknown parent (block_lookups/)."""
        for pid, rpc in self.peers.items():
            try:
                blocks = rpc.blocks_by_root(
                    self.chain.genesis_root.hex()[:8], [parent_root]
                )
            except Exception:
                continue
            if blocks:
                try:
                    self.chain.process_block(blocks[0])
                    return True
                except Exception:
                    return False
        return False
