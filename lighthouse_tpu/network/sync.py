"""Fault-tolerant sync manager: range sync, backfill, parent lookups.

Role of the reference's `SyncManager` (network/src/sync/manager.rs:1-34)
plus the batch retry discipline of range_sync/batch.rs: peer Status
reveals a distant finalized/head slot; range sync pulls `BlocksByRange`
batches (EPOCHS_PER_BATCH epochs per request) AND their
`BlobSidecarsByRange` companions, feeds sidecars through the DA checker,
and imports blocks through `process_chain_segment` (one bulk signature
batch per segment — the device-friendly path); single-block parent
lookups resolve unknown-parent gossip blocks via `BlocksByRoot` +
`BlobSidecarsByRoot`.

The req/resp plane is treated as adversarial:

  * range requests (range sync, backfill, completion probes) run
    through one retriable helper (`_fetch`) with per-request timeout
    accounting, capped exponential backoff with DETERMINISTIC jitter
    (seeded per (range, attempt) so chaos runs replay), and rotation
    to a DIFFERENT peer on every attempt; parent lookups iterate
    peers directly (success there means "the block imported", not
    "the response validated") but share the same scoring vocabulary;
  * peer Status is cached with a short TTL so a long sync cannot burn
    its own `status` rate-limit budget, and `RateLimitExceeded` means
    "try the next peer", never "dead peer";
  * malformed responses — out-of-range slots, broken hash chains,
    foreign sidecars, lying advertisers — downscore the serving peer
    through the gossip hub and quarantine it for the rest of the run;
  * a failed batch re-queues the range (bounded) instead of aborting
    the sync loop, and an empty usable-peer set forgives the
    quarantine once per run before giving up (graceful degradation).
"""

import random
import time

from lighthouse_tpu.common.events_journal import JOURNAL
from lighthouse_tpu.common.logging import get_logger
from lighthouse_tpu.common.metrics import REGISTRY, RegistryBackedMetrics
from lighthouse_tpu.common.tracing import span
from lighthouse_tpu.network.gossip import (
    SCORE_INVALID_MESSAGE,
    SCORE_TIMEOUT,
    SCORE_VALID,
)
from lighthouse_tpu.network.rpc import (
    MAX_REQUEST_BLOB_SIDECARS,
    BlobIdentifier,
    BlobSidecarsByRangeRequest,
    BlocksByRangeRequest,
    DataColumnIdentifier,
    RateLimitExceeded,
    RpcError,
)

_LOG = get_logger("sync")

EPOCHS_PER_BATCH = 2
# peer Status cache TTL: well under the 15 s status-bucket window, so a
# sync loop re-checks heads often enough to notice progress but never
# polls one peer more than ~2x per bucket refill
STATUS_TTL_SECONDS = 6.0
MAX_ATTEMPTS_PER_REQUEST = 4  # distinct peers tried per request
MAX_REQUEUES_PER_RANGE = 3  # failed-batch re-queues before giving up
MAX_PARENT_CHAIN_DEPTH = 32  # ancestor-walk bound for parent lookups
MAX_RATE_LIMIT_STRIKES = 3  # consecutive rate-limit answers -> quarantine
BACKOFF_BASE_SECONDS = 0.02
BACKOFF_CAP_SECONDS = 1.0
REQUEST_TIMEOUT_SECONDS = 5.0

# validation verdicts that are SUSPICIOUS but not provably malicious —
# they rotate the peer score-free instead of quarantining it
SOFT_VALIDATION_REASONS = {
    "empty_range_from_advertising_peer",
    "uncovering_sidecar_response",
}
# a cached status may serve as a fallback when a refresh fails, but only
# this long — past it the peer is treated as unreachable, so a crashed
# peer cannot pin its last advertised head in the usable set forever
STATUS_STALE_MAX_SECONDS = 30.0

_BATCHES = REGISTRY.counter_vec(
    "lighthouse_tpu_sync_batches_total",
    "range-sync batches, by outcome (imported|requeued|abandoned|empty)",
    ("outcome",),
)
_RETRIES = REGISTRY.counter(
    "lighthouse_tpu_sync_batch_retries_total",
    "req/resp attempts beyond the first, across all sync request kinds",
)
_REQUEST_ERRORS = REGISTRY.counter_vec(
    "lighthouse_tpu_sync_request_errors_total",
    "req/resp client failures seen by the sync manager "
    "(kind: timeout|rate_limited|error|malformed)",
    ("method", "kind"),
)
_DOWNSCORES = REGISTRY.counter_vec(
    "lighthouse_tpu_sync_peer_downscores_total",
    "peer downscores issued by the sync manager, by reason",
    ("reason",),
)
_BACKOFF_SECONDS = REGISTRY.counter(
    "lighthouse_tpu_sync_backoff_seconds_total",
    "total backoff delay requested between sync retries",
)
_BLOCKS_SYNCED = REGISTRY.counter(
    "lighthouse_tpu_sync_blocks_synced_total",
    "blocks imported via range sync",
)
_SIDECARS_FETCHED = REGISTRY.counter(
    "lighthouse_tpu_sync_sidecars_fetched_total",
    "blob sidecars fetched over req/resp and routed into the DA checker",
)
_QUARANTINED = REGISTRY.gauge(
    "lighthouse_tpu_sync_quarantined_peers",
    "peers currently quarantined by the sync manager",
)
_QUARANTINE_RESETS = REGISTRY.counter(
    "lighthouse_tpu_sync_quarantine_resets_total",
    "times an empty usable-peer set forgave the quarantine to keep "
    "syncing (graceful degradation)",
)


class SyncManager:
    def __init__(
        self,
        chain,
        spec,
        hub=None,
        rng_seed=0,
        sleep=None,
        local_peer_id=None,
    ):
        self.chain = chain
        self.spec = spec
        # gossip hub (or SocketNet) for peer scoring; None = scoreless
        self.hub = hub
        # how this node identifies itself to serving peers — their rate
        # limiter buckets key on it, so it must be per-NODE (two nodes
        # sharing an id would drain each other's budgets)
        self.local_peer_id = local_peer_id
        self.peers: dict[str, object] = {}  # peer_id -> RpcServer handle
        self.quarantined: set[str] = set()
        # the node's lifecycle journal (chain-owned, per node): every
        # request attempt, batch outcome, downscore, and quarantine
        # lands there with peer attribution
        self.journal = getattr(chain, "journal", None) or JOURNAL
        # dict-compatible view mirrored onto lighthouse_tpu_sync_client_*
        # registry gauges (the PR 5 deferred note, now fully closed):
        # EVERY sync-internal number — progress counters AND the
        # previously hand-rolled peer-view gauges (usable peers,
        # quarantine size, live rate-limit strikes, status-cache
        # occupancy) — rides this one view, so sync internals, /metrics
        # scrapes, and registry snapshots read the same numbers; the
        # sync_* counter families above stay the cross-peer totals
        self.metrics = RegistryBackedMetrics(
            "lighthouse_tpu_sync_client_",
            initial={
                "batches": 0,
                "blocks_synced": 0,
                "retries": 0,
                "requeues": 0,
                "sidecars_fetched": 0,
                "peers": 0,
                "quarantined": 0,
                "rl_strikes_active": 0,
                "status_cache_entries": 0,
            },
        )
        self.request_timeout = REQUEST_TIMEOUT_SECONDS
        self._status_cache: dict[str, tuple] = {}  # pid -> (status, t)
        self._rl_strikes: dict[str, int] = {}
        self._rng_seed = rng_seed
        self._sleep = sleep if sleep is not None else time.sleep
        self._last_sidecar_peer = None

    # -------------------------------------------------------------- peers

    def _refresh_peer_gauges(self):
        """Mirror the peer-view internals onto the registry-backed view
        so /metrics carries them (the PR 5 deferred-note closure)."""
        self.metrics["peers"] = len(self.peers)
        self.metrics["quarantined"] = len(self.quarantined)
        self.metrics["rl_strikes_active"] = len(self._rl_strikes)
        self.metrics["status_cache_entries"] = len(self._status_cache)

    def add_peer(self, peer_id: str, rpc_server):
        self.peers.setdefault(peer_id, rpc_server)
        self.quarantined.discard(peer_id)
        _QUARANTINED.set(len(self.quarantined))
        self._refresh_peer_gauges()

    def remove_peer(self, peer_id: str):
        self.peers.pop(peer_id, None)
        self.quarantined.discard(peer_id)
        self._status_cache.pop(peer_id, None)
        self._rl_strikes.pop(peer_id, None)
        _QUARANTINED.set(len(self.quarantined))
        self._refresh_peer_gauges()

    def disconnect(self, peer_id: str, reason: int = 1):
        """Clean client-side disconnect: send `goodbye`, drop the peer."""
        rpc = self.peers.get(peer_id)
        if rpc is not None:
            try:
                rpc.goodbye(self._caller(), reason)
            except Exception as e:
                # best-effort farewell: the peer is going away anyway
                _LOG.debug("goodbye to %s failed: %s", peer_id, e)
        self.remove_peer(peer_id)

    def _caller(self) -> str:
        if self.local_peer_id is not None:
            return self.local_peer_id
        return self.chain.genesis_root.hex()[:8]

    def _downscore(self, peer_id: str, delta: float, reason: str):
        _DOWNSCORES.labels(reason).inc()
        self.journal.emit(
            "peer_downscore", peer=peer_id, outcome=reason, delta=delta
        )
        if self.hub is not None:
            try:
                self.hub.report(peer_id, delta)
            except Exception as e:
                # the score still counts locally; a hub glitch must not
                # break the sync path — but it must be visible
                _LOG.warning(
                    "hub.report(%s, %s) failed: %s", peer_id, delta, e
                )

    def _quarantine(self, peer_id: str, reason: str):
        self._downscore(peer_id, SCORE_INVALID_MESSAGE, reason)
        self.quarantined.add(peer_id)
        self.journal.emit(
            "peer_quarantine", peer=peer_id, outcome=reason
        )
        _QUARANTINED.set(len(self.quarantined))
        self._refresh_peer_gauges()

    def _peer_status(self, peer_id: str, rpc):
        """Cached Status with a short TTL. RateLimitExceeded falls back
        to the stale cache (our own polling budget, not a dead peer)."""
        now = time.monotonic()
        cached = self._status_cache.get(peer_id)
        if cached is not None and now - cached[1] <= STATUS_TTL_SECONDS:
            return cached[0]
        try:
            st = rpc.status(self._caller())
        except RateLimitExceeded:
            _REQUEST_ERRORS.labels("status", "rate_limited").inc()
            return self._stale_status(peer_id, now)
        except Exception:
            _REQUEST_ERRORS.labels("status", "error").inc()
            return self._stale_status(peer_id, now)
        self._status_cache[peer_id] = (st, now)
        self._refresh_peer_gauges()
        return st

    def _stale_status(self, peer_id: str, now: float):
        """Bounded stale fallback: a failed refresh may reuse the last
        status for STATUS_STALE_MAX_SECONDS; beyond that the entry is
        dropped and the peer reads unreachable."""
        cached = self._status_cache.get(peer_id)
        if cached is not None and now - cached[1] <= (
            STATUS_STALE_MAX_SECONDS
        ):
            return cached[0]
        self._status_cache.pop(peer_id, None)
        self._refresh_peer_gauges()
        return None

    def _usable_peers(self):
        """[(peer_id, rpc, head_slot)] sorted best-head-first, skipping
        quarantined peers and peers with no reachable status."""
        out = []
        for pid, rpc in self.peers.items():
            if pid in self.quarantined:
                continue
            st = self._peer_status(pid, rpc)
            if st is None:
                continue
            out.append((pid, rpc, int(st.head_slot)))
        out.sort(key=lambda x: -x[2])
        return out

    def _best_peer(self):
        peers = self._usable_peers()
        if not peers:
            return None, -1
        pid, rpc, head_slot = peers[0]
        return (pid, rpc), head_slot

    # ----------------------------------------------------- retriable unit

    def _backoff(self, key: str, attempt: int):
        """Capped exponential backoff with deterministic jitter: the
        delay for (seed, key, attempt) is a pure function, so a chaos
        run replays exactly from its seed."""
        rng = random.Random(f"{self._rng_seed}:{key}:{attempt}")
        delay = min(
            BACKOFF_CAP_SECONDS, BACKOFF_BASE_SECONDS * (2**attempt)
        )
        delay *= 0.5 + rng.random()  # jitter in [0.5, 1.5)
        _BACKOFF_SECONDS.inc(delay)
        self._sleep(delay)

    def _fetch(self, method: str, key: str, call, validate=None,
               prefer=None, min_head=None, stats=None):
        """One retriable req/resp unit: try up to
        MAX_ATTEMPTS_PER_REQUEST DISTINCT peers (best cached head first,
        `prefer` before all), backing off between attempts. `min_head`
        excludes peers whose advertised head is below it — an empty
        reply is only authoritative from a peer that claims to HAVE the
        range, so behind-peers must not serve (and thereby end) a range
        request. `call(pid, rpc)` runs the request; `validate(result,
        peer_head)` returns an error reason or None — a malformed
        response downscores AND (unless the reason is soft) quarantines
        the serving peer. Returns (peer_id, result) or (None, None)
        when every attempt failed."""
        tried: set[str] = set()
        for attempt in range(MAX_ATTEMPTS_PER_REQUEST):
            candidates = [
                c
                for c in self._usable_peers()
                if c[0] not in tried
                and (min_head is None or c[2] >= min_head)
            ]
            if prefer is not None:
                candidates.sort(key=lambda c: c[0] != prefer)
            if not candidates:
                break
            pid, rpc, peer_head = candidates[0]
            tried.add(pid)
            if stats is not None:
                stats["attempts"] = stats.get("attempts", 0) + 1
            if attempt:
                _RETRIES.inc()
                self.metrics["retries"] += 1
                self._backoff(key, attempt)
            t0 = time.monotonic()

            def _req_event(outcome, **attrs):
                self.journal.emit(
                    "sync_request",
                    peer=pid,
                    outcome=outcome,
                    duration_s=time.monotonic() - t0,
                    method=method,
                    attempt=attempt,
                    **attrs,
                )

            try:
                with span(f"sync/{method}", peer=pid, attempt=attempt):
                    result = call(pid, rpc)
            except RateLimitExceeded:
                # the peer says WE are over budget — usually our own
                # polling; rotate without penalty, but a peer that
                # answers nothing but rate-limits is starving us.
                # The quarantine here is SCORELESS: being over budget
                # is this client's doing, so the peer must not bleed
                # toward the gossip ban threshold for it
                _REQUEST_ERRORS.labels(method, "rate_limited").inc()
                _req_event("rate_limited")
                strikes = self._rl_strikes.get(pid, 0) + 1
                self._rl_strikes[pid] = strikes
                self._refresh_peer_gauges()
                if strikes >= MAX_RATE_LIMIT_STRIKES:
                    _DOWNSCORES.labels("rate_limit_starvation").inc()
                    self.journal.emit(
                        "peer_quarantine",
                        peer=pid,
                        outcome="rate_limit_starvation",
                    )
                    self.quarantined.add(pid)
                    _QUARANTINED.set(len(self.quarantined))
                continue
            except RpcError as e:
                kind = "timeout" if e.code == 2 else "error"
                _REQUEST_ERRORS.labels(method, kind).inc()
                _req_event(kind)
                self._downscore(pid, SCORE_TIMEOUT, kind)
                continue
            except Exception:
                _REQUEST_ERRORS.labels(method, "error").inc()
                _req_event("error")
                self._downscore(pid, SCORE_TIMEOUT, "error")
                continue
            self._rl_strikes.pop(pid, None)
            if time.monotonic() - t0 > self.request_timeout:
                # late but present: count the stall, keep the data
                _REQUEST_ERRORS.labels(method, "timeout").inc()
                self._downscore(pid, SCORE_TIMEOUT, "slow_response")
            if validate is not None:
                reason = validate(result, peer_head)
                if reason is not None:
                    _REQUEST_ERRORS.labels(method, "malformed").inc()
                    _req_event("malformed", reason=reason)
                    if reason in SOFT_VALIDATION_REASONS:
                        # not provably malicious (an all-skip-slot range
                        # or pruned history also yields an empty answer
                        # from a high-head peer): rotate to cross-check
                        # against other peers, score-free — the caller
                        # reads `stats` to see whether the answer was
                        # UNANIMOUS across peers
                        if stats is not None:
                            stats["soft"] = stats.get("soft", 0) + 1
                    else:
                        self._quarantine(pid, reason)
                    continue
            _req_event("ok")
            return pid, result
        return None, None

    # ----------------------------------------------------------- range sync

    def run_range_sync(
        self, max_batches: int = 64, batch_slots: int | None = None
    ) -> int:
        """Pull batches until caught up with the best peer, fetching
        missing blob sidecars alongside each batch so blob-committing
        segments import through the DA gate. A failed batch re-queues
        the range against rotated peers instead of ending the sync.
        Returns blocks imported."""
        imported = 0
        batch_slots = batch_slots or (
            EPOCHS_PER_BATCH * self.spec.SLOTS_PER_EPOCH
        )
        # a batch must stay inside the server's sidecar window — the
        # blocks bucket could fund a larger request, but its sidecar
        # companion would be clamped server-side and the truncated DA
        # data would read as withholding
        batch_slots = min(
            batch_slots,
            MAX_REQUEST_BLOB_SIDECARS // self.spec.MAX_BLOBS_PER_BLOCK,
        )
        requeues = 0
        forgiven = False
        # the fetch cursor: normally head+1, but it advances PAST a
        # window every usable peer unanimously reports empty (an
        # all-skip-slot stretch would otherwise pin the sync forever —
        # blocks beyond it still chain to our head, so importing them
        # needs no blocks from the empty window)
        cursor = 0
        for _ in range(max_batches):
            peers = self._usable_peers()
            if not peers and self.quarantined and not forgiven:
                # graceful degradation: everyone is quarantined but the
                # range is not done — forgive ONCE per run rather than
                # stalling forever on our own suspicion (rate-limit
                # strikes reset with it: they describe the budget we
                # ourselves exhausted)
                self.quarantined.clear()
                self._rl_strikes.clear()
                _QUARANTINED.set(0)
                self._refresh_peer_gauges()
                _QUARANTINE_RESETS.inc()
                self.journal.emit("peer_quarantine", outcome="forgiven")
                forgiven = True
                peers = self._usable_peers()
            if not peers:
                break
            target = max(head_slot for _, _, head_slot in peers)
            head = self.chain.head_state.slot
            cursor = max(cursor, head + 1)
            # the TTL-cached target can lag a fast-moving peer by
            # several slots, and the scarce status bucket (5/15 s)
            # cannot fund a fresh poll per batch. So completion is
            # confirmed with a PROBE: one more blocks_by_range past the
            # cursor (the 1024-token blocks bucket is plentiful),
            # ignoring the advertised-head filter. Probes that produce
            # blocks keep pulling; an unproductive probe means done.
            probe = target < cursor
            start = cursor
            count = (
                batch_slots
                if probe
                else min(batch_slots, target - start + 1)
            )
            outcome, n = self._sync_one_batch(start, count, probe=probe)
            _BATCHES.labels(outcome).inc()
            self.journal.emit(
                "sync_batch",
                slot=start,
                outcome=outcome,
                n_blocks=n,
                count=count,
                probe=probe,
            )
            imported += n
            if n > 0:
                # progress — imported fully, or a retriable failure
                # after a prefix landed. Either way keep pulling (in
                # probe mode too: a productive probe proves the peers
                # have more) and reset the no-progress budget
                requeues = 0
                cursor = 0  # restart from the (advanced) head
                if outcome == "imported":
                    self.metrics["batches"] += 1
                else:
                    self.metrics["requeues"] += 1
                continue
            if probe:
                break
            if outcome == "window_empty":
                # every usable peer agrees [start, start+count) holds
                # nothing: step the cursor over the skip window
                cursor = start + count
                continue
            if outcome in ("requeued", "abandoned"):
                # "abandoned" = every peer failed THIS request; the
                # loop-top forgiveness may still rescue the next pass,
                # so both count against the same bounded requeue budget
                self.metrics["requeues"] += 1
                requeues += 1
                if requeues > MAX_REQUEUES_PER_RANGE:
                    break
                cursor = 0  # rewind: the window may have been skipped
                # on a lying peer's word
                continue
            break  # empty: the best advertised range holds no data
        return imported

    def _validate_block_range(self, start: int, count: int):
        def validate(blocks, peer_head):
            if not blocks:
                # a peer advertising a head inside (or past) the range
                # yet serving nothing is lying about one or the other
                if peer_head >= start:
                    return "empty_range_from_advertising_peer"
                return None
            prev_slot = -1
            prev_root = None
            for sb in blocks:
                slot = int(sb.message.slot)
                if slot < start or slot >= start + count:
                    return "slot_out_of_range"
                if slot <= prev_slot:
                    return "unordered_slots"
                if prev_root is not None and (
                    bytes(sb.message.parent_root) != prev_root
                ):
                    return "hash_chain_violation"
                prev_slot = slot
                prev_root = type(sb.message).hash_tree_root(sb.message)
            return None

        return validate

    def _sync_one_batch(self, start: int, count: int, probe: bool = False):
        """Returns (outcome, blocks_imported). `probe` disables the
        advertised-head candidate filter — a completion probe must reach
        peers whose TTL-cached status understates their real head."""
        min_head = None if probe else start
        # suspect tracking is per-batch: a DA failure must never be
        # pinned on a peer that served a PREVIOUS batch's sidecars
        self._last_sidecar_peer = None
        stats: dict = {}
        with span("sync/batch", start=start, count=count, probe=probe):
            pid, blocks = self._fetch(
                "blocks_by_range",
                f"range:{start}",
                lambda p, r: r.blocks_by_range(
                    self._caller(),
                    BlocksByRangeRequest(
                        start_slot=start, count=count, step=1
                    ),
                ),
                validate=self._validate_block_range(start, count),
                min_head=min_head,
                stats=stats,
            )
            if pid is None:
                attempts = stats.get("attempts", 0)
                if attempts and stats.get("soft", 0) == attempts:
                    # every peer that answered says the window is empty
                    # — a unanimous verdict is authoritative (all-skip
                    # slots), a single peer's word is not (see _fetch)
                    return "window_empty", 0
                return "abandoned", 0
            if not blocks:
                return "empty", 0
            if not self._fetch_segment_sidecars(
                blocks, start, count, pid, min_head=min_head
            ):
                return "requeued", 0
            try:
                with span("sync/import_segment", blocks=len(blocks)):
                    roots = self.chain.process_chain_segment(blocks)
            except Exception as e:
                msg = str(e)
                if "data unavailable" in msg:
                    # the sidecar response was incomplete or its blobs
                    # failed KZG at settle time — the sidecar server is
                    # the suspect
                    suspect = self._last_sidecar_peer or pid
                    self._quarantine(suspect, "segment_data_unavailable")
                elif (
                    "parent unknown" not in msg
                    and "unknown parent" not in msg
                ):
                    # the block server handed us an unimportable segment
                    # (signature batch failure, invalid block, ...)
                    self._quarantine(pid, "segment_invalid")
                # an unknown parent — either phrasing: "segment parent
                # unknown" from the segment pre-pass or "unknown parent"
                # from _import_verified mid-apply — is not provably the
                # peer's fault (we may be on the wrong side of a fork):
                # requeue penalty-free; the requeue cap bounds the loop.
                # A mid-segment failure still imported its prefix —
                # count what actually landed (the range always starts
                # above the pre-batch head, so nothing pre-existed)
                landed = sum(
                    1
                    for sb in blocks
                    if self.chain.store.get_block(
                        type(sb.message).hash_tree_root(sb.message)
                    )
                    is not None
                )
                _BLOCKS_SYNCED.inc(landed)
                self.metrics["blocks_synced"] += landed
                return "requeued", landed
            if self.hub is not None and roots:
                self.hub.report(pid, SCORE_VALID)
            _BLOCKS_SYNCED.inc(len(roots))
            self.metrics["blocks_synced"] += len(roots)
            return "imported", len(roots)

    def _fetch_segment_sidecars(
        self,
        blocks,
        start: int,
        count: int,
        block_peer: str,
        min_head=None,
    ) -> bool:
        """Fetch the blob sidecars a segment needs and route them into
        the DA checker ahead of import. Returns False when sidecars are
        needed but unfetchable (the batch must requeue)."""
        da = self.chain.da_checker
        needed: dict[bytes, tuple] = {}
        for sb in blocks:
            if not da.block_commitments(sb):
                continue
            root = type(sb.message).hash_tree_root(sb.message)
            missing = da.missing_indices(root, sb)
            if missing:
                needed[root] = (sb, missing)
        if not needed:
            return True
        if getattr(da, "put_column", None) is not None:
            # column mode: blob sidecars don't exist on this node's
            # wire — pull each block's missing columns by root instead
            return self._fetch_segment_columns(needed, block_peer)

        needed_keys = {
            (root, i)
            for root, (_, missing) in needed.items()
            for i in missing
        }

        def validate(sidecars, peer_head):
            seen = set()
            for sc in sidecars:
                hdr = sc.signed_block_header.message
                slot = int(hdr.slot)
                if slot < start or slot >= start + count:
                    return "sidecar_slot_out_of_range"
                key = (type(hdr).hash_tree_root(hdr), int(sc.index))
                if key in seen:
                    return "duplicate_sidecar"
                seen.add(key)
            if not seen & needed_keys:
                # withholding (or honest blob-pruned history): rotate
                # to another sidecar server BEFORE the segment pays its
                # state transitions + signature batch only to fail the
                # DA gate
                return "uncovering_sidecar_response"
            return None

        pid, sidecars = self._fetch(
            "blob_sidecars_by_range",
            f"sidecars:{start}",
            lambda p, r: r.blob_sidecars_by_range(
                self._caller(),
                BlobSidecarsByRangeRequest(start_slot=start, count=count),
            ),
            validate=validate,
            prefer=block_peer,
            min_head=min_head,
        )
        if pid is None:
            return False
        self._last_sidecar_peer = pid
        # foreign roots are NOT penalized here: a by_range response
        # legitimately includes sidecars for in-range blocks we already
        # hold
        self._ingest_bound_sidecars(pid, sidecars, needed)
        return True

    def _ingest_bound_sidecars(
        self, pid, sidecars, wanted, foreign_reason=None
    ) -> int:
        """Route fetched sidecars into the DA checker under the
        structural binding rule shared by range sync and parent lookup:
        the sidecar's header must carry EXACTLY the served block's
        signature, so the block's own (batch- or import-time) proposal
        check covers the sidecar header with no extra pairing (see
        PERF_NOTES). `wanted` maps block root -> (signed block, wanted
        index set); `foreign_reason` set means a sidecar for any OTHER
        root is a scored offense (by-root requests name exact roots).
        Returns the number ingested."""
        fetched = 0
        for sc in sidecars:
            hdr = sc.signed_block_header.message
            root = type(hdr).hash_tree_root(hdr)
            entry = wanted.get(root)
            if entry is None:
                if foreign_reason is not None:
                    self._downscore(
                        pid, SCORE_INVALID_MESSAGE, foreign_reason
                    )
                continue
            sb, indices = entry
            if int(sc.index) not in indices:
                continue
            if bytes(sc.signed_block_header.signature) != bytes(
                sb.signature
            ):
                self._downscore(
                    pid, SCORE_INVALID_MESSAGE, "sidecar_header_mismatch"
                )
                continue
            try:
                self.chain.process_blob_sidecar(sc, verify_header=False)
                fetched += 1
            except Exception as e:
                # duplicates on a re-queued range are expected; real
                # mismatches surface as DA failures at import
                _LOG.debug("sidecar ingest skipped: %s", e)
        _SIDECARS_FETCHED.inc(fetched)
        self.metrics["sidecars_fetched"] += fetched
        return fetched

    # ------------------------------------------------------------ backfill

    def run_backfill(self, batch_slots: int | None = None) -> int:
        """Backfill history behind a checkpoint anchor
        (network/src/sync/backfill_sync/mod.rs): fetch blocks BACKWARDS
        from the anchor, verify the parent-root hash chain plus one bulk
        proposer-signature batch per batch (no state transitions), and
        store them. Failed batches rotate peers like range sync."""
        from lighthouse_tpu.state_processing import signature_sets as ss

        anchor = getattr(self.chain, "anchor_slot", None)
        if not anchor:
            return 0
        batch_slots = batch_slots or (
            EPOCHS_PER_BATCH * self.spec.SLOTS_PER_EPOCH
        )
        stored = 0
        # expected parent of the lowest block we hold
        lowest = self.chain.store.get_canonical_block_root(anchor)
        expected_parent = bytes(
            self.chain.store.get_block(lowest).message.parent_root
        )
        next_end = anchor  # exclusive
        requeues = 0
        while next_end > 1:
            start = max(1, next_end - batch_slots)
            count = next_end - start
            pid, blocks = self._fetch(
                "blocks_by_range",
                f"backfill:{start}",
                lambda p, r: r.blocks_by_range(
                    self._caller(),
                    BlocksByRangeRequest(
                        start_slot=start, count=count, step=1
                    ),
                ),
                validate=self._validate_block_range(start, count),
                min_head=start,
            )
            if pid is None or not blocks:
                break
            state = self.chain.head_state
            self.chain.pubkey_cache.import_new(state)
            sets = [
                ss.block_proposal_set(
                    state, sb, self.chain.pubkey_cache.get, self.spec
                )
                for sb in blocks
            ]
            ok = self.chain.verification_bus.submit(
                sets,
                consumer="sync_segment",
                backend=self.chain.backend,
                journal=self.journal,
                slot=start,
                journal_attrs={
                    "n_blocks": len(blocks),
                    "backfill": True,
                },
            )
            if ok:
                # hash-chain walk backwards against the known child:
                # validate the WHOLE batch before storing any of it, so
                # a mid-batch break leaves the store untouched and the
                # range retries cleanly against another peer
                exp = expected_parent
                checked = []
                for sb in reversed(blocks):
                    root = type(sb.message).hash_tree_root(sb.message)
                    if root != exp:
                        checked = None
                        break
                    checked.append((root, sb))
                    exp = bytes(sb.message.parent_root)
                if checked is not None:
                    for root, sb in checked:
                        self.chain.store.put_block(root, sb)
                        self.chain.store.set_canonical_block_root(
                            sb.message.slot, root
                        )
                        stored += 1
                    expected_parent = exp
                    next_end = start
                    requeues = 0
                    continue
            # the peer served signature-invalid or chain-breaking blocks:
            # quarantine it and retry the SAME range against another peer
            self._quarantine(pid, "backfill_batch_invalid")
            _BATCHES.labels("requeued").inc()
            self.metrics["requeues"] += 1
            requeues += 1
            if requeues > MAX_REQUEUES_PER_RANGE:
                break
        return stored

    # ------------------------------------------------------ parent lookup

    def lookup_parent(
        self, parent_root: bytes, _depth: int = 0, _failed=None
    ) -> bool:
        """Parent-chain lookup for an unknown parent (block_lookups/):
        fetch the parent by root, and when the parent ITSELF has an
        unknown parent, recurse down the ancestor chain (bounded at
        MAX_PARENT_CHAIN_DEPTH — the reference's parent-lookup chains
        do the same walk) before importing back up. This is how a node
        rejoining after a partition/eclipse adopts the other side's
        branch from one gossip block: the whole fork segment imports
        oldest-first through this walk. Each level fetches that block's
        blob sidecars too when its body commits to blobs — a
        blob-committing ancestor imports through the DA gate from
        req/resp alone. A peer whose returned block fails import is
        downscored, not silently tolerated.

        `_failed` memoizes roots that already failed WITHIN one
        top-level walk: without it, every peer at every depth serving
        the (hash-verified, so identical) block would re-trigger the
        full deeper recursion that just failed — O(peers^depth) RPCs
        from a single old orphan."""
        if _depth >= MAX_PARENT_CHAIN_DEPTH:
            return False
        parent_root = bytes(parent_root)
        if _failed is None:
            _failed = set()
        if parent_root in _failed:
            return False
        da = self.chain.da_checker
        # quarantined peers stay excluded here too — a lookup that
        # cannot be served by any trusted peer fails and retries on the
        # next trigger rather than consulting a known-bad server
        candidates = [
            (pid, rpc)
            for pid, rpc in self.peers.items()
            if pid not in self.quarantined
        ]
        for pid, rpc in candidates:
            try:
                with span("sync/blocks_by_root", peer=pid):
                    blocks = rpc.blocks_by_root(
                        self._caller(), [parent_root]
                    )
            except RateLimitExceeded:
                _REQUEST_ERRORS.labels(
                    "blocks_by_root", "rate_limited"
                ).inc()
                continue
            except Exception:
                _REQUEST_ERRORS.labels("blocks_by_root", "error").inc()
                continue
            if not blocks:
                continue
            block = blocks[0]
            root = type(block.message).hash_tree_root(block.message)
            if root != parent_root:
                self._downscore(
                    pid, SCORE_INVALID_MESSAGE, "wrong_block_by_root"
                )
                continue
            if da.block_commitments(block):
                self._fetch_lookup_sidecars(pid, rpc, parent_root, block)
            try:
                self.chain.process_block(block)
                return True
            except Exception as e:
                msg = str(e)
                if "already" in msg:
                    return True
                if "unknown parent" in msg:
                    # walk one level deeper down the ancestor chain,
                    # then retry THIS block on top of it
                    if self.lookup_parent(
                        bytes(block.message.parent_root),
                        _depth=_depth + 1,
                        _failed=_failed,
                    ):
                        try:
                            self.chain.process_block(block)
                            return True
                        except Exception as e2:
                            _LOG.debug(
                                "parent retry after chain walk "
                                "failed: %s", e2,
                            )
                    continue
                if (
                    "data unavailable" in msg
                    or "parent state" in msg
                ):
                    # sidecars unfetchable, or OUR pruned state — not
                    # provably this peer's fault; try another
                    continue
                self._downscore(
                    pid, SCORE_INVALID_MESSAGE, "invalid_parent_block"
                )
                continue
        _failed.add(parent_root)
        return False

    def _fetch_lookup_sidecars(self, pid, rpc, root: bytes, block):
        """Pull the missing sidecars for a by-root block from the same
        peer and stage them in the DA checker; the following
        process_block settles and verifies them."""
        missing = self.chain.da_checker.missing_indices(root, block)
        if not missing:
            return
        if getattr(self.chain.da_checker, "put_column", None) is not None:
            self._fetch_lookup_columns(pid, rpc, root, block)
            return
        idents = [
            BlobIdentifier(block_root=root, index=i)
            for i in sorted(missing)
        ]
        try:
            with span("sync/blob_sidecars_by_root", peer=pid):
                sidecars = rpc.blob_sidecars_by_root(
                    self._caller(), idents
                )
        except RateLimitExceeded:
            _REQUEST_ERRORS.labels(
                "blob_sidecars_by_root", "rate_limited"
            ).inc()
            return
        except Exception:
            _REQUEST_ERRORS.labels("blob_sidecars_by_root", "error").inc()
            return
        # by-root named exact roots, so a foreign sidecar is an offense
        self._ingest_bound_sidecars(
            pid,
            sidecars,
            {root: (block, missing)},
            foreign_reason="foreign_sidecar",
        )

    def _fetch_lookup_columns(self, pid, rpc, root: bytes, block):
        """Column-mode twin of the blob lookup fetch: pull the missing
        column sidecars for a by-root block from the same peer and
        route them through the chain's column entry point. The
        structural binding rule is the blob plane's: a column whose
        header does not carry EXACTLY the served block's signature is
        a scored offense, and the accepted header needs no extra
        pairing because the block's own proposal check covers it.
        Crossing the 50% threshold inside this loop releases (and
        imports) the held block; the caller's process_block then hits
        the known-block gate, which lookup_parent treats as success."""
        da = self.chain.da_checker
        missing = da.missing_indices(root, block)
        if not missing:
            return
        idents = [
            DataColumnIdentifier(block_root=root, index=i)
            for i in sorted(missing)
        ]
        try:
            with span("sync/data_column_sidecars_by_root", peer=pid):
                sidecars = rpc.data_column_sidecars_by_root(
                    self._caller(), idents
                )
        except RateLimitExceeded:
            _REQUEST_ERRORS.labels(
                "data_column_sidecars_by_root", "rate_limited"
            ).inc()
            return
        except Exception:
            _REQUEST_ERRORS.labels(
                "data_column_sidecars_by_root", "error"
            ).inc()
            return
        fetched = 0
        for sc in sidecars:
            hdr = sc.signed_block_header.message
            if type(hdr).hash_tree_root(hdr) != root:
                self._downscore(
                    pid, SCORE_INVALID_MESSAGE, "foreign_sidecar"
                )
                continue
            if int(sc.index) not in missing:
                continue
            if bytes(sc.signed_block_header.signature) != bytes(
                block.signature
            ):
                self._downscore(
                    pid, SCORE_INVALID_MESSAGE, "sidecar_header_mismatch"
                )
                continue
            try:
                self.chain.process_data_column_sidecar(
                    sc, verify_header=False
                )
                fetched += 1
            except Exception as e:
                # duplicates on a retried lookup are expected; real
                # mismatches surface as DA failures at import
                _LOG.debug("column ingest skipped: %s", e)
        if fetched:
            try:
                # a block the checker never registered caches the
                # fetched columns as UNVERIFIED candidates; put_block
                # settles them in one folded cell batch so
                # missing_indices reflects the fetch (no-op when the
                # block was already registered or held)
                da.put_block(root, block)
            except Exception as e:
                _LOG.debug("column settle skipped: %s", e)
        _SIDECARS_FETCHED.inc(fetched)
        self.metrics["sidecars_fetched"] += fetched

    def _fetch_segment_columns(self, needed, block_peer) -> bool:
        """Column-mode twin of `_fetch_segment_sidecars`' fetch half:
        by-range blob requests have no column analog here, so each
        blob-committing block in the segment pulls its missing columns
        by root — from the block's server first, then any other
        trusted peer. Returns False when some block stays below its
        50% threshold (the batch must requeue)."""
        da = self.chain.da_checker
        ordered = [block_peer] + [
            p for p in self.peers if p != block_peer
        ]
        ok = True
        for root, (sb, _missing) in needed.items():
            for pid in ordered:
                rpc = self.peers.get(pid)
                if rpc is None or pid in self.quarantined:
                    continue
                self._fetch_lookup_columns(pid, rpc, root, sb)
                if not da.missing_indices(root, sb):
                    break
            if da.missing_indices(root, sb):
                ok = False
        return ok
