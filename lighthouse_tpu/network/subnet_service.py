"""Attestation subnet plane: committee→subnet mapping + duty-driven
subscriptions.

Role of the reference's attestation subnet service
(beacon_node/network/src/subnet_service/attestation_subnets.rs:1-50 +
consensus/types/src/subnet_id.rs): gossip load shards across
ATTESTATION_SUBNET_COUNT (64) `beacon_attestation_{id}` topics. A node
keeps a few LONG-LIVED subnets (its share of the backbone, advertised via
discovery so peers can find subnet coverage) and joins others JUST IN TIME
for attestation duties, unsubscribing when the duty slot passes.
"""

SUBNETS_PER_NODE = 2  # long-lived backbone share (p2p spec)
# keep a duty subscription this many slots past its duty (aggregates of
# the duty slot still arrive during the next slot)
DUTY_LINGER_SLOTS = 1


def compute_subnet(
    spec, slot: int, committee_index: int, committees_per_slot: int
) -> int:
    """subnet_id.rs compute_subnet_for_attestation: committees since the
    epoch start, offset by the committee index, mod the subnet count."""
    slots_since_epoch_start = slot % spec.SLOTS_PER_EPOCH
    committees_since_epoch_start = (
        committees_per_slot * slots_since_epoch_start
    )
    return (
        committees_since_epoch_start + committee_index
    ) % spec.ATTESTATION_SUBNET_COUNT


def subnet_topic_name(subnet_id: int) -> str:
    return f"beacon_attestation_{subnet_id}"


class AttestationSubnetService:
    """Tracks which attestation subnets this node is subscribed to and
    why (long-lived backbone vs duty), driving the hub's
    subscribe/unsubscribe as duties come and go."""

    def __init__(self, spec, node_id: str, subscribe, unsubscribe):
        """`subscribe`/`unsubscribe` take a bare topic NAME (e.g.
        "beacon_attestation_7"); the node curries its fork digest in."""
        self.spec = spec
        self.node_id = node_id
        self._subscribe = subscribe
        self._unsubscribe = unsubscribe
        # subnet id -> expiry slot (duty subscriptions only)
        self._duty_expiry: dict[int, int] = {}
        # deterministic long-lived backbone subnets from the node id
        # (the reference derives them from the node's ENR/peer id so the
        # backbone is stable across restarts)
        import hashlib

        seed = hashlib.sha256(node_id.encode()).digest()
        count = spec.ATTESTATION_SUBNET_COUNT
        self.long_lived = []
        i = 0
        while len(self.long_lived) < min(SUBNETS_PER_NODE, count):
            sub = int.from_bytes(seed[4 * i : 4 * i + 4], "little") % count
            if sub not in self.long_lived:
                self.long_lived.append(sub)
            i += 1
        for sub in self.long_lived:
            self._subscribe(subnet_topic_name(sub))

    # ------------------------------------------------------------- duties

    def subscribe_for_duty(
        self, slot: int, committee_index: int, committees_per_slot: int
    ) -> int:
        """Join the subnet carrying `committee_index`'s attestations at
        `slot` (attestation_subnets.rs validator_subscriptions). Returns
        the subnet id."""
        sub = compute_subnet(
            self.spec, slot, committee_index, committees_per_slot
        )
        expiry = slot + DUTY_LINGER_SLOTS
        prev = self._duty_expiry.get(sub)
        if prev is None and sub not in self.long_lived:
            self._subscribe(subnet_topic_name(sub))
        if prev is None or expiry > prev:
            self._duty_expiry[sub] = expiry
        return sub

    def on_slot(self, slot: int):
        """Drop duty subscriptions whose window passed (long-lived
        backbone subnets are never dropped)."""
        expired = [
            sub for sub, exp in self._duty_expiry.items() if exp < slot
        ]
        for sub in expired:
            del self._duty_expiry[sub]
            if sub not in self.long_lived:
                self._unsubscribe(subnet_topic_name(sub))

    @property
    def active_subnets(self) -> set:
        return set(self.long_lived) | set(self._duty_expiry)
