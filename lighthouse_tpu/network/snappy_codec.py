"""Snappy codec: raw block format + framing, native-accelerated.

Role of the reference's `snap` crate usage: gossip messages are
raw-snappy-block compressed (lighthouse_network/src/types/pubsub.rs) and
req/resp streams use the snappy FRAME format with masked CRC32C
(rpc/codec/ssz_snappy.rs). Compression uses the C matcher
(native/snappy.c) when the toolchain is available; decompression and the
frame layer always verify lengths/checksums. The pure-Python fallback
compressor emits literal-only blocks — valid snappy, just uncompressed.
"""

import ctypes
import os
import struct
import subprocess
import threading

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "native", "snappy.c")
_SO = os.path.join(_HERE, "native", "_snappy.so")

_lib = None
_lib_lock = threading.Lock()


class SnappyError(ValueError):
    pass


def _load():
    global _lib
    if _lib is not None:  # lock-free fast path; _lib written once under lock
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        stale = os.path.exists(_SO) and os.path.getmtime(
            _SO
        ) < os.path.getmtime(_SRC)
        if not os.path.exists(_SO) or stale:
            cc = os.environ.get("CC", "cc")
            # compile to a private temp file and rename into place:
            # concurrent processes must never CDLL a half-written .so
            tmp = _SO + f".tmp{os.getpid()}"
            try:
                subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC", _SRC, "-o", tmp],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, _SO)
            except (subprocess.CalledProcessError, OSError):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                _lib = False
                return False
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            _lib = False
            return False
        lib.snappy_max_compressed.restype = ctypes.c_uint32
        lib.snappy_max_compressed.argtypes = [ctypes.c_uint32]
        lib.snappy_compress.restype = ctypes.c_uint32
        lib.snappy_compress.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p
        ]
        lib.snappy_uncompress.restype = ctypes.c_int64  # -1 = malformed
        lib.snappy_uncompress.argtypes = [
            ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p,
            ctypes.c_uint32,
        ]
        lib.snappy_crc32c.restype = ctypes.c_uint32
        lib.snappy_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint32]
        _lib = lib
        return lib


def native_available() -> bool:
    return bool(_load())


# ------------------------------------------------------------ raw block


def _varint(n: int) -> bytes:
    out = bytearray()
    while n >= 0x80:
        out.append((n & 0x7F) | 0x80)
        n >>= 7
    out.append(n)
    return bytes(out)


def compress_block(data: bytes) -> bytes:
    """Raw snappy block. Native matcher when available; else a valid
    literal-only encoding."""
    lib = _load()
    if lib:
        cap = lib.snappy_max_compressed(len(data))
        out = ctypes.create_string_buffer(cap)
        n = lib.snappy_compress(data, len(data), out)
        if n:
            return out.raw[:n]
    # literal-only fallback
    out = bytearray(_varint(len(data)))
    i = 0
    while i < len(data):
        chunk = data[i : i + 65536]
        n = len(chunk) - 1
        if n < 60:
            out.append(n << 2)
        else:
            out.append(61 << 2)
            out += struct.pack("<H", n)
        out += chunk
        i += len(chunk)
    return bytes(out)


def _read_varint(data: bytes, pos: int):
    result = shift = 0
    while True:
        if pos >= len(data) or shift > 28:
            raise SnappyError("bad varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def decompress_block(data: bytes, max_len: int = 1 << 27) -> bytes:
    """Raw snappy block decode with full validation."""
    expect, pos = _read_varint(data, 0)
    if expect > max_len:
        raise SnappyError("declared length too large")
    lib = _load()
    if lib:
        out = ctypes.create_string_buffer(max(expect, 1))
        n = lib.snappy_uncompress(data, len(data), out, expect)
        if n != expect:
            raise SnappyError("malformed snappy block")
        return out.raw[:expect]
    # pure-Python decode
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:
            ln = (tag >> 2) + 1
            if ln > 60:
                extra = ln - 60
                if n - pos < extra:
                    raise SnappyError("truncated length")
                ln = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if ln > n - pos:
                raise SnappyError("truncated literal")
            out += data[pos : pos + ln]
            pos += ln
        else:
            if kind == 1:
                if pos >= n:
                    raise SnappyError("truncated copy")
                ln = ((tag >> 2) & 7) + 4
                offset = ((tag >> 5) << 8) | data[pos]
                pos += 1
            elif kind == 2:
                if n - pos < 2:
                    raise SnappyError("truncated copy")
                ln = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 2], "little")
                pos += 2
            else:
                if n - pos < 4:
                    raise SnappyError("truncated copy")
                ln = (tag >> 2) + 1
                offset = int.from_bytes(data[pos : pos + 4], "little")
                pos += 4
            if offset == 0 or offset > len(out):
                raise SnappyError("bad copy offset")
            for _i in range(ln):
                out.append(out[-offset])
        if len(out) > expect:
            raise SnappyError("overrun")
    if len(out) != expect:
        raise SnappyError("length mismatch")
    return bytes(out)


# --------------------------------------------------------------- framing

_STREAM_IDENTIFIER = b"\xff\x06\x00\x00sNaPpY"
_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01


def _crc32c(data: bytes) -> int:
    lib = _load()
    if lib:
        return lib.snappy_crc32c(data, len(data))
    crc = 0xFFFFFFFF
    for byte in data:
        crc ^= byte
        for _ in range(8):
            crc = (0x82F63B78 ^ (crc >> 1)) if crc & 1 else crc >> 1
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    c = _crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def frame_compress(data: bytes) -> bytes:
    """Snappy frame format (the req/resp ssz_snappy stream encoding)."""
    out = bytearray(_STREAM_IDENTIFIER)
    for i in range(0, max(len(data), 1), 65536):
        chunk = data[i : i + 65536]
        body = compress_block(chunk)
        if len(body) < len(chunk):
            ctype = _CHUNK_COMPRESSED
        else:
            ctype, body = _CHUNK_UNCOMPRESSED, chunk
        payload = struct.pack("<I", _masked_crc(chunk)) + body
        out.append(ctype)
        out += struct.pack("<I", len(payload))[:3]
        out += payload
    return bytes(out)


def frame_decompress(data: bytes, max_len: int = 1 << 27) -> bytes:
    if not data.startswith(_STREAM_IDENTIFIER):
        raise SnappyError("missing stream identifier")
    pos = len(_STREAM_IDENTIFIER)
    out = bytearray()
    while pos < len(data):
        if pos + 4 > len(data):
            raise SnappyError("truncated chunk header")
        ctype = data[pos]
        ln = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        if pos + ln > len(data):
            raise SnappyError("truncated chunk")
        chunk = data[pos : pos + ln]
        pos += ln
        if ctype in (_CHUNK_COMPRESSED, _CHUNK_UNCOMPRESSED):
            if ln < 4:
                raise SnappyError("chunk too short")
            want_crc = int.from_bytes(chunk[:4], "little")
            body = chunk[4:]
            plain = (
                decompress_block(body, max_len)
                if ctype == _CHUNK_COMPRESSED
                else body
            )
            if _masked_crc(plain) != want_crc:
                raise SnappyError("checksum mismatch")
            out += plain
            if len(out) > max_len:
                raise SnappyError("stream too large")
        elif ctype == 0xFF:
            continue  # repeated stream identifier
        elif 0x80 <= ctype <= 0xFE:
            continue  # skippable (0xFE = padding)
        else:
            raise SnappyError(f"unknown chunk type {ctype:#x}")
    return bytes(out)
