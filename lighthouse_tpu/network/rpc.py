"""Req/resp RPC: Status, Ping, Metadata, Goodbye, BlocksByRange/ByRoot,
BlobSidecarsByRange/ByRoot.

Role of the reference's rpc stack (lighthouse_network/src/rpc/: methods,
protocol negotiation, ssz_snappy codec, per-protocol rate limiting). SSZ
payloads over an abstract peer channel (in-process here; the framing layer
is transport-agnostic), with a token-bucket rate limiter per (peer,
method) mirroring rpc/rate_limiter.rs.

The req/resp surface is an adversarial boundary: every method is
rate-limited BEFORE any store work runs, request sizes are clamped to the
protocol maxima, and the `lighthouse_tpu_rpc_requests_total` family
records every served/rate-limited/errored request so an abusive peer is
visible on the scrape before it is visible in the logs.
"""

import functools
import time
from dataclasses import dataclass

from lighthouse_tpu import ssz
from lighthouse_tpu.common.metrics import REGISTRY


class StatusMessage(ssz.Container):
    fork_digest: ssz.bytes4
    finalized_root: ssz.bytes32
    finalized_epoch: ssz.uint64
    head_root: ssz.bytes32
    head_slot: ssz.uint64


class Ping(ssz.Container):
    data: ssz.uint64


class MetaData(ssz.Container):
    seq_number: ssz.uint64
    attnets: ssz.Bitvector(64)


class Goodbye(ssz.Container):
    reason: ssz.uint64


class BlocksByRangeRequest(ssz.Container):
    start_slot: ssz.uint64
    count: ssz.uint64
    step: ssz.uint64


MAX_REQUEST_BLOCKS = 1024
# deneb p2p: MAX_REQUEST_BLOCKS_DENEB (128) * MAX_BLOBS_PER_BLOCK (6)
MAX_REQUEST_BLOB_SIDECARS = 768
# PeerDAS p2p: MAX_REQUEST_BLOCKS_DENEB (128) * NUMBER_OF_COLUMNS (128)
MAX_REQUEST_DATA_COLUMN_SIDECARS = 16384


class BlobIdentifier(ssz.Container):
    """(block_root, index) — the by-root request key for one sidecar
    (deneb p2p BlobIdentifier). A wire-local twin of the spec-bound
    container in types/containers.py: request framing must not depend
    on a Spec instance."""

    block_root: ssz.bytes32
    index: ssz.uint64


class DataColumnIdentifier(ssz.Container):
    """(block_root, index) — the by-root request key for one COLUMN
    sidecar (PeerDAS p2p DataColumnIdentifier), wire-local like
    BlobIdentifier above."""

    block_root: ssz.bytes32
    index: ssz.uint64


class BlobSidecarsByRootRequest(ssz.Container):
    identifiers: ssz.List(BlobIdentifier, MAX_REQUEST_BLOB_SIDECARS)


class DataColumnSidecarsByRootRequest(ssz.Container):
    identifiers: ssz.List(
        DataColumnIdentifier, MAX_REQUEST_DATA_COLUMN_SIDECARS
    )


class BlobSidecarsByRangeRequest(ssz.Container):
    start_slot: ssz.uint64
    count: ssz.uint64


# token-bucket quotas per method: (tokens, per_seconds)
QUOTAS = {
    "status": (5, 15),
    "ping": (2, 10),
    "metadata": (2, 5),
    "goodbye": (1, 10),
    "blocks_by_range": (1024, 10),
    "blocks_by_root": (128, 10),
    "blob_sidecars_by_range": (MAX_REQUEST_BLOB_SIDECARS, 10),
    "blob_sidecars_by_root": (MAX_REQUEST_BLOB_SIDECARS, 10),
    "data_column_sidecars_by_root": (MAX_REQUEST_DATA_COLUMN_SIDECARS, 10),
}

_RPC_REQUESTS = REGISTRY.counter_vec(
    "lighthouse_tpu_rpc_requests_total",
    "req/resp requests, by method and outcome (ok|rate_limited|error)",
    ("method", "outcome"),
)
_RPC_SIDECARS_SERVED = REGISTRY.counter(
    "lighthouse_tpu_rpc_blob_sidecars_served_total",
    "blob sidecars served over the by_range/by_root req/resp methods",
)
_RPC_COLUMNS_SERVED = REGISTRY.counter(
    "lighthouse_tpu_rpc_data_columns_served_total",
    "data-column sidecars served over the by_root req/resp method",
)


class RateLimitExceeded(Exception):
    pass


class _Bucket:
    def __init__(self, tokens, per_seconds):
        self.capacity = tokens
        self.refill = tokens / per_seconds
        self.tokens = float(tokens)
        self.last = time.monotonic()

    def take(self, n=1.0):
        now = time.monotonic()
        self.tokens = min(
            self.capacity, self.tokens + (now - self.last) * self.refill
        )
        self.last = now
        if self.tokens < n:
            raise RateLimitExceeded
        self.tokens -= n


@dataclass
class RpcError(Exception):
    code: int
    message: str


def _counted(method_name: str):
    """Record the request outcome AFTER the handler runs: ok only when
    it actually served, error when it raised (rate_limited is recorded
    at the bucket, before any work)."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(self, peer_id, *args, **kwargs):
            try:
                out = fn(self, peer_id, *args, **kwargs)
            except RateLimitExceeded:
                raise
            except Exception:
                _RPC_REQUESTS.labels(method_name, "error").inc()
                raise
            _RPC_REQUESTS.labels(method_name, "ok").inc()
            return out

        return wrapper

    return deco


class RpcServer:
    """Per-node RPC endpoint serving the standard methods from a chain."""

    def __init__(self, chain, node_id: str, fork_digest: bytes):
        self.chain = chain
        self.node_id = node_id
        self.fork_digest = fork_digest
        self.seq_number = 0
        self._buckets: dict[tuple, _Bucket] = {}
        # goodbye hook: the node wires this to SyncManager.remove_peer so
        # a cleanly-disconnecting peer leaves without any score penalty
        self.on_goodbye = None

    def _limit(self, peer_id: str, method: str, n=1.0):
        key = (peer_id, method)
        if key not in self._buckets:
            self._buckets[key] = _Bucket(*QUOTAS[method])
        try:
            self._buckets[key].take(n)
        except RateLimitExceeded:
            _RPC_REQUESTS.labels(method, "rate_limited").inc()
            raise

    # ------------------------------------------------------------ methods

    @_counted("status")
    def status(self, peer_id: str) -> StatusMessage:
        self._limit(peer_id, "status")
        chain = self.chain
        head = chain.head_state
        fin = head.finalized_checkpoint
        return StatusMessage(
            fork_digest=self.fork_digest,
            finalized_root=bytes(fin.root)
            if fin.epoch
            else chain.genesis_root,
            finalized_epoch=fin.epoch,
            head_root=chain.head_root,
            head_slot=head.slot,
        )

    @_counted("ping")
    def ping(self, peer_id: str, data: int) -> int:
        self._limit(peer_id, "ping")
        return self.seq_number

    @_counted("metadata")
    def metadata(self, peer_id: str) -> MetaData:
        self._limit(peer_id, "metadata")
        return MetaData(seq_number=self.seq_number, attnets=[True] * 64)

    @_counted("goodbye")
    def goodbye(self, peer_id: str, reason: int = 0):
        """Clean disconnect (rpc GoodbyeReason): the peer announced it is
        leaving, so drop it from the serving side's sync view with NO
        score penalty — saying goodbye is polite, not misbehavior."""
        self._limit(peer_id, "goodbye")
        if self.on_goodbye is not None:
            self.on_goodbye(peer_id, int(reason))

    @_counted("blocks_by_range")
    def blocks_by_range(self, peer_id: str, req: BlocksByRangeRequest):
        count = min(req.count, MAX_REQUEST_BLOCKS)
        self._limit(peer_id, "blocks_by_range", float(count))
        if req.step != 1:
            raise RpcError(1, "step != 1 unsupported")
        out = []
        for slot in range(req.start_slot, req.start_slot + count):
            root = self.chain.store.get_canonical_block_root(slot)
            if root is None:
                continue
            block = self.chain.store.get_block(root)
            if block is not None:
                out.append(block)
        return out

    @_counted("blocks_by_root")
    def blocks_by_root(self, peer_id: str, roots):
        self._limit(peer_id, "blocks_by_root", float(len(roots)))
        out = []
        for root in roots:
            block = self.chain.store.get_block(bytes(root))
            if block is not None:
                out.append(block)
        return out

    @_counted("blob_sidecars_by_root")
    def blob_sidecars_by_root(self, peer_id: str, identifiers):
        """Serve stored sidecars for explicit (block_root, index) keys —
        the unknown-parent recovery path. Requests beyond
        MAX_REQUEST_BLOB_SIDECARS identifiers are clamped, and the
        bucket is charged per identifier BEFORE any store read."""
        identifiers = list(identifiers)[:MAX_REQUEST_BLOB_SIDECARS]
        self._limit(
            peer_id, "blob_sidecars_by_root", float(len(identifiers) or 1)
        )
        out = []
        wanted: dict[bytes, set] = {}
        for ident in identifiers:
            wanted.setdefault(bytes(ident.block_root), set()).add(
                int(ident.index)
            )
        for root, indices in wanted.items():
            for sc in self.chain.store.get_blob_sidecars(root):
                if int(sc.index) in indices:
                    out.append(sc)
        _RPC_SIDECARS_SERVED.inc(len(out))
        return out

    @_counted("data_column_sidecars_by_root")
    def data_column_sidecars_by_root(self, peer_id: str, identifiers):
        """Serve verified (or reconstructed) column sidecars for
        explicit (block_root, index) keys — the column-mode twin of
        blob_sidecars_by_root behind unknown-parent recovery and range
        sync. A column node holds every released block's FULL column
        set until finalization prunes it (the checker reconstructs the
        missing half at release), so any node that imported the block
        can serve any index. Blob-mode nodes hold no columns and
        answer empty."""
        identifiers = list(identifiers)[:MAX_REQUEST_DATA_COLUMN_SIDECARS]
        self._limit(
            peer_id,
            "data_column_sidecars_by_root",
            float(len(identifiers) or 1),
        )
        columns_for = getattr(self.chain.da_checker, "columns_for", None)
        if columns_for is None:
            return []
        wanted: dict[bytes, set] = {}
        for ident in identifiers:
            wanted.setdefault(bytes(ident.block_root), set()).add(
                int(ident.index)
            )
        out = []
        for root, indices in wanted.items():
            for sc in columns_for(root):
                if int(sc.index) in indices:
                    out.append(sc)
        _RPC_COLUMNS_SERVED.inc(len(out))
        return out

    @_counted("blob_sidecars_by_range")
    def blob_sidecars_by_range(
        self, peer_id: str, req: BlobSidecarsByRangeRequest
    ):
        """Serve canonical sidecars for a slot range (range-sync DA
        companion to blocks_by_range), capped at
        MAX_REQUEST_BLOB_SIDECARS sidecars total."""
        # charge for what a slot can actually CARRY (MAX_BLOBS_PER_BLOCK
        # sidecars), not one token per slot — a per-slot charge would be
        # a 6x bandwidth amplifier against the bucket. The slot clamp
        # keeps the worst-case charge exactly at the bucket's capacity
        # (768 / 6 = 128 slots on mainnet params = the deneb
        # MAX_REQUEST_BLOCKS_DENEB), so a maximal request is serveable
        # on a fresh bucket and never truncates mid-range.
        max_blobs = self.chain.store.spec.MAX_BLOBS_PER_BLOCK
        count = min(req.count, MAX_REQUEST_BLOB_SIDECARS // max_blobs)
        self._limit(
            peer_id, "blob_sidecars_by_range", float(count * max_blobs)
        )
        out = self.chain.store.get_blob_sidecars_by_range(
            int(req.start_slot), int(count), limit=MAX_REQUEST_BLOB_SIDECARS
        )
        _RPC_SIDECARS_SERVED.inc(len(out))
        return out
