"""Req/resp RPC: Status, Ping, Metadata, Goodbye, BlocksByRange/ByRoot.

Role of the reference's rpc stack (lighthouse_network/src/rpc/: methods,
protocol negotiation, ssz_snappy codec, per-protocol rate limiting). SSZ
payloads over an abstract peer channel (in-process here; the framing layer
is transport-agnostic), with a token-bucket rate limiter per (peer,
method) mirroring rpc/rate_limiter.rs.
"""

import time
from dataclasses import dataclass

from lighthouse_tpu import ssz


class StatusMessage(ssz.Container):
    fork_digest: ssz.bytes4
    finalized_root: ssz.bytes32
    finalized_epoch: ssz.uint64
    head_root: ssz.bytes32
    head_slot: ssz.uint64


class Ping(ssz.Container):
    data: ssz.uint64


class MetaData(ssz.Container):
    seq_number: ssz.uint64
    attnets: ssz.Bitvector(64)


class Goodbye(ssz.Container):
    reason: ssz.uint64


class BlocksByRangeRequest(ssz.Container):
    start_slot: ssz.uint64
    count: ssz.uint64
    step: ssz.uint64


MAX_REQUEST_BLOCKS = 1024

# token-bucket quotas per method: (tokens, per_seconds)
QUOTAS = {
    "status": (5, 15),
    "ping": (2, 10),
    "metadata": (2, 5),
    "goodbye": (1, 10),
    "blocks_by_range": (1024, 10),
    "blocks_by_root": (128, 10),
}


class RateLimitExceeded(Exception):
    pass


class _Bucket:
    def __init__(self, tokens, per_seconds):
        self.capacity = tokens
        self.refill = tokens / per_seconds
        self.tokens = float(tokens)
        self.last = time.monotonic()

    def take(self, n=1.0):
        now = time.monotonic()
        self.tokens = min(
            self.capacity, self.tokens + (now - self.last) * self.refill
        )
        self.last = now
        if self.tokens < n:
            raise RateLimitExceeded
        self.tokens -= n


@dataclass
class RpcError(Exception):
    code: int
    message: str


class RpcServer:
    """Per-node RPC endpoint serving the standard methods from a chain."""

    def __init__(self, chain, node_id: str, fork_digest: bytes):
        self.chain = chain
        self.node_id = node_id
        self.fork_digest = fork_digest
        self.seq_number = 0
        self._buckets: dict[tuple, _Bucket] = {}

    def _limit(self, peer_id: str, method: str, n=1.0):
        key = (peer_id, method)
        if key not in self._buckets:
            self._buckets[key] = _Bucket(*QUOTAS[method])
        self._buckets[key].take(n)

    # ------------------------------------------------------------ methods

    def status(self, peer_id: str) -> StatusMessage:
        self._limit(peer_id, "status")
        chain = self.chain
        head = chain.head_state
        fin = head.finalized_checkpoint
        return StatusMessage(
            fork_digest=self.fork_digest,
            finalized_root=bytes(fin.root)
            if fin.epoch
            else chain.genesis_root,
            finalized_epoch=fin.epoch,
            head_root=chain.head_root,
            head_slot=head.slot,
        )

    def ping(self, peer_id: str, data: int) -> int:
        self._limit(peer_id, "ping")
        return self.seq_number

    def metadata(self, peer_id: str) -> MetaData:
        self._limit(peer_id, "metadata")
        return MetaData(seq_number=self.seq_number, attnets=[True] * 64)

    def blocks_by_range(self, peer_id: str, req: BlocksByRangeRequest):
        count = min(req.count, MAX_REQUEST_BLOCKS)
        self._limit(peer_id, "blocks_by_range", float(count))
        if req.step != 1:
            raise RpcError(1, "step != 1 unsupported")
        out = []
        for slot in range(req.start_slot, req.start_slot + count):
            root = self.chain.store.get_canonical_block_root(slot)
            if root is None:
                continue
            block = self.chain.store.get_block(root)
            if block is not None:
                out.append(block)
        return out

    def blocks_by_root(self, peer_id: str, roots):
        self._limit(peer_id, "blocks_by_root", float(len(roots)))
        out = []
        for root in roots:
            block = self.chain.store.get_block(bytes(root))
            if block is not None:
                out.append(block)
        return out
