"""Deterministic fault injection for the req/resp plane.

Role of the reference's Antithesis / network-simulation fault campaigns
(the reference client is continuously fuzzed with dropped, delayed, and
corrupted network messages): wrap any RpcServer-shaped peer handle in a
`FaultyRpc` and, driven by a SEEDED RNG, drop, stall, truncate, corrupt,
duplicate, or rate-limit-exhaust its responses. Every decision comes off
`random.Random(seed)` in call order, so a failing chaos run replays
exactly from its seed — no real sleeping, no wall-clock dependence.

Fault semantics (what the sync client should observe):

  drop        empty response (the peer claims it has nothing)
  stall       RpcError(code=2, ...) — the socket layer's timeout shape
  truncate    only the first half of the response arrives
  corrupt     one element is rewritten: a block's signature or
              parent_root is flipped, a sidecar's blob is flipped —
              exercising the segment signature batch, the hash-chain
              validation, and the KZG settle path respectively
  duplicate   one element is repeated in place
  rate_limit  RateLimitExceeded — the peer claims the caller is over
              budget on every request
"""

import random

from lighthouse_tpu.network.rpc import RateLimitExceeded, RpcError

FAULT_KINDS = (
    "drop",
    "stall",
    "truncate",
    "corrupt",
    "duplicate",
    "rate_limit",
)


def _reencode(obj):
    """A deep, independent copy via the SSZ wire (Container.copy() can
    share nested structure; a corrupted response must never mutate the
    serving store's objects)."""
    return type(obj).decode(obj.to_bytes())


def _flip(data: bytes, pos: int, mask: int = 0x01) -> bytes:
    out = bytearray(data)
    out[pos] ^= mask
    return bytes(out)


def corrupt_element(obj, rng: random.Random):
    """Rewrite one adversarial field of a response element."""
    c = _reencode(obj)
    if hasattr(c, "blob"):
        # sidecar: flip the low byte of one field element — still a
        # canonical field encoding, but the KZG proof no longer opens it
        blob = bytearray(bytes(c.blob))
        blob[rng.randrange(len(blob) // 32) * 32 + 31] ^= 0x01
        c.blob = bytes(blob)
        return c
    if hasattr(c, "message") and hasattr(c, "signature"):
        if rng.random() < 0.5:
            # signature flip: survives structural validation, fails the
            # segment's bulk signature batch
            c.signature = _flip(bytes(c.signature), 1)
        else:
            # parent-root flip: a hash-chain violation the client's
            # response validation must catch without any crypto
            c.message.parent_root = _flip(
                bytes(c.message.parent_root), 0
            )
        return c
    return c


class FaultyRpc:
    """RpcServer-shaped wrapper injecting seeded faults into responses.

    `fault_rate` is the per-call probability of injecting a fault;
    `kinds` restricts the fault mix (default: all). `injected` counts
    what actually fired, per kind — chaos tests assert against it so a
    quiet seed cannot silently test nothing.
    """

    def __init__(
        self,
        inner,
        seed: int = 0,
        fault_rate: float = 0.5,
        kinds=FAULT_KINDS,
        fault_status: bool = False,
    ):
        self.inner = inner
        self.rng = random.Random(seed)
        self.fault_rate = fault_rate
        self.kinds = tuple(kinds)
        self.fault_status = fault_status
        self.injected = {k: 0 for k in self.kinds}
        self.calls = 0

    def _pick_fault(self):
        if self.rng.random() >= self.fault_rate:
            return None
        kind = self.kinds[self.rng.randrange(len(self.kinds))]
        self.injected[kind] += 1
        return kind

    def _listy(self, method: str, call):
        """Apply one fault decision to a list-shaped response."""
        self.calls += 1
        kind = self._pick_fault()
        if kind == "stall":
            raise RpcError(2, f"injected stall on {method}")
        if kind == "rate_limit":
            raise RateLimitExceeded
        if kind == "drop":
            return []
        out = list(call())
        if kind is None or not out:
            return out
        if kind == "truncate":
            return out[: len(out) // 2]
        if kind == "duplicate":
            i = self.rng.randrange(len(out))
            return out[: i + 1] + [_reencode(out[i])] + out[i + 1 :]
        if kind == "corrupt":
            i = self.rng.randrange(len(out))
            out[i] = corrupt_element(out[i], self.rng)
        return out

    # ----------------------------------------------- RpcServer surface

    def status(self, caller: str):
        if self.fault_status:
            kind = self._pick_fault()
            if kind == "stall":
                raise RpcError(2, "injected stall on status")
            if kind in ("drop", "rate_limit"):
                raise RateLimitExceeded
        return self.inner.status(caller)

    def ping(self, caller: str, data: int):
        return self.inner.ping(caller, data)

    def metadata(self, caller: str):
        return self.inner.metadata(caller)

    def goodbye(self, caller: str, reason: int = 0):
        return self.inner.goodbye(caller, reason)

    def blocks_by_range(self, caller: str, req):
        return self._listy(
            "blocks_by_range",
            lambda: self.inner.blocks_by_range(caller, req),
        )

    def blocks_by_root(self, caller: str, roots):
        return self._listy(
            "blocks_by_root",
            lambda: self.inner.blocks_by_root(caller, roots),
        )

    def blob_sidecars_by_range(self, caller: str, req):
        return self._listy(
            "blob_sidecars_by_range",
            lambda: self.inner.blob_sidecars_by_range(caller, req),
        )

    def blob_sidecars_by_root(self, caller: str, identifiers):
        return self._listy(
            "blob_sidecars_by_root",
            lambda: self.inner.blob_sidecars_by_root(caller, identifiers),
        )
