"""Backpressure-driven load shedding for the beacon processor's queues.

The PR 2 queue depth/wait metrics exist precisely to drive admission
decisions; this module is the consumer. A `SheddingPolicy` watches each
work kind's queue depth (as a fraction of its bound) and flips a
per-kind *shed window* open when the depth crosses a high-water mark,
closed when it drains below a low-water mark — classic hysteresis, so a
queue oscillating around one threshold does not flap the policy on
every submit.

Two hard rules shape the policy:

  * FORENSIC KINDS ARE NEVER SHED. Blocks, blob sidecars, and chain
    segments are the objects whose lifecycle the journal correlates by
    root — shedding them would lose consensus-critical work AND punch
    holes in the forensic record. Overload degrades the cheap,
    re-derivable gossip planes first (attestations, then aggregates,
    sync messages); the import path starves last.
  * SHED EARLY, SHED CHEAP. The decision runs at submit time, before
    the item is queued — a shed item costs one counter increment, not
    a queue slot plus a worker drain plus a handler error.

Shed state is observable three ways: the
``lighthouse_tpu_processor_shed_total{kind}`` counter (exact per-item
count), one ``shed_window`` journal event per open/close transition
(bounded — a flood cannot flush the ring through this kind), and
`SheddingPolicy.state()` surfaced under ``overload`` in
``GET /lighthouse/health``. The HTTP API reads the same policy to
return 429 on REST endpoints that enqueue processor work while the
matching kind's window is open.
"""

import threading

from lighthouse_tpu.common.metrics import REGISTRY

_SHED_TOTAL = REGISTRY.counter_vec(
    "lighthouse_tpu_processor_shed_total",
    "work items rejected at submit time by the backpressure shedding "
    "policy, per kind (forensic kinds are exempt and never count here)",
    ("kind",),
)

# kinds whose loss is unrecoverable for consensus or forensics: the
# import path and its DA inputs. The shedding policy refuses to shed
# these no matter how deep their queues run — the bounded queue's own
# drop (counted + journaled by the processor) is the only backstop.
FORENSIC_KINDS = frozenset(
    {"gossip_block", "gossip_blob_sidecar", "chain_segment"}
)

# default hysteresis thresholds, as fractions of each kind's queue
# bound: open the shed window at high_water, close it at low_water.
HIGH_WATER = 0.75
LOW_WATER = 0.25


class SheddingPolicy:
    """Per-kind hysteresis shed windows over the processor queues.

    `should_shed(kind, depth)` is the submit-time admission decision;
    `observe_depth(kind, depth)` lets the drain path close windows as
    queues empty. Both are cheap (one lock, two compares) — they run on
    the gossip ingest hot path.
    """

    def __init__(
        self,
        bounds: dict,
        journal=None,
        high_water: float = HIGH_WATER,
        low_water: float = LOW_WATER,
    ):
        """`bounds` is held BY REFERENCE (the beacon processor passes
        its own dict), so there is exactly one source of truth — a
        caller adjusting queue bounds adjusts the hysteresis thresholds
        with it. Use `enabled = False` to turn the policy off (the
        bench A/B), never by mutating bounds out from under it."""
        if not 0.0 < low_water < high_water <= 1.0:
            raise ValueError(
                f"shedding thresholds need 0 < low ({low_water}) < "
                f"high ({high_water}) <= 1"
            )
        self.bounds = bounds if bounds is not None else {}
        self.enabled = True
        self.journal = journal
        self.high_water = high_water
        self.low_water = low_water
        self._lock = threading.Lock()
        self._open: dict[str, bool] = {}
        self._shed_counts: dict[str, int] = {}
        self._windows_opened: dict[str, int] = {}

    # ------------------------------------------------------------ decisions

    def _transition(self, kind: str, depth: int) -> bool:
        """Update the kind's window from `depth`; returns whether the
        window is open AFTER the update. Caller holds the lock."""
        bound = self.bounds.get(kind)
        if not bound:
            return False
        frac = depth / bound
        was_open = self._open.get(kind, False)
        if was_open and frac <= self.low_water:
            self._open[kind] = False
            self._emit(kind, "closed")
            return False
        if not was_open and frac >= self.high_water:
            self._open[kind] = True
            self._windows_opened[kind] = (
                self._windows_opened.get(kind, 0) + 1
            )
            self._emit(kind, "opened")
            return True
        return was_open

    def _emit(self, kind: str, outcome: str):
        if self.journal is None:
            return
        self.journal.emit(
            "shed_window",
            outcome=outcome,
            work=kind,
        )

    def should_shed(self, kind: str, depth: int) -> bool:
        """Submit-time admission: True = reject this item now. Forensic
        kinds are never shed; everything else sheds while the kind's
        hysteresis window is open."""
        if not self.enabled or kind in FORENSIC_KINDS:
            return False
        with self._lock:
            open_ = self._transition(kind, depth)
            if open_:
                self._shed_counts[kind] = (
                    self._shed_counts.get(kind, 0) + 1
                )
        if open_:
            _SHED_TOTAL.labels(kind).inc()
        return open_

    def observe_depth(self, kind: str, depth: int):
        """Drain-path observation: closes the window once the queue
        falls below the low-water mark (submit may never run again
        after a flood lifts, so the drain must be able to close it)."""
        if kind in FORENSIC_KINDS:
            return
        with self._lock:
            self._transition(kind, depth)

    # ---------------------------------------------------------------- reads

    def is_shedding(self, kind: str) -> bool:
        with self._lock:
            return self._open.get(kind, False)

    def any_open(self) -> bool:
        """True while ANY kind's shed window is open — the cheap
        whole-node pressure read the verification bus polls."""
        with self._lock:
            return any(self._open.values())

    def state(self) -> dict:
        """The health-plane view: which windows are open right now,
        exact shed counts, and how many windows each kind has opened."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "active": sorted(
                    k for k, open_ in self._open.items() if open_
                ),
                "shed_total": dict(self._shed_counts),
                "windows_opened": dict(self._windows_opened),
                "high_water": self.high_water,
                "low_water": self.low_water,
            }
