"""Beacon processor: bounded priority work queues feeding worker threads.

Role of the reference's `BeaconProcessor`
(beacon_node/network/src/beacon_processor/mod.rs:1-40 design doc,
:85-120 queue bounds): a manager drains per-kind bounded FIFO/LIFO queues
in priority order into a capped worker pool. Two reference behaviors are
preserved because they shape the TPU data plane:

  * attestation COALESCING — queued gossip attestations are handed to one
    worker as a batch (mod.rs attestation queues), which downstream becomes
    ONE device signature batch;
  * the reprocessing queue — early (future-slot) or unknown-parent work is
    delayed and re-injected (work_reprocessing_queue.rs).
"""

import heapq
import threading

from lighthouse_tpu.common.events_journal import JOURNAL
from lighthouse_tpu.common.locks import TimedLock
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.network.shedding import SheddingPolicy
import time
from dataclasses import dataclass, field

# queue-plane observability (the reference exports the same shape from
# beacon_processor/mod.rs via lighthouse_metrics): depth per kind,
# submit/drop/process events, time-in-queue, and handler wall time
_QUEUE_DEPTH = REGISTRY.gauge_vec(
    "lighthouse_tpu_beacon_processor_queue_depth",
    "queued work items per kind",
    ("kind",),
)
_QUEUE_EVENTS = REGISTRY.counter_vec(
    "lighthouse_tpu_beacon_processor_events_total",
    "beacon processor queue events (submitted/dropped/shed/reprocess_"
    "scheduled/processed/handler_error) per kind",
    ("kind", "event"),
)
_QUEUE_WAIT_SECONDS = REGISTRY.histogram_vec(
    "lighthouse_tpu_beacon_processor_wait_seconds",
    "time a work item spent queued before a worker picked it up",
    ("kind",),
)
_WORK_SECONDS = REGISTRY.histogram_vec(
    "lighthouse_tpu_beacon_processor_work_seconds",
    "handler wall time per drained batch, by kind",
    ("kind",),
)


@dataclass(order=True)
class WorkItem:
    priority: int
    seq: int
    kind: str = field(compare=False)
    payload: object = field(compare=False)
    t_submit: float = field(compare=False, default=0.0)


# priority per work kind (lower = more urgent), mirroring the reference's
# drain order: chain segments/blocks first, then aggregates, attestations,
# then the long tail.
PRIORITIES = {
    "gossip_block": 0,
    # sidecars drain right after blocks: a held block's import latency
    # is bounded by its slowest sidecar (deneb queue ordering)
    "gossip_blob_sidecar": 1,
    # column sidecars share the sidecar tier: a column-mode block's
    # import latency is bounded by its slowest 50%-threshold column
    "gossip_data_column": 1,
    "chain_segment": 1,
    "gossip_aggregate": 2,
    "gossip_attestation": 3,
    "sync_message": 4,
    "rpc_request": 5,
    "gossip_exit": 6,
    "gossip_slashing": 6,
}

DEFAULT_BOUNDS = {
    "gossip_block": 1024,
    "gossip_blob_sidecar": 4096,
    "gossip_data_column": 4096,
    "chain_segment": 64,
    "gossip_aggregate": 4096,
    "gossip_attestation": 16384,
    "sync_message": 4096,
    "rpc_request": 1024,
    "gossip_exit": 512,
    "gossip_slashing": 512,
}

ATTESTATION_BATCH_MAX = 64
AGGREGATE_BATCH_MAX = 64

# journal policy: per-item enqueue events only for the object kinds
# whose lifecycle the journal correlates by root downstream (blocks,
# sidecars, segments) — the 16k-deep attestation queue would otherwise
# flush every block's forensic trail out of the ring. Drops are
# journaled for EVERY kind (a dropped item is exactly the event a
# forensic query needs), and each drained batch lands one
# processor_batch event.
_JOURNALED_ENQUEUE_KINDS = frozenset(
    {
        "gossip_block",
        "gossip_blob_sidecar",
        "gossip_data_column",
        "chain_segment",
    }
)


class BeaconProcessor:
    # one journaled drop event per this many drops of a non-forensic
    # kind (the exact count rides in the event's dropped_total attr)
    DROP_SAMPLE = 256

    def __init__(
        self, handlers, max_workers: int = 2, bounds=None, journal=None
    ):
        """handlers: kind -> callable(payload_or_batch). Attestation and
        aggregate kinds receive LISTS (coalesced batches). `journal` is
        the owning node's event journal (defaults to the process-global
        one)."""
        self.handlers = handlers
        self.journal = journal if journal is not None else JOURNAL
        self.bounds = dict(DEFAULT_BOUNDS)
        if bounds:
            self.bounds.update(bounds)
        # backpressure shedding: queue depths become an admission
        # signal — cheap gossip kinds are rejected at submit while a
        # hysteresis window is open, forensic kinds never are
        self.shedder = SheddingPolicy(self.bounds, journal=self.journal)
        self._queues: dict[str, list] = {k: [] for k in PRIORITIES}
        self._dropped: dict[str, int] = {k: 0 for k in PRIORITIES}
        self._lock = TimedLock("beacon_processor.queues")
        self._work_available = threading.Condition(self._lock)
        self._seq = 0
        self._workers = []
        self._max_workers = max_workers
        self._stop = False
        self._reprocess: list = []  # (ready_time, kind, payload)
        self.metrics = {
            "processed": 0, "reprocessed": 0, "dropped": 0, "shed": 0,
        }

    def queue_depths(self) -> dict:
        """Current depth per work kind (the health-plane read)."""
        with self._lock:
            return {k: len(q) for k, q in self._queues.items()}

    def shed_state(self) -> dict:
        """The overload view for /lighthouse/health: open shed windows,
        exact shed counts, window transitions."""
        return self.shedder.state()

    def pressure_high(self) -> bool:
        """Queue-depth pressure signal for the verification bus's flush
        policy: True while any shed window is open or any queue sits
        at/over its high-water fraction — the node is loaded, so the
        bus should dispatch immediately instead of holding for
        co-riders (big batches form naturally from the backlog)."""
        if self.shedder.any_open():
            return True
        with self._lock:
            for kind, q in self._queues.items():
                bound = self.bounds.get(kind)
                if bound and len(q) / bound >= self.shedder.high_water:
                    return True
        return False

    # -------------------------------------------------------------- submit

    def submit(self, kind: str, payload) -> bool:
        """Enqueue work; returns False when the bounded queue dropped it
        or the backpressure shedding policy rejected it (cheapest-first
        overload degradation; forensic kinds are never shed)."""
        with self._lock:
            q = self._queues[kind]
            if self.shedder.should_shed(kind, len(q)):
                self.metrics["shed"] += 1
                _QUEUE_EVENTS.labels(kind, "shed").inc()
                return False
            if len(q) >= self.bounds[kind]:
                self._dropped[kind] += 1
                self.metrics["dropped"] += 1
                _QUEUE_EVENTS.labels(kind, "dropped").inc()
                # drop journaling is per-item only for the forensic
                # object kinds; a high-volume drop storm (attestation
                # flood) is sampled every DROP_SAMPLE so it cannot
                # flush the ring it is being recorded in (the counter
                # above stays exact)
                if (
                    kind in _JOURNALED_ENQUEUE_KINDS
                    or self._dropped[kind] % self.DROP_SAMPLE == 1
                ):
                    self.journal.emit(
                        "processor_drop",
                        outcome="queue_full",
                        work=kind,
                        depth=len(q),
                        dropped_total=self._dropped[kind],
                    )
                return False
            self._seq += 1
            q.append(
                WorkItem(
                    PRIORITIES[kind], self._seq, kind, payload,
                    time.monotonic(),
                )
            )
            _QUEUE_EVENTS.labels(kind, "submitted").inc()
            _QUEUE_DEPTH.labels(kind).set(len(q))
            if kind in _JOURNALED_ENQUEUE_KINDS:
                self.journal.emit(
                    "processor_enqueue",
                    outcome="submitted",
                    work=kind,
                    depth=len(q),
                )
            self._work_available.notify()
        return True

    def submit_delayed(self, kind: str, payload, delay_s: float):
        """Reprocessing queue: re-inject after `delay_s` (early blocks,
        unknown-parent attestations)."""
        with self._lock:
            heapq.heappush(
                self._reprocess,
                (time.monotonic() + delay_s, self._seq, kind, payload),
            )
            self._seq += 1
            self.metrics["reprocessed"] += 1
            _QUEUE_EVENTS.labels(kind, "reprocess_scheduled").inc()

    # --------------------------------------------------------------- drain

    def _next_batch(self):
        """Pop the highest-priority work; coalesce attestation kinds."""
        now = time.monotonic()
        while self._reprocess and self._reprocess[0][0] <= now:
            _, _, kind, payload = heapq.heappop(self._reprocess)
            self.submit(kind, payload)

        for kind in sorted(PRIORITIES, key=PRIORITIES.get):
            q = self._queues[kind]
            if not q:
                continue
            if kind == "gossip_attestation":
                items = q[:ATTESTATION_BATCH_MAX]
            elif kind == "gossip_aggregate":
                items = q[:AGGREGATE_BATCH_MAX]
            else:
                items = q[:1]
            del q[: len(items)]
            # the drain is allowed to close a shed window: after a
            # flood lifts, submit may never run for this kind again
            self.shedder.observe_depth(kind, len(q))
            wait_hist = _QUEUE_WAIT_SECONDS.labels(kind)
            for w in items:
                if w.t_submit:
                    wait_hist.observe(now - w.t_submit)
            _QUEUE_DEPTH.labels(kind).set(len(q))
            if kind in ("gossip_attestation", "gossip_aggregate"):
                return kind, [w.payload for w in items]
            return kind, items[0].payload
        return None

    def process_pending(self, max_items: int | None = None):
        """Synchronous drain (deterministic testing mode — the manual-clock
        analog of the async worker loop)."""
        n = 0
        while max_items is None or n < max_items:
            with self._lock:
                nxt = self._next_batch()
            if nxt is None:
                return n
            kind, payload = nxt
            self._run_batch(kind, payload)
            n += 1
        return n

    def _run_batch(self, kind: str, payload):
        """Run one drained batch through its handler, timing it into the
        work histogram and journaling the batch. A raising handler is
        counted as handler_error — in BOTH the event counter and the
        journal, so the two stay cross-checkable — never as processed."""
        t0 = time.perf_counter()
        n = len(payload) if isinstance(payload, list) else 1
        try:
            self.handlers[kind](payload)
        except Exception:
            dt = time.perf_counter() - t0
            _WORK_SECONDS.labels(kind).observe(dt)
            _QUEUE_EVENTS.labels(kind, "handler_error").inc()
            self.journal.emit(
                "processor_batch",
                outcome="handler_error",
                duration_s=dt,
                work=kind,
                n=n,
            )
            raise
        dt = time.perf_counter() - t0
        _WORK_SECONDS.labels(kind).observe(dt)
        self.metrics["processed"] += 1
        _QUEUE_EVENTS.labels(kind, "processed").inc()
        self.journal.emit(
            "processor_batch",
            outcome="processed",
            duration_s=dt,
            work=kind,
            n=n,
        )

    # ------------------------------------------------------ threaded mode

    def start(self):
        self._stop = False
        for _ in range(self._max_workers):
            th = threading.Thread(target=self._worker_loop, daemon=True)
            th.start()
            self._workers.append(th)

    def stop(self):
        with self._lock:
            self._stop = True
            self._work_available.notify_all()
        for th in self._workers:
            th.join(timeout=5)
        self._workers = []

    def _worker_loop(self):
        while True:
            with self._lock:
                if self._stop:
                    return
                nxt = self._next_batch()
                if nxt is None:
                    self._work_available.wait(timeout=0.05)
                    continue
            kind, payload = nxt
            try:
                self._run_batch(kind, payload)
            # lint: allow(except-swallow): belt for the pool loop —
            except Exception:  # _run_batch already counted handler_error
                pass
