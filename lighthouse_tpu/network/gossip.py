"""Gossip plane: topic pub/sub with per-peer scoring, in-process transport.

Role of the reference's lighthouse_network gossipsub wrapper
(behaviour/mod.rs:148 composing gossipsub + peer manager): fork-versioned
topic strings, publish/subscribe fan-out, duplicate suppression by message
id, and peer scoring hooks that quarantine misbehaving peers
(peer_manager/ scoring). The transport here is in-process (the
testing/simulator topology — multiple nodes, one process); a socket
transport can implement the same `publish/deliver` surface.
"""

import hashlib
from collections import defaultdict

GOSSIP_MAX_SIZE = 10 * 1024 * 1024

# peer-score actions (peer_manager scoring semantics)
SCORE_INVALID_MESSAGE = -20.0
SCORE_DUPLICATE = -0.5
SCORE_VALID = 0.5
# req/resp misbehavior is scored through the same hub: an unresponsive
# or erroring peer costs a little (it may just be overloaded), a peer
# serving malformed/hash-chain-violating responses costs
# SCORE_INVALID_MESSAGE (it is provably lying)
SCORE_TIMEOUT = -1.0
BAN_THRESHOLD = -50.0


def topic(fork_digest: bytes, name: str) -> str:
    return f"/eth2/{fork_digest.hex()}/{name}/ssz_snappy"


def blob_sidecar_topic_name(subnet_id: int) -> str:
    """`blob_sidecar_{subnet_id}` — the deneb p2p sidecar topics; a
    sidecar's subnet is its index modulo BLOB_SIDECAR_SUBNET_COUNT
    (compute_subnet_for_blob_sidecar)."""
    return f"blob_sidecar_{subnet_id}"


def compute_blob_subnet(index: int, subnet_count: int) -> int:
    return int(index) % max(int(subnet_count), 1)


def data_column_sidecar_topic_name(subnet_id: int) -> str:
    """`data_column_sidecar_{subnet_id}` — the PeerDAS column topics; a
    column's subnet is its index modulo DATA_COLUMN_SIDECAR_SUBNET_COUNT
    (compute_subnet_for_data_column_sidecar)."""
    return f"data_column_sidecar_{subnet_id}"


def compute_column_subnet(index: int, subnet_count: int) -> int:
    return int(index) % max(int(subnet_count), 1)


def message_id(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()[:20]


class Peer:
    def __init__(self, peer_id: str, deliver):
        self.peer_id = peer_id
        self.deliver = deliver  # callable(topic, data, from_peer)
        self.score = 0.0
        self.banned = False

    def apply_score(self, delta: float):
        self.score += delta
        if self.score <= BAN_THRESHOLD:
            self.banned = True


class GossipHub:
    """A broadcast domain connecting peers (nodes)."""

    def __init__(self):
        self.peers: dict[str, Peer] = {}
        self.subscriptions: dict[str, set] = defaultdict(set)
        self._seen: set[bytes] = set()

    def join(self, peer_id: str, deliver) -> Peer:
        peer = Peer(peer_id, deliver)
        self.peers[peer_id] = peer
        return peer

    def subscribe(self, peer_id: str, topic_str: str):
        self.subscriptions[topic_str].add(peer_id)

    def unsubscribe(self, peer_id: str, topic_str: str):
        self.subscriptions[topic_str].discard(peer_id)

    def publish(self, from_peer: str, topic_str: str, data: bytes):
        """Fan out to subscribers; drops duplicates and oversized frames,
        skips banned publishers."""
        src = self.peers.get(from_peer)
        if src is None or src.banned:
            return 0
        if len(data) > GOSSIP_MAX_SIZE:
            src.apply_score(SCORE_INVALID_MESSAGE)
            return 0
        mid = message_id(topic_str.encode() + data)
        if mid in self._seen:
            src.apply_score(SCORE_DUPLICATE)
            return 0
        self._seen.add(mid)
        delivered = 0
        for pid in list(self.subscriptions.get(topic_str, ())):
            if pid == from_peer:
                continue
            peer = self.peers.get(pid)
            if peer is None or peer.banned:
                continue
            peer.deliver(topic_str, data, from_peer)
            delivered += 1
        return delivered

    def report(self, peer_id: str, delta: float):
        """Application-level validation feedback -> peer score."""
        peer = self.peers.get(peer_id)
        if peer is not None:
            peer.apply_score(delta)

    def prune_seen(self, keep: int = 100_000):
        if len(self._seen) > keep:
            self._seen = set(list(self._seen)[-keep // 2 :])


# ----------------------------------------------------------- wire codecs


def encode_gossip(ssz_bytes: bytes) -> bytes:
    """Gossip payloads are raw-snappy-block compressed SSZ (the
    `/ssz_snappy` topic encoding, types/pubsub.rs)."""
    from lighthouse_tpu.network.snappy_codec import compress_block

    return compress_block(ssz_bytes)


def decode_gossip(data: bytes, max_len: int = 10 * 1024 * 1024) -> bytes:
    from lighthouse_tpu.network.snappy_codec import decompress_block

    return decompress_block(data, max_len)
