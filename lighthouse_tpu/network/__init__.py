from lighthouse_tpu.network.beacon_processor import (  # noqa: F401
    BeaconProcessor,
    WorkItem,
)
from lighthouse_tpu.network.gossip import GossipHub  # noqa: F401
