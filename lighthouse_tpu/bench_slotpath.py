"""BENCH_CONFIG=slotpath: the slot-budget decomposition harness.

Boots ONE full `BeaconNode` (fake crypto backend — the CPU proxy; the
tpu backend when the tunnel is up), drives BENCH_NSETS block imports
through `chain.process_block`, and reports the slot-budget recorder's
decomposition: per-stage medians, import wall p50/p99 against the
200 ms budget, the serial-dispatch count, and the fusable gap — the
host time between consecutive device round trips that the ROADMAP's
one-dispatch-slot item would erase. `scripts/perf_gate.py` diffs this
line against its committed baseline; `scripts/tpu_watcher.py` sweeps
it on hardware and stamps the baseline's `hardware` block.

On the fake backend the STAGE TIMINGS are a CPU proxy (the structure —
stage set, serial-dispatch count, accounting identity — is exact; the
milliseconds are not hardware), so the line is `valid_for_headline`
only on tpu/axon.
"""

import os

from lighthouse_tpu.common.slot_budget import SLOT_BUDGET_MS

N_VALIDATORS = 16
# bellatrix activates at epoch 1 (minimal: slot 8); every 4th slot
# after that carries blobs so the import pays the KZG-settle round trip
# on top of the signature fold — the two-dispatch shape whose gap the
# fusable-gap ledger exists to measure. SLOTPATH_BLOB_PERIOD/
# SLOTPATH_BLOBS override the cadence and per-slot blob count so the
# fused path can be benched at heavier blob geometries without
# editing this file.
BLOB_PERIOD = 4


def _geometry():
    """(n_imports, blob_period, blobs_per_slot) from the env:
    SLOTPATH_BLOCKS (BENCH_NSETS keeps working as the legacy name),
    SLOTPATH_BLOB_PERIOD, SLOTPATH_BLOBS."""
    n_imports = int(
        os.environ.get("SLOTPATH_BLOCKS")
        or os.environ.get("BENCH_NSETS")
        or 16
    )
    period = int(os.environ.get("SLOTPATH_BLOB_PERIOD") or BLOB_PERIOD)
    blobs = int(os.environ.get("SLOTPATH_BLOBS") or 2)
    return n_imports, max(1, period), max(1, blobs)


def _build_node(backend: str):
    from lighthouse_tpu.harness import Harness
    from lighthouse_tpu.node import BeaconNode
    from lighthouse_tpu.types.spec import minimal_spec

    spec = minimal_spec(
        name="bench-slotpath",
        ALTAIR_FORK_EPOCH=0,
        BELLATRIX_FORK_EPOCH=1,
    )
    h = Harness(spec, N_VALIDATORS, backend=backend)
    node = BeaconNode("bench0", h.state, spec, backend=backend)
    return h, node


def _blob(spec, seed: int) -> bytes:
    n = int(spec.FIELD_ELEMENTS_PER_BLOB)
    return b"".join(
        ((seed * 2654435761 + i * 31 + 7) % (2**200)).to_bytes(32, "big")
        for i in range(n)
    )


def measure(jax, platform):
    from lighthouse_tpu import kzg
    from lighthouse_tpu.state_processing.per_block import (
        BlockSignatureStrategy,
    )

    on_tpu = platform in ("tpu", "axon")
    # the import pipeline's crypto backend: real kernels on hardware,
    # the fake backend as the CPU proxy (BENCH_SLOTPATH_BACKEND
    # overrides, e.g. =ref to time the host reference pairing)
    backend = os.environ.get(
        "BENCH_SLOTPATH_BACKEND", "tpu" if on_tpu else "fake"
    )
    n_imports, blob_period, blobs_per_slot = _geometry()

    h, node = _build_node(backend)
    chain = node.chain
    # BENCH_SLOTFUSE=off restores the serial three-dispatch path (the
    # A/B partner bench_slotfuse drives both arms itself)
    if os.environ.get("BENCH_SLOTFUSE") == "off":
        chain.slot_fuse = False
    recorder = chain.slot_budget
    recorder.configure(ring=max(n_imports + 8, 128))
    blob_start = int(h.spec.SLOTS_PER_EPOCH)
    blob_imports = 0
    for slot in range(1, n_imports + 1):
        node.on_slot(slot)
        if slot >= blob_start and slot % blob_period == 0:
            blob_imports += 1
            blobs = [
                _blob(h.spec, slot * 16 + i)
                for i in range(blobs_per_slot)
            ]
            comms = [
                kzg.blob_to_kzg_commitment(b, consumer="bench")
                for b in blobs
            ]
            block = h.produce_block(
                slot, [], blob_kzg_commitments=comms
            )
            h.import_block(
                block, strategy=BlockSignatureStrategy.NO_VERIFICATION
            )
            for sc in h.make_blob_sidecars(block, blobs):
                chain.process_blob_sidecar(sc)
        else:
            block = h.produce_block(slot, [])
            h.import_block(
                block, strategy=BlockSignatureStrategy.NO_VERIFICATION
            )
        chain.process_block(block)

    recs = recorder.recent()
    summary = recorder.summary()
    # the recorder's defining identity must close on every import —
    # a gate run with broken accounting is not a timing regression,
    # it is a broken instrument
    accounting_complete = bool(recs) and all(
        abs(r["union_s"] + r["unattributed_s"] - r["wall_s"]) <= 1e-3
        and r["serial_dispatches"] == len(r["dispatches"])
        for r in recs
    )
    wall_p50_ms = round((summary["wall_p50_s"] or 0.0) * 1000.0, 3)
    # the gap is only defined between round trips: report its median
    # over the imports that paid >= 2 serial dispatches (blob slots —
    # settle then fold), where a fused slot-program would collapse them
    multi_gaps = sorted(
        r["fusable_gap_s"]
        for r in recs
        if r["serial_dispatches"] >= 2
    )
    gap_multi_ms = round(
        multi_gaps[len(multi_gaps) // 2] * 1000.0, 3
    ) if multi_gaps else 0.0
    # one-dispatch-slot evidence: how many imports went out as a fused
    # chained program (dispatch kind "fused") vs the serial shape
    fused_imports = sum(
        1
        for r in recs
        if any(d.get("kind") == "fused" for d in r["dispatches"])
    )
    return {
        "metric": "slotpath_wall_p50_ms",
        "value": wall_p50_ms,
        "unit": "ms",
        "vs_baseline": 0.0,
        "budget_utilization": round(wall_p50_ms / SLOT_BUDGET_MS, 4),
        "platform": platform,
        "impl": backend,
        "n_sets": n_imports,
        "p50_s": round(wall_p50_ms / 1000.0, 4),
        "wall_p99_ms": round(
            (summary["wall_p99_s"] or 0.0) * 1000.0, 3
        ),
        "stages_p50_ms": {
            name: round(s["p50_s"] * 1000.0, 3)
            for name, s in summary["stages"].items()
        },
        "fusable_gap_p50_ms": round(
            (summary["fusable_gap_p50_s"] or 0.0) * 1000.0, 3
        ),
        "fusable_gap_multi_dispatch_p50_ms": gap_multi_ms,
        "multi_dispatch_imports": len(multi_gaps),
        "serial_dispatches_p50": summary["serial_dispatches_p50"],
        "serial_dispatches_max": summary["serial_dispatches_max"],
        "accounting_complete": accounting_complete,
        "slot_fuse": bool(chain.slot_fuse),
        "blob_imports": blob_imports,
        "fused_imports": fused_imports,
        "blob_period": blob_period,
        "blobs_per_slot": blobs_per_slot,
        "valid_for_headline": bool(on_tpu and n_imports >= 16),
    }
