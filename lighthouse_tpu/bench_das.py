"""BENCH_CONFIG=das: the data-availability sampling plane's kernels.

One line covering both device workloads of `lighthouse_tpu/da`:

  * Reed-Solomon blob extension (`ops/rs_extend` via
    `da.erasure.extend_blobs(backend="tpu")`) — the column-sidecar
    production path: every blob polynomial evaluated over the 2x
    extended domain in ONE batched Horner scan, checked byte-identical
    against the host bigint oracle every iteration.
  * Batched cell-multiproof verification
    (`da.cells.verify_cell_proof_batch(backend="tpu")`) — the sampling
    hot path: all cells of all blobs folded into ONE two-pair pairing
    on the guarded device plane, cross-checked against the ref verdict
    (and a corrupted batch must be REJECTED by both tiers — agreement
    on accept alone would not prove soundness).

Both paths go through the guarded executor (GUARD.dispatch with
xla-host -> ref failover), so a flapping tunnel degrades the number,
never the correctness assertions. The headline `value` is cell proofs
verified per second through the fold; the extension throughput rides
as `extend_evals_per_sec`.

Shape knobs: BENCH_NSETS = blob count (default 8). The geometry is the
dev preset scaled up (64-element blobs, 32-element cells -> 4 columns);
mainnet-scale blob counts are the ROADMAP's remaining DA item, not this
config's claim.
"""

import json
import os
import time

N_BLOB_ELEMENTS = 64
N_CELL_ELEMENTS = 32


def _blob(geo, seed: int) -> bytes:
    return b"".join(
        ((seed * 997 + i * 2654435761 + 13) % (2**200)).to_bytes(32, "big")
        for i in range(geo.blob_elements)
    )


def measure(jax, platform):
    from lighthouse_tpu import kzg
    from lighthouse_tpu.da import cells as da_cells
    from lighthouse_tpu.da import erasure
    from lighthouse_tpu.da.domain import geometry

    if platform == "cpu":
        n_blobs, blob_n, cell_m, reps = 2, 8, 4, 2  # prove the path only
    else:
        n_blobs = int(os.environ.get("BENCH_NSETS") or 8)
        blob_n, cell_m, reps = N_BLOB_ELEMENTS, N_CELL_ELEMENTS, 5

    geo = geometry(blob_n, cell_m)
    setup = kzg.dev_setup(blob_n)
    blobs = [_blob(geo, k) for k in range(n_blobs)]

    # ---- RS extension: device vs host oracle, then steady-state p50
    oracle = erasure.extend_blobs(blobs, geo, consumer="bench")
    t0 = time.perf_counter()
    got = erasure.extend_blobs(blobs, geo, backend="tpu", consumer="bench")
    compile_s = time.perf_counter() - t0
    if got != oracle:
        raise RuntimeError("device RS extension diverged from host oracle")
    extend_t = []
    for _ in range(reps):
        t0 = time.perf_counter()
        got = erasure.extend_blobs(
            blobs, geo, backend="tpu", consumer="bench"
        )
        extend_t.append(time.perf_counter() - t0)
        if got != oracle:
            raise RuntimeError(
                "device RS extension diverged from host oracle"
            )
    extend_p50 = sorted(extend_t)[len(extend_t) // 2]

    # ---- cell multiproofs: one item per (blob, cell), one fold
    items = []
    for blob in blobs:
        comm = kzg.blob_to_kzg_commitment(blob, setup, consumer="bench")
        cells, proofs = da_cells.compute_cells_and_kzg_proofs(
            blob, geo, setup=setup, consumer="bench"
        )
        items.extend(
            (comm, k, cells[k], proofs[k]) for k in range(geo.num_cells)
        )

    def verify(batch, backend):
        return da_cells.verify_cell_proof_batch(
            batch, geo, backend=backend, setup=setup, seed=7,
            consumer="bench",
        )

    t0 = time.perf_counter()
    dev_ok = verify(items, "tpu")
    verify_compile_s = time.perf_counter() - t0
    if not (dev_ok and verify(items, "ref")):
        raise RuntimeError("honest cell batch rejected (tpu/ref disagree)")
    # soundness half of the oracle check: one flipped cell byte must be
    # rejected on BOTH tiers
    comm, k, cell, proof = items[0]
    bad = [(comm, k, bytes([cell[0] ^ 1]) + cell[1:], proof)] + items[1:]
    if verify(bad, "tpu") or verify(bad, "ref"):
        raise RuntimeError("corrupted cell batch accepted")

    verify_t = []
    for _ in range(reps):
        t0 = time.perf_counter()
        ok = verify(items, "tpu")
        verify_t.append(time.perf_counter() - t0)
        if not ok:
            raise RuntimeError("cell batch rejected mid-measurement")
    verify_p50 = sorted(verify_t)[len(verify_t) // 2]

    on_tpu = platform in ("tpu", "axon")
    return {
        "metric": "das_cell_verify_throughput",
        "value": round(len(items) / verify_p50, 2),
        "unit": "cells/sec",
        "vs_baseline": 0.0,  # no published reference number for this shape
        "platform": platform,
        "impl": "rs_horner+cell_fold",
        "n_sets": n_blobs,
        "n_cells": len(items),
        "blob_elements": geo.blob_elements,
        "cell_elements": geo.cell_elements,
        "p50_s": round(verify_p50, 4),
        "extend_evals_per_sec": round(
            n_blobs * geo.ext_elements / extend_p50, 2
        ),
        "extend_p50_s": round(extend_p50, 4),
        "compile_s": round(compile_s + verify_compile_s, 1),
        "byte_identical": True,
        "valid_for_headline": bool(on_tpu and n_blobs >= 8),
    }


if __name__ == "__main__":
    print(json.dumps(measure(None, "cpu"), indent=2))
