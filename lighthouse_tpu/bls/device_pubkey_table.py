"""Device-resident decompressed validator pubkey table.

Role of beacon_node/beacon_chain/src/validator_pubkey_cache.rs:9-24 on
the TPU plane (SURVEY §7 hard part 4): decompression and limb packing
happen ONCE per validator at registration; a signature batch then ships
(S, K) int32 validator indices instead of 48-byte points, and the device
gathers affine Montgomery limbs from HBM-resident tables. At 30k sigs a
slot this removes all per-pubkey Python bigint work from the hot path.
"""

import numpy as np

import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import P
from lighthouse_tpu.crypto.ref_curve import G1 as G1_GROUP
from lighthouse_tpu.ops import fieldb as fb


def _mont_limbs(values) -> np.ndarray:
    """ints -> (N, NB) Montgomery-domain canonical limbs, host-side
    (cheap: one bigint mulmod per value; avoids a device round-trip per
    append)."""
    return fb.pack_ints([(v << 384) % P for v in values])


class DevicePubkeyTable:
    """(capacity, 1, NB) x/y Montgomery limb arrays on device, indexed by
    validator index + 1. Row 0 is a zero row so masked-out gather lanes
    read a harmless (0, 0); capacity grows in powers of two so the jitted
    gather-verify graph recompiles O(log N) times over a chain's life."""

    def __init__(self):
        self._x_np = np.zeros((1, 1, fb.NB), dtype=np.int64)
        self._y_np = np.zeros((1, 1, fb.NB), dtype=np.int64)
        self._x = None
        self._y = None
        self.count = 0  # validator rows (excludes the zero row)

    def append(self, pubkeys) -> None:
        """Append decompressed `bls.PublicKey`s (one-time per validator)."""
        if not pubkeys:
            return
        affs = [G1_GROUP.to_affine(p.point) for p in pubkeys]
        xs = _mont_limbs([a[0] for a in affs])[:, None, :]
        ys = _mont_limbs([a[1] for a in affs])[:, None, :]
        used = self.count + 1
        self._x_np = np.concatenate([self._x_np[:used], xs], axis=0)
        self._y_np = np.concatenate([self._y_np[:used], ys], axis=0)
        self.count += len(pubkeys)
        self._x = None  # re-uploaded (padded) on next rows()

    def _capacity(self) -> int:
        cap = 8
        while cap < self.count + 1:
            cap *= 2
        return cap

    def rows(self):
        """(x, y) device arrays, shape (capacity, 1, NB); validator i
        lives at row i+1."""
        if self._x is None:
            cap = self._capacity()
            pad = cap - self._x_np.shape[0]
            widths = ((0, pad), (0, 0), (0, 0))
            self._x = jnp.asarray(
                np.pad(self._x_np, widths).astype(np.int32)
            )
            self._y = jnp.asarray(
                np.pad(self._y_np, widths).astype(np.int32)
            )
        return self._x, self._y

    @staticmethod
    def gather_indices(validator_indices) -> np.ndarray:
        """Host helper: validator indices -> table row indices (shifting
        past the zero row; -1 == masked lane -> row 0)."""
        idx = np.asarray(validator_indices, dtype=np.int32)
        return np.where(idx >= 0, idx + 1, 0).astype(np.int32)
