"""BLS12-381 point compression/decompression (ZCash serialization format).

The wire format of every pubkey (48 B) and signature (96 B) in the protocol
— the reference gets this from blst's serialize/deserialize behind
`GenericPublicKey::from_bytes` / `GenericSignature::serialize`
(crypto/bls/src/generic_public_key.rs, generic_signature.rs).

Flag bits in the top byte of the (first) x coordinate:
  0x80 compression flag (always set here)
  0x40 infinity flag
  0x20 sort flag: y is the lexicographically larger root
"""

from lighthouse_tpu.crypto import ref_fields as ff
from lighthouse_tpu.crypto.constants import B_G1, B_G2, P
from lighthouse_tpu.crypto.ref_curve import G1 as G1_GROUP
from lighthouse_tpu.crypto.ref_curve import G2 as G2_GROUP

COMPRESSION_FLAG = 0x80
INFINITY_FLAG = 0x40
SORT_FLAG = 0x20


class DecodeError(ValueError):
    pass


def _y_is_lexicographically_largest_fp(y: int) -> bool:
    return y > (P - 1) // 2


def _y_is_lexicographically_largest_fp2(y) -> bool:
    if y[1] != 0:
        return y[1] > (P - 1) // 2
    return y[0] > (P - 1) // 2


# ---------------------------------------------------------------------- G1


def g1_compress(pt_jacobian) -> bytes:
    aff = G1_GROUP.to_affine(pt_jacobian)
    if aff is None:
        return bytes([COMPRESSION_FLAG | INFINITY_FLAG]) + b"\x00" * 47
    x, y = aff
    flags = COMPRESSION_FLAG
    if _y_is_lexicographically_largest_fp(y):
        flags |= SORT_FLAG
    data = bytearray(x.to_bytes(48, "big"))
    data[0] |= flags
    return bytes(data)


def g1_decompress(data: bytes):
    """48 bytes -> Jacobian point (on-curve checked; NOT subgroup checked —
    callers apply subgroup policy, mirroring the reference's split between
    deserialization and `key_validate`)."""
    if len(data) != 48:
        raise DecodeError("G1: expected 48 bytes")
    flags = data[0]
    if not flags & COMPRESSION_FLAG:
        raise DecodeError("G1: uncompressed flag on compressed input")
    if flags & INFINITY_FLAG:
        if flags & SORT_FLAG or any(data[1:]) or (data[0] & 0x3F):
            raise DecodeError("G1: malformed infinity encoding")
        return G1_GROUP.infinity
    x = int.from_bytes(
        bytes([data[0] & 0x1F]) + data[1:], "big"
    )
    if x >= P:
        raise DecodeError("G1: x not canonical")
    y = _g1_solve_y(x)
    if y is None:
        raise DecodeError("G1: x not on curve")
    if bool(flags & SORT_FLAG) != _y_is_lexicographically_largest_fp(y):
        y = P - y
    return (x, y, 1)


def _sqrt_fp(a: int):
    root = pow(a, (P + 1) // 4, P)
    return root if root * root % P == a % P else None


def _g1_solve_y(x: int):
    """y with y^2 = x^3 + 4, preferring the native C path
    (native/g2decomp.c — ~13x the pure-Python exponentiation)."""
    from lighthouse_tpu.native import g2decomp

    y = g2decomp.g1_sqrt_rhs(x)
    if y is None:  # no native library: Python fallback
        return _sqrt_fp((x * x % P * x + B_G1) % P)
    return None if y is False else y


def _g2_solve_y(x):
    """y with y^2 = x^3 + 4(1+u) over Fp2, native-first."""
    from lighthouse_tpu.native import g2decomp

    y = g2decomp.g2_sqrt_rhs(x[0], x[1])
    if y is None:
        rhs = ff.fp2_add(ff.fp2_mul(ff.fp2_sqr(x), x), B_G2)
        return ff.fp2_sqrt(rhs)
    return None if y is False else y


# ---------------------------------------------------------------------- G2


def g2_compress(pt_jacobian) -> bytes:
    aff = G2_GROUP.to_affine(pt_jacobian)
    if aff is None:
        return bytes([COMPRESSION_FLAG | INFINITY_FLAG]) + b"\x00" * 95
    (x0, x1), y = aff
    flags = COMPRESSION_FLAG
    if _y_is_lexicographically_largest_fp2(y):
        flags |= SORT_FLAG
    data = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    data[0] |= flags
    return bytes(data)


def g2_decompress(data: bytes):
    if len(data) != 96:
        raise DecodeError("G2: expected 96 bytes")
    flags = data[0]
    if not flags & COMPRESSION_FLAG:
        raise DecodeError("G2: uncompressed flag on compressed input")
    if flags & INFINITY_FLAG:
        if flags & SORT_FLAG or any(data[1:]) or (data[0] & 0x3F):
            raise DecodeError("G2: malformed infinity encoding")
        return G2_GROUP.infinity
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise DecodeError("G2: x not canonical")
    x = (x0, x1)
    y = _g2_solve_y(x)
    if y is None:
        raise DecodeError("G2: x not on curve")
    if bool(flags & SORT_FLAG) != _y_is_lexicographically_largest_fp2(y):
        y = ff.fp2_neg(y)
    return (x, y, ff.FP2_ONE)
