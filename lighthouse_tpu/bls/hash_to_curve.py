"""RFC 9380 hash-to-curve for BLS12-381 G2 (BLS12381G2_XMD:SHA-256_SSWU_RO_).

This is the message side of the signature plane — the reference client gets
it from blst's `hash_to_g2` inside signature verification
(crypto/bls/src/impls/blst.rs:69 Aggregate::hash_to_g2 path). Pipeline:

    expand_message_xmd(SHA-256) -> hash_to_field (2 Fp2 elements)
    -> simplified SWU on the 3-isogenous curve E'
    -> 3-isogeny to E -> point add -> clear cofactor (psi endomorphism)

Every non-trivially-derivable constant here is validated mathematically by
tests (tests/test_hash_to_curve.py): the SSWU output must satisfy E', the
isogeny must carry E' points onto E, psi must act as multiplication by the
curve parameter x on G2, and final outputs must be r-torsion. A wrong
constant cannot pass those identities.
"""

import hashlib

from lighthouse_tpu.crypto import ref_fields as ff
from lighthouse_tpu.crypto.constants import BLS_X, DST_G2, P, R
from lighthouse_tpu.crypto.ref_curve import G2 as G2_GROUP

# ------------------------------------------------------ expand_message_xmd

B_IN_BYTES = 32  # SHA-256 output
R_IN_BYTES = 64  # SHA-256 block size
L = 64  # ceil((ceil(log2(p)) + k) / 8) = (381 + 128)/8 rounded up


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + B_IN_BYTES - 1) // B_IN_BYTES
    if ell > 255:
        raise ValueError("expand_message_xmd: output too long")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * R_IN_BYTES
    l_i_b_str = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(
        z_pad + msg + l_i_b_str + b"\x00" + dst_prime
    ).digest()
    b1 = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    bs = [b1]
    for i in range(2, ell + 1):
        prev = bs[-1]
        xored = bytes(a ^ b for a, b in zip(b0, prev))
        bs.append(hashlib.sha256(xored + bytes([i]) + dst_prime).digest())
    return b"".join(bs)[:len_in_bytes]


def hash_to_field_fp2(msg: bytes, count: int, dst: bytes = DST_G2):
    """count Fp2 field elements from msg."""
    m = 2
    uniform = expand_message_xmd(msg, dst, count * m * L)
    out = []
    for i in range(count):
        comps = []
        for j in range(m):
            off = L * (j + i * m)
            comps.append(int.from_bytes(uniform[off : off + L], "big") % P)
        out.append(tuple(comps))
    return out


# ----------------------------------------------------------- SSWU on E2'

# E2': y^2 = x^3 + A*x + B over Fp2, the curve 3-isogenous to E2.
SSWU_A = (0, 240)
SSWU_B = (1012, 1012)
SSWU_Z = ((-2) % P, (-1) % P)  # Z = -(2 + I)


def _g_prime(x):
    """g'(x) = x^3 + A x + B on E'."""
    return ff.fp2_add(
        ff.fp2_add(
            ff.fp2_mul(ff.fp2_sqr(x), x), ff.fp2_mul(SSWU_A, x)
        ),
        SSWU_B,
    )


def _sgn0(x) -> int:
    """RFC 9380 sgn0 for Fp2 (m=2)."""
    sign_0 = x[0] % 2
    zero_0 = x[0] == 0
    sign_1 = x[1] % 2
    return sign_0 or (zero_0 and sign_1)


def map_to_curve_sswu(u):
    """Simplified SWU: Fp2 element -> point on E' (never fails)."""
    u2 = ff.fp2_sqr(u)
    tv1 = ff.fp2_mul(SSWU_Z, u2)  # Z u^2
    tv2 = ff.fp2_add(ff.fp2_sqr(tv1), tv1)  # Z^2 u^4 + Z u^2
    neg_b_over_a = ff.fp2_mul(
        ff.fp2_neg(SSWU_B), ff.fp2_inv(SSWU_A)
    )
    if tv2 == ff.FP2_ZERO:
        # exceptional case: x1 = B / (Z A)
        x1 = ff.fp2_mul(SSWU_B, ff.fp2_inv(ff.fp2_mul(SSWU_Z, SSWU_A)))
    else:
        x1 = ff.fp2_mul(
            neg_b_over_a, ff.fp2_add(ff.FP2_ONE, ff.fp2_inv(tv2))
        )
    gx1 = _g_prime(x1)
    y1 = ff.fp2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = ff.fp2_mul(tv1, x1)  # Z u^2 x1
        gx2 = _g_prime(x2)
        y2 = ff.fp2_sqrt(gx2)
        assert y2 is not None, "SSWU: neither gx1 nor gx2 square"
        x, y = x2, y2
    if _sgn0(u) != _sgn0(y):
        y = ff.fp2_neg(y)
    return (x, y)


# ------------------------------------------------------------- 3-isogeny

# Coefficients of the 3-isogeny E' -> E (RFC 9380 appendix E.3). Validated
# in tests by mapping points of E' and checking the E equation.


def _fp2(c0, c1):
    return (c0 % P, c1 % P)


_ISO_XNUM = [
    _fp2(
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97D6,
    ),
    _fp2(
        0,
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71A,
    ),
    _fp2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71E,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38D,
    ),
    _fp2(
        0x171D6541FA38CCFAED6DEA691F5FB614CB14B4E7F4E810AA22D6108F142B85757098E38D0F671C7188E2AAAAAAAA5ED1,
        0,
    ),
]

_ISO_XDEN = [
    _fp2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA63,
    ),
    _fp2(
        0xC,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA9F,
    ),
    ff.FP2_ONE,  # monic x^2 term
]

_ISO_YNUM = [
    _fp2(
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
        0x1530477C7AB4113B59A4C18B076D11930F7DA5D4A07F649BF54439D87D27E500FC8C25EBF8C92F6812CFC71C71C6D706,
    ),
    _fp2(
        0,
        0x5C759507E8E333EBB5B7A9A47D7ED8532C52D39FD3A042A88B58423C50AE15D5C2638E343D9C71C6238AAAAAAAA97BE,
    ),
    _fp2(
        0x11560BF17BAA99BC32126FCED787C88F984F87ADF7AE0C7F9A208C6B4F20A4181472AAA9CB8D555526A9FFFFFFFFC71C,
        0x8AB05F8BDD54CDE190937E76BC3E447CC27C3D6FBD7063FCD104635A790520C0A395554E5C6AAAA9354FFFFFFFFE38F,
    ),
    _fp2(
        0x124C9AD43B6CF79BFBF7043DE3811AD0761B0F37A1E26286B0E977C69AA274524E79097A56DC4BD9E1B371C71C718B10,
        0,
    ),
]

_ISO_YDEN = [
    _fp2(
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA8FB,
    ),
    _fp2(
        0,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFA9D3,
    ),
    _fp2(
        0x12,
        0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAA99,
    ),
    ff.FP2_ONE,  # monic x^3 term
]


def _horner(coeffs, x):
    acc = coeffs[-1]
    for c in reversed(coeffs[:-1]):
        acc = ff.fp2_add(ff.fp2_mul(acc, x), c)
    return acc


def iso_map(pt):
    """3-isogeny E'(Fp2) -> E(Fp2), affine in/out."""
    x, y = pt
    x_num = _horner(_ISO_XNUM, x)
    x_den = _horner(_ISO_XDEN, x)
    y_num = _horner(_ISO_YNUM, x)
    y_den = _horner(_ISO_YDEN, x)
    out_x = ff.fp2_mul(x_num, ff.fp2_inv(x_den))
    out_y = ff.fp2_mul(y, ff.fp2_mul(y_num, ff.fp2_inv(y_den)))
    return (out_x, out_y)


# -------------------------------------------------- psi & cofactor clearing

# psi = twist^-1 . Frobenius . twist on E'(Fp2). With the tower w^2 = v,
# v^3 = xi: x picks up xi^((p-1)/3), y picks up xi^((p-1)/2) factors (up to
# inversion convention). The exact constants are FIXED by the validated
# identity psi(P) == [x]P on G2 (p == x mod r for BLS curves); tests assert
# it, and _PSI_CX/_PSI_CY below are derived, not quoted.

# xi^((p-1)/3) and xi^((p-1)/2) — derive both and invert as needed.
_XI = (1, 1)


def _fp2_pow(a, e):
    return ff.fp2_pow(a, e)


_PSI_CX = ff.fp2_inv(_fp2_pow(_XI, (P - 1) // 3))  # applied to conj(x)
_PSI_CY = ff.fp2_inv(_fp2_pow(_XI, (P - 1) // 2))  # applied to conj(y)


def psi(pt):
    """Untwist-Frobenius-twist endomorphism on affine E'(Fp2) points."""
    x, y = pt
    return (
        ff.fp2_mul(ff.fp2_conj(x), _PSI_CX),
        ff.fp2_mul(ff.fp2_conj(y), _PSI_CY),
    )


# psi^2 constants: x factor = (cx * conj(cx)), y factor = (cy * conj(cy))
_PSI2_CX = ff.fp2_mul(_PSI_CX, ff.fp2_conj(_PSI_CX))
_PSI2_CY = ff.fp2_mul(_PSI_CY, ff.fp2_conj(_PSI_CY))


def psi2(pt):
    x, y = pt
    return (ff.fp2_mul(x, _PSI2_CX), ff.fp2_mul(y, _PSI2_CY))


def _jac(aff):
    return G2_GROUP.from_affine(aff)


def _mul_by_x_abs(pt_jac):
    """[|x|] P via double-and-add on the 64-bit parameter."""
    return G2_GROUP.mul_scalar(pt_jac, abs(BLS_X))


def clear_cofactor(pt_affine):
    """Budroni-Pintore cofactor clearing:
    h_eff * P = [x^2 - x - 1]P + [x - 1]psi(P) + psi^2([2]P)
    computed as psi2(2P) + [x](P + psi(P)) - [x... via x-multiplications
    ([x] = -[|x|] since the BLS parameter is negative).
    Returns a Jacobian point in G2.
    """
    G = G2_GROUP
    p_jac = _jac(pt_affine)
    psi_p = _jac(psi(pt_affine))
    t1 = G.neg(_mul_by_x_abs(p_jac))  # [x] P
    t2 = G.neg(_mul_by_x_abs(t1))  # [x^2] P
    t3 = G.neg(_mul_by_x_abs(psi_p))  # [x] psi(P)
    psi2_2p = _jac(psi2(G.to_affine(G.double(p_jac))))
    acc = G.add(t2, G.neg(t1))  # [x^2 - x] P
    acc = G.add(acc, G.neg(p_jac))  # [x^2 - x - 1] P
    acc = G.add(acc, t3)  # + [x] psi(P)
    acc = G.add(acc, G.neg(psi_p))  # - psi(P)
    return G.add(acc, psi2_2p)  # + psi^2([2] P)


# --------------------------------------------------------------- entry point


def hash_to_g2(msg: bytes, dst: bytes = DST_G2):
    """Full hash_to_curve: message -> Jacobian point in G2."""
    u0, u1 = hash_to_field_fp2(msg, 2, dst)
    q0 = iso_map(map_to_curve_sswu(u0))
    q1 = iso_map(map_to_curve_sswu(u1))
    r = G2_GROUP.add(G2_GROUP.from_affine(q0), G2_GROUP.from_affine(q1))
    return clear_cofactor(G2_GROUP.to_affine(r))
