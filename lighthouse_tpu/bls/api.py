"""Host-side BLS API: keys, signatures, signature sets, backend dispatch.

Mirrors the capability surface of the reference's `crypto/bls` crate:
generic wrappers (`GenericPublicKey`/`GenericSignature`/
`GenericSignatureSet`, crypto/bls/src/generic_signature_set.rs:61) over
pluggable backends selected at runtime (the reference selects blst/milagro/
fake_crypto at compile time via `define_mod!`, crypto/bls/src/lib.rs:95-151).

Backends here:
  "ref"  — pure-Python pairing (the milagro analog; ground truth)
  "tpu"  — device batch verification (`ops.batch_verify`), the production
           path: one multi-pairing per batch with RLC scalars
  "fake" — always-valid (the fake_crypto analog for spec tests)

Policy preserved from the reference:
  * pubkeys are validated at deserialization: on-curve, not infinity,
    in-subgroup (blst.rs:126-136 key_validate)
  * signatures are subgroup-checked at verification time (blst.rs:72-81)
  * empty signature-set batches fail (blst.rs:41-43)
"""

from __future__ import annotations

import hashlib
import os
import secrets
import time

from lighthouse_tpu.bls import point_serde
from lighthouse_tpu.bls.hash_to_curve import hash_to_g2
from lighthouse_tpu.common import device_attribution as attribution
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.common.tracing import span
from lighthouse_tpu.crypto import ref_pairing
from lighthouse_tpu.crypto.constants import R
from lighthouse_tpu.crypto.ref_curve import G1 as G1_GROUP
from lighthouse_tpu.crypto.ref_curve import G2 as G2_GROUP

_VERIFY_BATCHES = REGISTRY.counter_vec(
    "lighthouse_tpu_verify_batches_total",
    "verify_signature_sets batches by backend and verdict",
    ("backend", "result"),
)
_VERIFY_SETS = REGISTRY.counter(
    "lighthouse_tpu_verify_sets_total",
    "signature sets entering verify_signature_sets",
)
_VERIFY_BATCH_SECONDS = REGISTRY.histogram(
    "lighthouse_tpu_verify_batch_seconds",
    "end-to-end wall time of one verify_signature_sets batch",
)
_VERIFY_BATCH_SIZE = REGISTRY.histogram(
    "lighthouse_tpu_verify_batch_size",
    "signature sets per verify_signature_sets batch",
    buckets=(1, 4, 16, 64, 256, 1024, 4096, 16384, 65536),
)

INFINITY_PUBKEY_BYTES = bytes([0xC0]) + b"\x00" * 47
INFINITY_SIGNATURE_BYTES = bytes([0xC0]) + b"\x00" * 95

_DEFAULT_BACKEND = os.environ.get("LIGHTHOUSE_TPU_BLS_BACKEND", "ref")


def default_backend() -> str:
    """The process-default backend (env-selected) — for callers that
    must verify on the DEFAULT backend regardless of their chain's
    (deposit signatures, spec semantics) while routing through the
    verification bus, which would otherwise substitute its own."""
    return _DEFAULT_BACKEND


class BlsError(ValueError):
    pass


# ------------------------------------------------------------------ secrets


class SecretKey:
    __slots__ = ("_sk",)

    def __init__(self, scalar: int):
        if not 1 <= scalar < R:
            raise BlsError("secret key out of range")
        self._sk = scalar

    @classmethod
    def from_bytes(cls, data: bytes) -> "SecretKey":
        if len(data) != 32:
            raise BlsError("secret key: expected 32 bytes")
        return cls(int.from_bytes(data, "big"))

    @classmethod
    def random(cls) -> "SecretKey":
        return cls(1 + secrets.randbelow(R - 1))

    def to_bytes(self) -> bytes:
        return self._sk.to_bytes(32, "big")

    def public_key(self) -> "PublicKey":
        pt = G1_GROUP.mul_scalar(G1_GROUP.generator, self._sk)
        return PublicKey(pt)

    def sign(self, message: bytes) -> "Signature":
        h = hash_to_g2(message)
        return Signature(G2_GROUP.mul_scalar(h, self._sk))


class Keypair:
    __slots__ = ("sk", "pk")

    def __init__(self, sk: SecretKey):
        self.sk = sk
        self.pk = sk.public_key()


def interop_keypairs(n: int) -> list[Keypair]:
    """Deterministic interop keypairs (common/eth2_interop_keypairs analog):
    sk_i = int(sha256(le32(i+1))) % r, nonzero-adjusted."""
    out = []
    for i in range(n):
        digest = hashlib.sha256((i + 1).to_bytes(32, "little")).digest()
        sk = int.from_bytes(digest, "little") % R
        out.append(Keypair(SecretKey(sk if sk else 1)))
    return out


# ------------------------------------------------------------------- points


class PublicKey:
    """Validated G1 point (never infinity, always in-subgroup).

    `validator_index`/`cache` are set by the chain's PubkeyCache so the
    TPU backend can ship table indices instead of points (the
    validator_pubkey_cache.rs analog's device half)."""

    __slots__ = ("point", "_bytes", "validator_index", "cache")

    def __init__(self, point_jacobian, compressed: bytes | None = None):
        self.point = point_jacobian
        self._bytes = compressed
        self.validator_index = None
        self.cache = None

    @classmethod
    def from_bytes(cls, data: bytes) -> "PublicKey":
        pt = point_serde.g1_decompress(bytes(data))
        if G1_GROUP.is_infinity(pt):
            raise BlsError("pubkey: point at infinity rejected")
        if not G1_GROUP.in_subgroup(pt):
            raise BlsError("pubkey: not in subgroup")
        return cls(pt, bytes(data))

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = point_serde.g1_compress(self.point)
        return self._bytes

    def __eq__(self, other):
        return isinstance(other, PublicKey) and self.to_bytes() == other.to_bytes()

    def __hash__(self):
        return hash(self.to_bytes())


class Signature:
    """G2 point; subgroup checked at verification (or explicitly)."""

    __slots__ = ("point", "_bytes", "_subgroup_ok")

    def __init__(self, point_jacobian, compressed: bytes | None = None):
        self.point = point_jacobian
        self._bytes = compressed
        self._subgroup_ok = None

    @classmethod
    def from_bytes(cls, data: bytes) -> "Signature":
        return cls(point_serde.g2_decompress(bytes(data)), bytes(data))

    def to_bytes(self) -> bytes:
        if self._bytes is None:
            self._bytes = point_serde.g2_compress(self.point)
        return self._bytes

    def is_infinity(self) -> bool:
        return G2_GROUP.is_infinity(self.point)

    def in_subgroup(self) -> bool:
        if self._subgroup_ok is None:
            self._subgroup_ok = G2_GROUP.in_subgroup(self.point)
        return self._subgroup_ok

    def __eq__(self, other):
        return isinstance(other, Signature) and self.to_bytes() == other.to_bytes()


def aggregate_signatures(sigs) -> Signature:
    sigs = list(sigs)
    if not sigs:
        # spec Aggregate() precondition: n >= 1 (the official bls
        # aggregate vectors expect an error for the empty list)
        raise BlsError("aggregate of zero signatures")
    acc = G2_GROUP.infinity
    for s in sigs:
        acc = G2_GROUP.add(acc, s.point)
    return Signature(acc)


def aggregate_public_keys(pubkeys) -> PublicKey:
    if not pubkeys:
        raise BlsError("aggregate of zero pubkeys")
    acc = G1_GROUP.infinity
    for p in pubkeys:
        acc = G1_GROUP.add(acc, p.point)
    return PublicKey(acc)


def aggregate_pubkeys_bytes(pubkey_bytes_list) -> bytes:
    """Aggregate compressed pubkeys -> compressed aggregate (may be the
    infinity encoding if keys cancel; used for SyncCommittee aggregates)."""
    acc = G1_GROUP.infinity
    for data in pubkey_bytes_list:
        acc = G1_GROUP.add(acc, point_serde.g1_decompress(bytes(data)))
    return point_serde.g1_compress(acc)


# --------------------------------------------------------------- signature sets


class SignatureSet:
    """One verification unit: signature over message by >= 1 pubkeys
    (pre-aggregated by point addition), the analog of
    `GenericSignatureSet` (generic_signature_set.rs:61)."""

    __slots__ = ("signature", "pubkeys", "message")

    def __init__(self, signature: Signature, pubkeys, message: bytes):
        if not pubkeys:
            raise BlsError("signature set with no pubkeys")
        self.signature = signature
        self.pubkeys = list(pubkeys)
        self.message = bytes(message)


def _verify_one_ref(sset: SignatureSet) -> bool:
    """Single-set ground-truth check, staged under the tracer: each
    pipeline phase (subgroup check, aggregation, hash-to-curve, affine
    conversion, Miller loop, final exponentiation) is its own leaf span
    so `lighthouse_tpu_verify_stage_seconds{stage=...}` attributes the
    wall time stage-by-stage."""
    with span("verify/subgroup_check"):
        bad = (
            sset.signature.is_infinity()
            or not sset.signature.in_subgroup()
        )
    if bad:
        return False
    with span("verify/pubkey_aggregation", n_keys=len(sset.pubkeys)):
        agg_pk = G1_GROUP.infinity
        for p in sset.pubkeys:
            agg_pk = G1_GROUP.add(agg_pk, p.point)
    with span("verify/hash_to_curve"):
        h = hash_to_g2(sset.message)
    with span("verify/to_affine"):
        pairs = [
            (G1_GROUP.to_affine(p), G2_GROUP.to_affine(q))
            for p, q in (
                (agg_pk, h),
                (G1_GROUP.neg(G1_GROUP.generator), sset.signature.point),
            )
        ]
    # multi_pairing_is_one carries the verify/miller_loop and
    # verify/final_exp stage spans itself
    return ref_pairing.multi_pairing_is_one(pairs)


def verify(pk: PublicKey, message: bytes, sig: Signature) -> bool:
    return _verify_one_ref(SignatureSet(sig, [pk], message))


def fast_aggregate_verify(pubkeys, message: bytes, sig: Signature) -> bool:
    if not pubkeys:
        return False
    return _verify_one_ref(SignatureSet(sig, pubkeys, message))


def eth_fast_aggregate_verify(pubkeys, message: bytes, sig: Signature) -> bool:
    """Ethereum variant: the infinity signature over zero pubkeys is valid
    (empty sync aggregates)."""
    if not pubkeys and sig.to_bytes() == INFINITY_SIGNATURE_BYTES:
        return True
    return fast_aggregate_verify(pubkeys, message, sig)


def aggregate_verify(pubkeys, messages, sig: Signature) -> bool:
    """Distinct-message aggregate verification."""
    if not pubkeys or len(pubkeys) != len(messages):
        return False
    if sig.is_infinity() or not sig.in_subgroup():
        return False
    g1s = [p.point for p in pubkeys] + [G1_GROUP.neg(G1_GROUP.generator)]
    g2s = [hash_to_g2(m) for m in messages] + [sig.point]
    return ref_pairing.pairing_check_points(g1s, g2s)


# ----------------------------------------------------------- batch dispatch


def _journal_batch(
    journal, consumer, ok, n_sets, backend, slot, extra=None,
    individual=False,
):
    """One `signature_batch` journal event per dispatched batch, with
    the consumer label and (when the tpu backend marshalled it on this
    thread) the batch's exact lane/waste economics. Draining the
    thread-local pending records even when journal is None keeps the
    window scoped to one call."""
    records = attribution.take_batches()
    if journal is None:
        return
    attrs = {"consumer": consumer, "n_sets": n_sets, "backend": backend}
    if individual:
        attrs["individual"] = True
    if len(records) == 1 and records[0].get("lanes") is not None:
        r = records[0]
        attrs["lanes"] = r["lanes"]
        attrs["waste"] = r.get("waste", 0)
        attrs["amortized_fixed_ms"] = r.get("amortized_fixed_ms")
    if extra:
        attrs.update(extra)
    journal.emit(
        "signature_batch",
        slot=slot,
        outcome="ok" if ok else "failed",
        **attrs,
    )


def verify_signature_sets(
    sets,
    backend: str | None = None,
    seed: int | None = None,
    consumer: str | None = None,
    journal=None,
    slot: int | None = None,
    journal_attrs: dict | None = None,
) -> bool:
    """Batch-verify signature sets — the north-star boundary
    (blst.rs:36-119 verify_signature_sets).

    Empty batches fail. On the tpu backend the whole batch becomes one
    device multi-pairing with >=64-bit RLC scalars; "ref" verifies each set
    with an independent pairing check (ground truth); "fake" returns True.

    `consumer` names who pays this batch (device_attribution.CONSUMERS;
    the consumer-label lint requires it explicitly at every package call
    site). `journal` (a chain's events journal) makes the batch a
    `signature_batch` forensic event carrying the consumer, set count,
    and — on the tpu backend — the exact lanes/padding-waste economics;
    `slot`/`journal_attrs` enrich that event.
    """
    sets = list(sets)
    if not sets:
        return False
    backend = backend or _DEFAULT_BACKEND
    consumer = attribution.note_sets(consumer, len(sets))
    _VERIFY_SETS.inc(len(sets))
    _VERIFY_BATCH_SIZE.observe(len(sets))
    attribution.begin_batch_window()
    t0 = time.perf_counter()
    with _VERIFY_BATCH_SECONDS.time(), span(
        "verify", n_sets=len(sets), backend=backend
    ):
        if backend == "fake":
            result = True
        elif backend == "ref":
            result = all(_verify_one_ref(s) for s in sets)
        elif backend == "tpu":
            from lighthouse_tpu.bls.tpu_backend import (
                verify_signature_sets_tpu,
            )

            result = verify_signature_sets_tpu(
                sets, seed=seed, consumer=consumer
            )
        else:
            raise BlsError(f"unknown BLS backend {backend!r}")
    if backend != "tpu":
        # host backends have no lane padding; the batch still counts
        attribution.note_batch(
            consumer, "bls", lanes=None, live=len(sets),
            duration_s=time.perf_counter() - t0,
        )
    _VERIFY_BATCHES.labels(backend, "ok" if result else "fail").inc()
    _journal_batch(
        journal, consumer, result, len(sets), backend, slot,
        extra=journal_attrs,
    )
    return result


def verify_signature_sets_shared(
    submissions,
    backend: str | None = None,
    seed: int | None = None,
    extra_sets=None,
) -> tuple:
    """ONE dispatch spanning several consumers' set batches — the
    verification bus's boundary. `submissions` is a list of
    (sets, consumer) pairs; the whole collection becomes a single
    batch (one device multi-pairing on the tpu backend) while the
    per-consumer attribution fans out: `device_sets_total` counts each
    contributor's own sets, and the batch economics (participation,
    proportional device seconds/waste, the SHARED amortized fixed
    cost) distribute via `device_attribution.begin_shared_window`.

    `extra_sets` are ATTRIBUTION-FREE riders — the device-plane canary
    sentinels the bus splices into guarded batches. They join the
    device dispatch but appear in NEITHER side of the
    attribution_complete equality (no `note_sets`, no contribs entry,
    no journal n_sets), and a batch that is empty apart from riders is
    still empty (no canary-only dispatches).

    Returns `(ok, record)` where `record` is the batch-economics dict
    (lanes/waste/amortized_fixed_ms when the tpu marshal ran) or None.
    NO journal emission happens here: the bus emits one
    `signature_batch` event per contributing submission itself, with a
    shared batch id, so `attribution_complete` holds per consumer."""
    contribs = []
    flat = []
    for sets, consumer in submissions:
        sets = list(sets)
        if not sets:
            continue
        consumer = attribution.note_sets(consumer, len(sets))
        contribs.append((consumer, len(sets)))
        flat.extend(sets)
    if not flat:
        return False, None
    if extra_sets:
        flat = flat + list(extra_sets)
    backend = backend or _DEFAULT_BACKEND
    # the largest contributor labels the raw backend call; the shared
    # window redistributes the actual accounting over every contributor
    primary = max(contribs, key=lambda cn: cn[1])[0]
    _VERIFY_SETS.inc(len(flat))
    _VERIFY_BATCH_SIZE.observe(len(flat))
    attribution.begin_shared_window(contribs)
    t0 = time.perf_counter()
    try:
        with _VERIFY_BATCH_SECONDS.time(), span(
            "verify",
            n_sets=len(flat),
            backend=backend,
            n_consumers=len(contribs),
        ):
            if backend == "fake":
                result = True
            elif backend == "ref":
                result = all(_verify_one_ref(s) for s in flat)
            elif backend == "tpu":
                from lighthouse_tpu.bls.tpu_backend import (
                    verify_signature_sets_tpu,
                )

                result = verify_signature_sets_tpu(
                    flat, seed=seed, consumer=primary
                )
            else:
                raise BlsError(f"unknown BLS backend {backend!r}")
        if backend != "tpu":
            attribution.note_batch(
                primary, "bls", lanes=None, live=len(flat),
                duration_s=time.perf_counter() - t0,
            )
    finally:
        # a raising dispatch must not leave the shared window open on
        # this thread (the next unrelated batch would fan out over it)
        records = attribution.take_batches()
    _VERIFY_BATCHES.labels(backend, "ok" if result else "fail").inc()
    record = records[0] if records else None
    if record is not None:
        record.setdefault(
            "duration_s", time.perf_counter() - t0
        )
    return result, record


def verify_signature_set_batches(
    batches,
    backend: str | None = None,
    seed: int | None = None,
    consumer: str | None = None,
    journal=None,
    slot: int | None = None,
) -> list:
    """Verify several batches with host/device overlap: on the tpu
    backend batch N+1 marshals while batch N verifies on device
    (double-buffered dispatch, SURVEY §2.6 pipeline row). Returns one
    bool per batch; empty batches are False."""
    batches = [list(b) for b in batches]
    backend = backend or _DEFAULT_BACKEND
    if backend == "tpu":
        from lighthouse_tpu.bls.tpu_backend import (
            verify_signature_set_batches_tpu,
        )

        consumer = attribution.normalize(consumer)
        attribution.begin_batch_window()
        results = verify_signature_set_batches_tpu(
            batches, seed=seed, consumer=consumer
        )
        attribution.take_batches()  # economics live in the registry
        for b, ok in zip(batches, results):
            if not b:
                continue
            attribution.note_sets(consumer, len(b))
            if journal is not None:
                journal.emit(
                    "signature_batch",
                    slot=slot,
                    outcome="ok" if ok else "failed",
                    consumer=consumer,
                    n_sets=len(b),
                    backend=backend,
                    streamed=True,
                )
        return results
    return [
        verify_signature_sets(
            b, backend=backend, consumer=consumer, journal=journal,
            slot=slot,
        )
        if b
        else False
        for b in batches
    ]


def verify_signature_sets_individually(
    sets,
    backend: str | None = None,
    consumer: str | None = None,
    journal=None,
    slot: int | None = None,
) -> list:
    """Per-set verdicts for a batch — the exact-fallback half of the
    reference's batch semantics (attestation batch.rs:115-131): when the
    RLC batch fails, recover which sets are bad. On the tpu backend this
    is ONE extra device call (per-set pairing residues), not a round trip
    per set. Empty input -> empty list."""
    sets = list(sets)
    if not sets:
        return []
    backend = backend or _DEFAULT_BACKEND
    consumer = attribution.note_sets(consumer, len(sets))
    attribution.begin_batch_window()
    t0 = time.perf_counter()
    if backend == "fake":
        out = [True] * len(sets)
    elif backend == "ref":
        out = [_verify_one_ref(s) for s in sets]
    elif backend == "tpu":
        from lighthouse_tpu.bls.tpu_backend import (
            verify_signature_sets_tpu_individual,
        )

        out = verify_signature_sets_tpu_individual(
            sets, consumer=consumer
        )
    else:
        raise BlsError(f"unknown BLS backend {backend!r}")
    if backend != "tpu":
        attribution.note_batch(
            consumer, "bls", lanes=None, live=len(sets),
            duration_s=time.perf_counter() - t0,
        )
    _journal_batch(
        journal, consumer, all(out), len(sets), backend, slot,
        individual=True,
    )
    return out
