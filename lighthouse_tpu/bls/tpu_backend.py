"""TPU backend for `verify_signature_sets`: host marshalling -> device batch.

The host side of the north-star boundary: converts heterogeneous
SignatureSets into the static-shaped, masked device arrays that
`ops.batch_verify.verify_signature_sets` consumes, with bucketed padding so
jit recompiles only per (set-bucket, key-bucket) shape class — the
TPU-native replacement for the reference's dynamic per-set heap vectors
(crypto/bls/src/impls/blst.rs:90-108).

Messages are hashed to G2 on the host (hash_to_curve), pubkey/signature
points are shipped as affine Montgomery limbs. Signature subgroup checks
run host-side before dispatch, mirroring blst.rs:72-81.
"""

import secrets

import numpy as np

import jax

from lighthouse_tpu.bls.hash_to_curve import hash_to_g2
from lighthouse_tpu.crypto.ref_curve import G1 as G1_GROUP
from lighthouse_tpu.crypto.ref_curve import G2 as G2_GROUP
from lighthouse_tpu.ops import batch_verify, curve, fieldb as fb, fp2

_jitted = None


def _get_fn():
    global _jitted
    if _jitted is None:
        _jitted = jax.jit(batch_verify.verify_signature_sets)
    return _jitted


def _bucket(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _pack_g1_affine(affs):
    xs = np.stack([fb.pack_ints([a[0] if a else 0]) for a in affs])
    ys = np.stack([fb.pack_ints([a[1] if a else 0]) for a in affs])
    return fb.to_mont(xs), fb.to_mont(ys)


def _pack_g2_affine(affs):
    zero = ((0, 0), (0, 0))
    xs = fp2.pack([(a or zero)[0] for a in affs])
    ys = fp2.pack([(a or zero)[1] for a in affs])
    return (fb.to_mont(xs), fb.to_mont(ys))


def verify_signature_sets_tpu(sets, seed: int | None = None) -> bool:
    # host-side policy checks (exact reference semantics)
    for s in sets:
        if s.signature.is_infinity() or not s.signature.in_subgroup():
            return False

    n_sets = len(sets)
    max_keys = max(len(s.pubkeys) for s in sets)
    s_bucket = _bucket(n_sets, 4)
    k_bucket = _bucket(max_keys, 1)

    rng = np.random.default_rng(seed) if seed is not None else None

    msgs, sigs, pk_rows, key_mask = [], [], [], []
    for s in sets:
        msgs.append(G2_GROUP.to_affine(hash_to_g2(s.message)))
        sigs.append(G2_GROUP.to_affine(s.signature.point))
        row = [G1_GROUP.to_affine(p.point) for p in s.pubkeys]
        key_mask.append(
            [True] * len(row) + [False] * (k_bucket - len(row))
        )
        pk_rows.append(row + [None] * (k_bucket - len(row)))
    for _ in range(s_bucket - n_sets):
        msgs.append(None)
        sigs.append(None)
        pk_rows.append([None] * k_bucket)
        key_mask.append([False] * k_bucket)

    set_mask = np.array(
        [True] * n_sets + [False] * (s_bucket - n_sets), dtype=bool
    )
    key_mask = np.array(key_mask, dtype=bool)

    if rng is not None:
        scalars = [
            int(rng.integers(1, 1 << 63)) for _ in range(s_bucket)
        ]
    else:
        scalars = [
            1 + secrets.randbelow((1 << batch_verify.RAND_BITS) - 1)
            for _ in range(s_bucket)
        ]
    rand_bits = curve.scalars_to_bits(scalars, batch_verify.RAND_BITS)

    pk_flat = [p for row in pk_rows for p in row]
    pk_x, pk_y = _pack_g1_affine(pk_flat)
    pubkeys = (
        np.asarray(pk_x).reshape(s_bucket, k_bucket, 1, fb.NB),
        np.asarray(pk_y).reshape(s_bucket, k_bucket, 1, fb.NB),
    )

    ok = _get_fn()(
        _pack_g2_affine(msgs),
        _pack_g2_affine(sigs),
        pubkeys,
        key_mask,
        rand_bits,
        set_mask,
    )
    return bool(np.asarray(ok))
