"""TPU backend for `verify_signature_sets`: host marshalling -> device batch.

The host side of the north-star boundary: converts heterogeneous
SignatureSets into the static-shaped, masked device arrays that
`ops.batch_verify.verify_signature_sets` consumes, with bucketed padding so
jit recompiles only per (set-bucket, key-bucket) shape class — the
TPU-native replacement for the reference's dynamic per-set heap vectors
(crypto/bls/src/impls/blst.rs:90-108).

Hot-path design (SURVEY §7 hard part 4, validator_pubkey_cache.rs:9-24):
  * pubkeys tagged by the chain's PubkeyCache ship as int32 table indices;
    the device gathers affine Montgomery limbs from the HBM-resident
    DevicePubkeyTable — zero per-pubkey Python work per batch.
  * message hash_to_g2 results are memoized — a slot's 30k attestation
    sets share ~committee-count distinct messages, so the cache collapses
    the per-set cost to a dict hit.
  * signature/message Jacobian->affine conversion uses one simultaneous
    (Montgomery-trick) inversion per batch instead of one Fp2 inversion
    per point.

Signature subgroup checks run host-side before dispatch, mirroring
blst.rs:72-81.
"""

import secrets
import time

import numpy as np

import jax

from lighthouse_tpu.bls.hash_to_curve import hash_to_g2
from lighthouse_tpu.common import device_attribution as attribution
from lighthouse_tpu.common.compile_ledger import LEDGER
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.common.tracing import span
from lighthouse_tpu.crypto.ref_curve import G1 as G1_GROUP
from lighthouse_tpu.crypto.ref_curve import G2 as G2_GROUP
from lighthouse_tpu.device_plane import GUARD, host_device_scope
from lighthouse_tpu.ops import batch_verify, curve, fieldb as fb, fp2

# jit-compilation observability: "wrapper" events track the python-side
# impl-keyed cache (a miss means a NEW jax.jit object); "xla" events
# track the jitted object's own trace cache (a retrace means a new
# (shape-bucket, dtype) class compiled — the cost bucketed padding
# exists to bound)
_JIT_EVENTS = REGISTRY.counter_vec(
    "lighthouse_tpu_jit_cache_events_total",
    "jit cache hits vs (re)traces per jitted verify entry point",
    ("fn", "layer", "event"),
)
_MSG_CACHE_EVENTS = REGISTRY.counter_vec(
    "lighthouse_tpu_msg_cache_events_total",
    "hash_to_g2 memo hits vs misses during batch marshalling",
    ("event",),
)
_MARSHAL_SECONDS = REGISTRY.histogram_vec(
    "lighthouse_tpu_marshal_seconds",
    "host marshalling wall time per phase (points / pack)",
    ("phase",),
)

def _note_wrapper_event(fn_name: str, hit: bool):
    _JIT_EVENTS.labels(fn_name, "wrapper", "hit" if hit else "trace").inc()


def _note_xla_events(fn_name: str, jitted, shape="", duration_s=None):
    """Classify this dispatch as retrace (the jitted object's trace
    cache grew — a new shape class compiled) or hit, via the process
    compile LEDGER which owns the cache-size bookkeeping (read-modify-
    write under its lock — concurrent worker dispatches must not count
    one compile as two retraces) and records the structured entry with
    impl key, shape bucket, and dispatch wall time. Version-tolerant —
    older jax without _cache_size records warm."""
    grew = LEDGER.note_dispatch(
        fn_name, jitted, _impl_key(), shape, duration_s=duration_s
    )
    if grew is None:
        return  # unclassifiable (old jax): the xla layer goes dark
    if grew > 0:
        _JIT_EVENTS.labels(fn_name, "xla", "retrace").inc(grew)
    else:
        _JIT_EVENTS.labels(fn_name, "xla", "hit").inc()

# jit caches keyed by the full impl choice — the LIGHTHOUSE_TPU_IMPL
# selection AND the MXU knobs (MXU_REDC/MXU_CONV) that fieldb reads at
# trace time — so flipping ANY of them mid-process retraces instead of
# silently reusing a stale trace
_jitted: dict = {}
_jitted_indexed: dict = {}

# host-marshalling telemetry for the last dispatched batch (read by bench)
LAST_HOST_STATS: dict = {}

# device-dispatch counters (read by tests asserting the <=2-call fallback)
CALL_COUNTS = {"batch": 0, "individual": 0}


def _use_pallas() -> bool:
    """The fused VMEM kernels (5,425-9,824 sigs/s measured vs the XLA
    graph's 1,470 — PERF_NOTES.md) lower only on real TPU hardware; the
    CPU mesh keeps the XLA graph. LIGHTHOUSE_TPU_IMPL=xla|pallas
    overrides the choice; any other value raises (fail-loud, matching
    bench_impl's exit-4 rule — a typo must not silently measure the
    auto-selected path)."""
    import os

    forced = os.environ.get("LIGHTHOUSE_TPU_IMPL")
    if forced == "pallas":
        return True
    if forced == "xla":
        return False
    # "" follows the shell convention for unset (tfield.use_mxu_redc
    # treats its knob the same way)
    if forced:
        raise ValueError(
            f"LIGHTHOUSE_TPU_IMPL={forced!r}: expected 'xla', 'pallas',"
            " or unset"
        )
    try:
        return jax.default_backend() in ("tpu", "axon")
    # lint: allow(except-swallow): no readable backend == not a TPU
    except Exception:
        return False


def _impl_key():
    """(use_pallas, MXU_REDC form, MXU_CONV on, ladder kind, FP12
    squaring form, fused tail) — everything read at trace time that
    changes the compiled program, NORMALIZED the way the kernels
    consume it (tfield.use_mxu_redc maps "1"/"i8" to one form and
    resolves the on-TPU default; window_ladder.ladder_impl resolves
    the default window kernel; fieldb only tests MXU_CONV == "1") so
    equivalent spellings share one trace instead of recompiling."""
    from lighthouse_tpu.ops import tfield, tower
    from lighthouse_tpu.ops.pallas_tail import use_fused_tail
    from lighthouse_tpu.ops.window_ladder import ladder_impl

    import os

    return (
        _use_pallas(),
        tfield.use_mxu_redc(),
        os.environ.get("LIGHTHOUSE_TPU_MXU_CONV") == "1",
        ladder_impl(),
        tower.use_fp12_sqr(),
        use_fused_tail(),
    )


def _verify_impl(use_pallas: bool):
    if use_pallas:
        import functools

        from lighthouse_tpu.ops.pallas_tail import use_fused_tail

        return functools.partial(
            batch_verify.verify_signature_sets_pallas,
            tail=use_fused_tail(),
        )
    return batch_verify.verify_signature_sets


def _get_fn():
    """Jitted verify fn for the CURRENT impl choice. Keyed by the choice
    (not cached once) so flipping LIGHTHOUSE_TPU_IMPL or an MXU knob
    mid-process takes effect on the next dispatch instead of being baked
    into the first trace."""
    key = _impl_key()
    fn = _jitted.get(key)
    _note_wrapper_event("verify", fn is not None)
    if fn is None:
        fn = _jitted[key] = jax.jit(_verify_impl(key[0]))
    return fn


def _indexed_verify(
    use_pallas, msgs, sigs, table_x, table_y, indices, key_mask,
    rand_bits, set_mask,
):
    """Gather pubkey limb rows by validator index on device, then verify."""
    import jax.numpy as jnp

    pk_x = jnp.take(table_x, indices, axis=0)  # (S, K, 1, NB)
    pk_y = jnp.take(table_y, indices, axis=0)
    return _verify_impl(use_pallas)(
        msgs, sigs, (pk_x, pk_y), key_mask, rand_bits, set_mask
    )


def _grouped_impl(use_pallas: bool):
    if use_pallas:
        import functools

        from lighthouse_tpu.ops.pallas_tail import use_fused_tail

        return functools.partial(
            batch_verify.verify_signature_sets_grouped_pallas,
            tail=use_fused_tail(),
        )
    return batch_verify.verify_signature_sets_grouped


def _grouped_indexed_verify(
    use_pallas, msgs, sigs, table_x, table_y, indices, key_mask,
    rand_bits, set_mask, group_mask,
):
    import jax.numpy as jnp

    pk_x = jnp.take(table_x, indices, axis=0)  # (G, Sg, K, 1, NB)
    pk_y = jnp.take(table_y, indices, axis=0)
    return _grouped_impl(use_pallas)(
        msgs, sigs, (pk_x, pk_y), key_mask, rand_bits, set_mask,
        group_mask,
    )


_jitted_grouped: dict = {}


def _get_grouped_fns():
    import functools

    key = _impl_key()
    pair = _jitted_grouped.get(key)
    _note_wrapper_event("verify_grouped", pair is not None)
    if pair is None:
        pair = _jitted_grouped[key] = (
            jax.jit(_grouped_impl(key[0])),
            jax.jit(functools.partial(_grouped_indexed_verify, key[0])),
        )
    return pair


def _get_indexed_fn():
    import functools

    key = _impl_key()
    fn = _jitted_indexed.get(key)
    _note_wrapper_event("verify_indexed", fn is not None)
    if fn is None:
        fn = _jitted_indexed[key] = jax.jit(
            functools.partial(_indexed_verify, key[0])
        )
    return fn


def _bucket(n: int, minimum: int) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


# --------------------------------------------------------- message hashing

_MSG_CACHE: dict = {}
_MSG_CACHE_MAX = 16_384


def _msg_affine(message: bytes):
    """Memoized hash_to_g2 -> affine ints. Attestation batches repeat the
    same signing root across a whole committee."""
    message = bytes(message)
    hit = _MSG_CACHE.get(message)
    if hit is None:
        _MSG_CACHE_EVENTS.labels("miss").inc()
        hit = G2_GROUP.to_affine(hash_to_g2(message))
        if len(_MSG_CACHE) >= _MSG_CACHE_MAX:
            _MSG_CACHE.clear()
        _MSG_CACHE[message] = hit
    else:
        _MSG_CACHE_EVENTS.labels("hit").inc()
    return hit


# ----------------------------------------------- batched affine conversion

_F2 = G2_GROUP.F


def batch_to_affine_g2(points):
    """Jacobian G2 points -> affine, ONE Fp2 inversion total (Montgomery
    simultaneous-inversion trick). Infinity points -> None."""
    zs, keep = [], []
    for i, pt in enumerate(points):
        if not G2_GROUP.is_infinity(pt):
            zs.append(pt[2])
            keep.append(i)
    out = [None] * len(points)
    if not zs:
        return out
    # prefix products
    prefix = [zs[0]]
    for z in zs[1:]:
        prefix.append(_F2.mul(prefix[-1], z))
    acc = _F2.inv(prefix[-1])
    invs = [None] * len(zs)
    for j in range(len(zs) - 1, 0, -1):
        invs[j] = _F2.mul(acc, prefix[j - 1])
        acc = _F2.mul(acc, zs[j])
    invs[0] = acc
    for j, i in enumerate(keep):
        x, y, _ = points[i]
        zi2 = _F2.sqr(invs[j])
        out[i] = (_F2.mul(x, zi2), _F2.mul(y, _F2.mul(zi2, invs[j])))
    return out


def _pack_g1_affine(affs):
    xs = np.stack([fb.pack_ints([a[0] if a else 0]) for a in affs])
    ys = np.stack([fb.pack_ints([a[1] if a else 0]) for a in affs])
    return fb.to_mont(xs), fb.to_mont(ys)


def _pack_g2_affine(affs):
    zero = ((0, 0), (0, 0))
    xs = fp2.pack([(a or zero)[0] for a in affs])
    ys = fp2.pack([(a or zero)[1] for a in affs])
    return (fb.to_mont(xs), fb.to_mont(ys))


def _rlc_scalars(n: int, seed):
    """Full 64-bit RLC coefficients (blst.rs:15 RAND_BITS), seeded for
    deterministic tests or from the OS entropy pool in production."""
    top = 1 << batch_verify.RAND_BITS
    if seed is not None:
        rng = np.random.default_rng(seed)
        return [
            int(rng.integers(1, top, dtype=np.uint64)) for _ in range(n)
        ]
    return [1 + secrets.randbelow(top - 1) for _ in range(n)]


def _table_for(sets):
    """The shared DevicePubkeyTable when EVERY pubkey in every set is
    tagged by one PubkeyCache covering its index; else None."""
    cache = None
    for s in sets:
        for p in s.pubkeys:
            c = getattr(p, "cache", None)
            idx = getattr(p, "validator_index", None)
            if c is None or idx is None:
                return None
            if cache is None:
                cache = c
            elif c is not cache:
                return None
    if cache is None:
        return None
    table = cache.device_table()
    return table if table.count == len(cache) else None


class _Marshalled:
    """Static-shaped device inputs for one batch of SignatureSets."""

    __slots__ = (
        "msgs",
        "sigs",
        "key_mask",
        "set_mask",
        "table",
        "indices",
        "pubkeys",
        "s_bucket",
        "k_bucket",
        "timings",
        # message-grouped layout (None/False when flat)
        "grouped",
        "group_mask",
        "n_groups",
    )


def _grouping_enabled() -> bool:
    import os

    return os.environ.get("LIGHTHOUSE_TPU_GROUPED") != "0"


def _group_plan(sets):
    """Order-preserving message→set-index grouping, or None when the
    merge does not pay: grouping must at least HALVE the pair count
    (G*2 <= S), and the padded (G, Sg) grid must not blow past twice
    the flat bucket (pathologically skewed group sizes)."""
    by_msg: dict[bytes, list] = {}
    for i, s in enumerate(sets):
        by_msg.setdefault(bytes(s.message), []).append(i)
    n_sets = len(sets)
    G = len(by_msg)
    if G * 2 > n_sets:
        return None
    sg_b = _bucket(max(len(ix) for ix in by_msg.values()), 1)
    g_b = _bucket(G, 1)
    if g_b * sg_b > 2 * _bucket(n_sets, 4):
        return None
    return list(by_msg.items())


def _marshal(sets, allow_grouped: bool = True) -> _Marshalled:
    """Marshal a batch, preferring the message-grouped grid layout
    (G distinct messages -> G+1 Miller loops instead of S+1; the
    committee-shaped attestation load has S/G >= 100). The per-set
    fallback path marshals with allow_grouped=False — per-set verdicts
    need per-set pairs."""
    if allow_grouped and _grouping_enabled():
        plan = _group_plan(sets)
        if plan is not None:
            return _marshal_grouped(sets, plan)
    return _marshal_flat(sets)


def _marshal_grouped(sets, groups) -> _Marshalled:
    """Grid marshal: groups -> (g_bucket, sg_bucket) lanes, messages one
    per group. Padding lanes carry None sigs + all-False key masks."""
    t0 = time.perf_counter()
    m = _Marshalled()
    G = len(groups)
    g_b = _bucket(G, 1)
    sg_b = _bucket(max(len(ix) for _, ix in groups), 1)
    m.grouped = True
    m.n_groups = G
    m.s_bucket = g_b * sg_b
    m.k_bucket = _bucket(max(len(s.pubkeys) for s in sets), 1)

    with span("verify/marshal/points"):
        group_msgs = [_msg_affine(sets[ix[0]].message) for _, ix in groups]
        group_msgs += [None] * (g_b - G)
        m.group_mask = np.array(
            [True] * G + [False] * (g_b - G), dtype=bool
        )

        # lane order: group-major, each group padded to sg_b
        order: list = []
        for _, ix in groups:
            order += list(ix) + [None] * (sg_b - len(ix))
        order += [None] * ((g_b - G) * sg_b)

        sig_aff = batch_to_affine_g2([s.signature.point for s in sets])
        sigs = [None if i is None else sig_aff[i] for i in order]
    t1 = time.perf_counter()

    with span("verify/marshal/pack"):
        m.set_mask = np.array(
            [i is not None for i in order], dtype=bool
        ).reshape(g_b, sg_b)
        m.key_mask = np.array(
            [
                [False] * m.k_bucket
                if i is None
                else [True] * len(sets[i].pubkeys)
                + [False] * (m.k_bucket - len(sets[i].pubkeys))
                for i in order
            ],
            dtype=bool,
        ).reshape(g_b, sg_b, m.k_bucket)

        m.table = _table_for(sets)
        if m.table is not None:
            indices = np.full((len(order), m.k_bucket), -1, dtype=np.int32)
            for lane, i in enumerate(order):
                if i is None:
                    continue
                for k, p in enumerate(sets[i].pubkeys):
                    indices[lane, k] = p.validator_index
            m.indices = m.table.gather_indices(indices).reshape(
                g_b, sg_b, m.k_bucket
            )
            m.pubkeys = None
        else:
            pk_rows = []
            for i in order:
                row = (
                    []
                    if i is None
                    else [G1_GROUP.to_affine(p.point) for p in sets[i].pubkeys]
                )
                pk_rows.append(row + [None] * (m.k_bucket - len(row)))
            pk_flat = [p for row in pk_rows for p in row]
            pk_x, pk_y = _pack_g1_affine(pk_flat)
            m.indices = None
            m.pubkeys = (
                np.asarray(pk_x).reshape(g_b, sg_b, m.k_bucket, 1, fb.NB),
                np.asarray(pk_y).reshape(g_b, sg_b, m.k_bucket, 1, fb.NB),
            )
        m.msgs = _pack_g2_affine(group_msgs)
        m.sigs = tuple(
            np.asarray(c).reshape(g_b, sg_b, 2, fb.NB)
            for c in _pack_g2_affine(sigs)
        )
    t2 = time.perf_counter()
    m.timings = {"points_ms": (t1 - t0) * 1e3, "pack_ms": (t2 - t1) * 1e3}
    _MARSHAL_SECONDS.labels("points").observe(t1 - t0)
    _MARSHAL_SECONDS.labels("pack").observe(t2 - t1)
    return m


def _marshal_flat(sets) -> _Marshalled:
    t0 = time.perf_counter()
    n_sets = len(sets)
    max_keys = max(len(s.pubkeys) for s in sets)
    m = _Marshalled()
    m.grouped = False
    m.n_groups = None
    m.group_mask = None
    m.s_bucket = _bucket(n_sets, 4)
    m.k_bucket = _bucket(max_keys, 1)

    with span("verify/marshal/points"):
        msgs = [_msg_affine(s.message) for s in sets]
        sigs = batch_to_affine_g2([s.signature.point for s in sets])
        msgs += [None] * (m.s_bucket - n_sets)
        sigs += [None] * (m.s_bucket - n_sets)
    t1 = time.perf_counter()

    with span("verify/marshal/pack"):
        m.set_mask = np.array(
            [True] * n_sets + [False] * (m.s_bucket - n_sets), dtype=bool
        )
        m.key_mask = np.array(
            [
                [True] * len(s.pubkeys)
                + [False] * (m.k_bucket - len(s.pubkeys))
                for s in sets
            ]
            + [[False] * m.k_bucket] * (m.s_bucket - n_sets),
            dtype=bool,
        )

        m.table = _table_for(sets)
        if m.table is not None:
            indices = np.full((m.s_bucket, m.k_bucket), -1, dtype=np.int32)
            for i, s in enumerate(sets):
                for k, p in enumerate(s.pubkeys):
                    indices[i, k] = p.validator_index
            m.indices = m.table.gather_indices(indices)
            m.pubkeys = None
        else:
            # untagged pubkeys: legacy per-point packing
            pk_rows = []
            for s in sets:
                row = [G1_GROUP.to_affine(p.point) for p in s.pubkeys]
                pk_rows.append(row + [None] * (m.k_bucket - len(row)))
            pk_rows += [[None] * m.k_bucket] * (m.s_bucket - n_sets)
            pk_flat = [p for row in pk_rows for p in row]
            pk_x, pk_y = _pack_g1_affine(pk_flat)
            m.indices = None
            m.pubkeys = (
                np.asarray(pk_x).reshape(m.s_bucket, m.k_bucket, 1, fb.NB),
                np.asarray(pk_y).reshape(m.s_bucket, m.k_bucket, 1, fb.NB),
            )
        m.msgs = _pack_g2_affine(msgs)
        m.sigs = _pack_g2_affine(sigs)
    t2 = time.perf_counter()
    m.timings = {"points_ms": (t1 - t0) * 1e3, "pack_ms": (t2 - t1) * 1e3}
    _MARSHAL_SECONDS.labels("points").observe(t1 - t0)
    _MARSHAL_SECONDS.labels("pack").observe(t2 - t1)
    return m


def _record_stats(n_sets, m, t_start, t_subgroup, t_marshal, t_end):
    LAST_HOST_STATS.clear()
    LAST_HOST_STATS.update(
        {
            "n_sets": n_sets,
            "indexed_path": m.table is not None,
            "grouped": bool(m.grouped),
            "n_groups": m.n_groups,
            "subgroup_ms": (t_subgroup - t_start) * 1e3,
            "points_ms": m.timings["points_ms"],
            "pack_ms": m.timings["pack_ms"],
            "host_ms": (t_marshal - t_start) * 1e3,
            "device_ms": (t_end - t_marshal) * 1e3,
        }
    )


def _shape_key(m) -> str:
    """Shape-bucket string for the compile ledger: the (set, key)
    bucket class this marshal compiled/hit."""
    if m.grouped:
        g_b, sg_b = m.set_mask.shape
        return f"g{g_b}x{sg_b}k{m.k_bucket}"
    return f"s{m.s_bucket}k{m.k_bucket}"


def verify_signature_sets_tpu(
    sets, seed: int | None = None, consumer: str | None = None
) -> bool:
    t_start = time.perf_counter()
    # host-side policy checks (exact reference semantics)
    with span("verify/subgroup_check", n_sets=len(sets)):
        ok = all(
            not s.signature.is_infinity() and s.signature.in_subgroup()
            for s in sets
        )
    if not ok:
        return False
    t_subgroup = time.perf_counter()

    with span("verify/marshal", n_sets=len(sets)):
        m = _marshal(sets)
    with span("verify/rlc_sample"):
        rand_bits = curve.scalars_to_bits(
            _rlc_scalars(m.s_bucket, seed), batch_verify.RAND_BITS
        )
    t_marshal = time.perf_counter()

    def device_attempt(plan):
        with span(
            "verify/device",
            s_bucket=m.s_bucket,
            grouped=bool(m.grouped),
            indexed=m.table is not None,
        ):
            return bool(
                plan.verdict(bool(np.asarray(_dispatch(m, rand_bits))))
            )

    def xla_host_tier():
        # same compiled graph, pinned to the host CPU device
        with host_device_scope(), span(
            "verify/device", s_bucket=m.s_bucket, failover="xla-host"
        ):
            return bool(np.asarray(_dispatch(m, rand_bits)))

    def ref_tier():
        from lighthouse_tpu.bls.api import _verify_one_ref

        return all(_verify_one_ref(s) for s in sets)

    result = GUARD.dispatch(
        "bls",
        _shape_key(m),
        device_attempt,
        fallbacks=[("xla-host", xla_host_tier), ("ref", ref_tier)],
    )
    t_end = time.perf_counter()
    attribution.note_batch(
        consumer,
        "bls",
        lanes=m.s_bucket,
        live=len(sets),
        duration_s=t_end - t_marshal,
    )
    _record_stats(len(sets), m, t_start, t_subgroup, t_marshal, t_end)
    return result


# stream-dispatch telemetry for the last verify_signature_set_batches_tpu
LAST_STREAM_STATS: dict = {}


def _dispatch(m, rand_bits):
    """Async device dispatch of a marshalled batch — returns the
    unforced device value. The dispatch call is timed for the compile
    ledger: JAX dispatch is async, so a cold (retraced) call's wall is
    dominated by trace+compile while a warm call's is dispatch
    overhead."""
    CALL_COUNTS["batch"] += 1
    shape = _shape_key(m)
    t0 = time.perf_counter()
    if m.grouped:
        # rand bits were sampled for s_bucket lanes; the grouped verify
        # takes them on the (G, Sg) grid
        rand_bits = np.asarray(rand_bits).reshape(
            m.set_mask.shape + (batch_verify.RAND_BITS,)
        )
        plain, indexed = _get_grouped_fns()
        if m.table is not None:
            tx, ty = m.table.rows()
            out = indexed(
                m.msgs, m.sigs, tx, ty, m.indices, m.key_mask,
                rand_bits, m.set_mask, m.group_mask,
            )
            _note_xla_events(
                "verify_grouped_indexed", indexed, shape,
                time.perf_counter() - t0,
            )
        else:
            out = plain(
                m.msgs, m.sigs, m.pubkeys, m.key_mask, rand_bits,
                m.set_mask, m.group_mask,
            )
            _note_xla_events(
                "verify_grouped", plain, shape, time.perf_counter() - t0
            )
        return out
    if m.table is not None:
        tx, ty = m.table.rows()
        fn = _get_indexed_fn()
        out = fn(
            m.msgs, m.sigs, tx, ty, m.indices, m.key_mask, rand_bits,
            m.set_mask,
        )
        _note_xla_events(
            "verify_indexed", fn, shape, time.perf_counter() - t0
        )
        return out
    fn = _get_fn()
    out = fn(
        m.msgs, m.sigs, m.pubkeys, m.key_mask, rand_bits, m.set_mask
    )
    _note_xla_events("verify", fn, shape, time.perf_counter() - t0)
    return out


def verify_signature_set_batches_tpu(
    batches, seed=None, consumer: str | None = None
) -> list:
    """Streamed (double-buffered) verification of several batches: batch
    N+1 is marshalled on the host WHILE batch N runs on the device.

    JAX dispatch is asynchronous — the device value is not forced until
    `np.asarray`. The loop therefore: dispatch batch N, marshal batch
    N+1 (device busy the whole time), dispatch N+1, only then force N.
    At 30k sigs/slot the host marshal would otherwise add directly to
    the 200 ms budget (SURVEY §2.6 pipeline row; the reference overlaps
    the same way with rayon in block_verification.rs:21-44).

    Returns one bool per batch (empty batches are False, matching
    verify_signature_sets)."""
    t_wall0 = time.perf_counter()
    batches = [list(b) for b in batches]
    stream = {"host_ms": 0.0, "n_dispatched": 0}

    def stream_attempt(plan):
        """The whole double-buffered pipeline is ONE guarded crossing:
        per-force watchdogs would serialize exactly the overlap the
        stream exists for, so the guard wraps the stream and the
        failover re-verifies every batch on the host."""
        results = [None] * len(batches)
        pending = None  # (batch_index, unforced device verdict)
        stream["host_ms"] = 0.0
        stream["n_dispatched"] = 0
        for bi, sets in enumerate(batches):
            if not sets or any(
                s.signature.is_infinity()
                or not s.signature.in_subgroup()
                for s in sets
            ):
                results[bi] = False
                continue
            t0 = time.perf_counter()
            m = _marshal(sets)
            rand_bits = curve.scalars_to_bits(
                _rlc_scalars(
                    m.s_bucket, None if seed is None else seed + bi
                ),
                batch_verify.RAND_BITS,
            )
            stream["host_ms"] += time.perf_counter() - t0
            ok = _dispatch(m, rand_bits)
            # per-batch economics; duration omitted — the
            # double-buffered overlap makes per-batch device time
            # unmeasurable (the whole call's wall is observed once
            # below)
            attribution.note_batch(
                consumer, "bls", lanes=m.s_bucket, live=len(sets)
            )
            stream["n_dispatched"] += 1
            if pending is not None:
                results[pending[0]] = bool(
                    plan.verdict(bool(np.asarray(pending[1])))
                )
            pending = (bi, ok)
        if pending is not None:
            results[pending[0]] = bool(
                plan.verdict(bool(np.asarray(pending[1])))
            )
        return results

    def ref_tier():
        from lighthouse_tpu.bls.api import _verify_one_ref

        return [
            bool(b) and all(_verify_one_ref(s) for s in b)
            for b in batches
        ]

    results = GUARD.dispatch(
        "bls",
        "stream",
        stream_attempt,
        fallbacks=[("ref", ref_tier)],
    )
    host_ms = stream["host_ms"]
    n_dispatched = stream["n_dispatched"]
    wall_ms = (time.perf_counter() - t_wall0) * 1e3
    if n_dispatched:
        attribution.observe_seconds(consumer, "bls", wall_ms / 1e3)
    LAST_STREAM_STATS.clear()
    LAST_STREAM_STATS.update(
        {
            "batches": len(batches),
            "dispatched": n_dispatched,
            "host_marshal_ms": round(host_ms * 1e3, 2),
            "wall_ms": round(wall_ms, 2),
            # fraction of host marshal hidden behind device time:
            # 1 - (wall - device-only-lower-bound)/... reported raw; the
            # bench derives overlap = (host + device - wall)/host using
            # its own device-only calibration
        }
    )
    return results


def _indexed_individual(
    msgs, sigs, table_x, table_y, indices, key_mask, set_mask
):
    import jax.numpy as jnp

    pk_x = jnp.take(table_x, indices, axis=0)
    pk_y = jnp.take(table_y, indices, axis=0)
    return batch_verify.verify_signature_sets_individual(
        msgs, sigs, (pk_x, pk_y), key_mask, set_mask
    )


_jitted_individual = None
_jitted_individual_indexed = None


def _get_individual_fns():
    global _jitted_individual, _jitted_individual_indexed
    _note_wrapper_event("verify_individual", _jitted_individual is not None)
    if _jitted_individual is None:
        _jitted_individual = jax.jit(
            batch_verify.verify_signature_sets_individual
        )
        _jitted_individual_indexed = jax.jit(_indexed_individual)
    return _jitted_individual, _jitted_individual_indexed


def verify_signature_sets_tpu_individual(
    sets, consumer: str | None = None
) -> list:
    """Per-set verdicts in ONE device call — the batch-failure fallback
    without per-set round trips (attestation batch.rs:115-131 made
    device-shaped; SURVEY §7 hard part 5)."""
    t_start = time.perf_counter()
    verdicts = [True] * len(sets)
    live = []
    with span("verify/subgroup_check", n_sets=len(sets)):
        for i, s in enumerate(sets):
            if s.signature.is_infinity() or not s.signature.in_subgroup():
                verdicts[i] = False
            else:
                live.append(i)
    if not live:
        return verdicts
    t_subgroup = time.perf_counter()

    subset = [sets[i] for i in live]
    with span("verify/marshal", n_sets=len(subset)):
        m = _marshal(subset, allow_grouped=False)  # per-set pairs needed
    t_marshal = time.perf_counter()

    plain_fn, indexed_fn = _get_individual_fns()
    CALL_COUNTS["individual"] += 1
    shape = _shape_key(m)

    def run_device():
        t0 = time.perf_counter()
        if m.table is not None:
            tx, ty = m.table.rows()
            ok = indexed_fn(
                m.msgs, m.sigs, tx, ty, m.indices, m.key_mask, m.set_mask
            )
            _note_xla_events(
                "verify_individual_indexed", indexed_fn, shape,
                time.perf_counter() - t0,
            )
        else:
            ok = plain_fn(
                m.msgs, m.sigs, m.pubkeys, m.key_mask, m.set_mask
            )
            _note_xla_events(
                "verify_individual", plain_fn, shape,
                time.perf_counter() - t0,
            )
        return np.asarray(ok)

    def device_attempt(plan):
        with span(
            "verify/device", s_bucket=m.s_bucket, individual=True
        ):
            return list(
                plan.verdict([bool(v) for v in run_device()[: len(live)]])
            )

    def xla_host_tier():
        with host_device_scope(), span(
            "verify/device", s_bucket=m.s_bucket, individual=True,
            failover="xla-host",
        ):
            return [bool(v) for v in run_device()[: len(live)]]

    def ref_tier():
        from lighthouse_tpu.bls.api import _verify_one_ref

        return [_verify_one_ref(sets[i]) for i in live]

    ok_live = GUARD.dispatch(
        "bls",
        shape,
        device_attempt,
        fallbacks=[("xla-host", xla_host_tier), ("ref", ref_tier)],
    )
    t_end = time.perf_counter()
    for j, i in enumerate(live):
        verdicts[i] = bool(ok_live[j])
    attribution.note_batch(
        consumer,
        "bls",
        lanes=m.s_bucket,
        live=len(live),
        duration_s=t_end - t_marshal,
    )
    _record_stats(len(sets), m, t_start, t_subgroup, t_marshal, t_end)
    return verdicts
