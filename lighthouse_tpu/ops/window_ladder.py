"""One signed-digit windowed-ladder plane for every per-lane scalar multiple.

Before this module the repo carried THREE independent scalar-ladder
forms: the per-bit double-add chain in `curve.ProjectiveGroup
.mul_scalar_bits` (the RLC ladders of `ops.batch_verify` at 64 bits and
the 3N independent 255-bit ladders of `ops.kzg_verify`), the transposed
chain in `ops.tcurve`, and the signed-digit window machinery of
`ops.msm` (which only served the MSM fold graphs). This module is the
single plane they all dispatch through now:

* **host digit decomposition** — `signed_digits` / `signed_digit_arrays`
  generalized from `ops.msm` to ARBITRARY scalar widths (64-bit RLC
  scalars, 255-bit KZG lane scalars), including the top-window carry
  slot when the signed bound overflows;
* **device recoding** — `recode_bits` turns the LSB-first bit matrices
  every verify entry point already marshals into window-major signed
  digits ON DEVICE (one cheap int32 scan), so no caller signature
  changes and the sharded/Pallas input builders stay bit-matrix shaped;
* **the window kernel** — `mul_scalar_bits_windowed` (batch-leading
  `curve.PG1`/`PG2` plane) and `mul_scalar_bits_windowed_t` (transposed
  `tcurve` plane): per window, c doublings + ONE complete add against a
  per-lane multiple table [0..2^(c-1)]·P selected by digit magnitude
  and conditionally negated. At c = 4 that is ~17 adds + 72 doublings
  for a 64-bit scalar vs the chain's 64 + 64 (~1.7x fewer field
  multiplies, and the same ~1.9x at 255 bits: 64 adds + 260 doublings
  vs 255 + 255) — see PERF_NOTES "unified windowed-ladder plane";
* **the dispatchers** — `ladder` / `ladder_t` route every caller
  through one `LIGHTHOUSE_TPU_LADDER` knob ("" = the window kernel, the
  default device path; "chain" = the legacy double-add, kept for A/B
  via BENCH_IMPL=chain; "w2" = the Pallas 2-bit unsigned window). Every
  future ladder win lands in the signature AND KZG planes at once.

Completeness: the RCB complete formulas make the identity table entry
and masked identity lanes exact, so there is no started-flag and no
collision precondition — any scalar width works, matching the contract
of `ProjectiveGroup.mul_scalar_bits`. The digit sign only negates the
y-coordinate (a no-op on the identity representative (0 : -1 : 0)).

`ops.msm` re-exports the host decomposition (its fixed 255-bit width is
this module's machinery specialized to the subgroup order), so the MSM
bucket graphs and the per-lane ladders cannot drift.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.ops import curve, fieldb as fb

WINDOW_BITS = 4  # default window width c; digit magnitudes in [0, 2^(c-1)]


def num_windows(nbits: int, c: int = WINDOW_BITS) -> int:
    """Window count for signed base-2^c digits of scalars < 2^nbits.

    The top window holds nbits - c*(W0-1) bits plus an incoming carry;
    an extra window is needed only when that can exceed the signed
    bound 2^(c-1) (e.g. nbits=64, c=4: 16 windows leave a 4-bit top
    digit whose carry overflows -> 17; nbits=255, c=4 leaves 3 bits and
    never does -> 64)."""
    w0 = -(-nbits // c)
    top_bits = nbits - c * (w0 - 1)
    if (1 << top_bits) - 1 + 1 > (1 << (c - 1)):
        return w0 + 1
    return w0


def signed_digits(s: int, c: int = WINDOW_BITS, nbits: int | None = None):
    """One scalar in [0, 2^nbits) -> W signed base-2^c digits,
    LSB-first, each in [-(2^(c-1) - 1), 2^(c-1)]:
    sum_w d_w 2^(cw) == s exactly."""
    if nbits is None:
        nbits = max(1, s.bit_length())
    assert 0 <= s < (1 << nbits), (s, nbits)
    half = 1 << (c - 1)
    full = 1 << c
    out = []
    carry = 0
    for _ in range(num_windows(nbits, c)):
        t = (s & (full - 1)) + carry
        s >>= c
        if t > half:
            out.append(t - full)
            carry = 1
        else:
            out.append(t)
            carry = 0
    assert carry == 0 and s == 0
    return out


def signed_digit_arrays(scalars, c: int = WINDOW_BITS, nbits: int = 255):
    """Host: scalars -> (mags, negs): (W, N) int32 digit magnitudes in
    [0, 2^(c-1)] and (W, N) bool negation flags, window-major (the scan
    axis of the device graphs)."""
    digits = np.array(
        [signed_digits(s, c, nbits) for s in scalars], dtype=np.int32
    ).T  # (W, N)
    return np.abs(digits), digits < 0


def recode_bits(bits, c: int = WINDOW_BITS):
    """Device: (..., nbits) int32 LSB-first 0/1 bits -> window-major
    signed digits ((W, ...) int32 magnitudes, (W, ...) bool negation
    flags) — the exact `signed_digits` rule as one cheap int32 carry
    scan, so callers keep marshalling the bit matrices they always
    did and the recoding costs nothing next to one group op."""
    nbits = bits.shape[-1]
    W = num_windows(nbits, c)
    pad = W * c - nbits
    if pad:
        widths = [(0, 0)] * (bits.ndim - 1) + [(0, pad)]
        bits = jnp.pad(bits, widths)
    # lint: allow(device-purity): static power-of-two weight table
    weights = jnp.asarray(np.array([1 << i for i in range(c)], np.int32))
    u = (bits.reshape(bits.shape[:-1] + (W, c)) * weights).sum(axis=-1)
    u = jnp.moveaxis(u, -1, 0)  # (W, ...) unsigned window values
    half = 1 << (c - 1)
    full = 1 << c

    def step(carry, uw):
        t = uw + carry
        over = t > half
        mag = jnp.where(over, full - t, t)
        # a borrowed-to-zero digit (t == 2^c) is sign-free — matches
        # the host rule exactly (signed_digits emits +0 there)
        neg = over & (mag > 0)
        return over.astype(uw.dtype), (mag, neg)

    carry0 = jnp.zeros(u.shape[1:], u.dtype)
    # the top window absorbs the final carry by construction
    # (num_windows adds the extra slot exactly when it could overflow)
    _, (mags, negs) = jax.lax.scan(step, carry0, u)
    return mags, negs


# ------------------------------------------------- batch-leading kernel


def _window_table(group, pt, c: int):
    """[identity, P, 2P, .., B·P] multiples (B = 2^(c-1)); even entries
    by doubling, odd by one add — complete formulas make the identity
    entry and identity input lanes exact."""
    table = [group.identity_like(pt), pt]
    for d in range(2, (1 << (c - 1)) + 1):
        table.append(
            group.double(table[d // 2])
            if d % 2 == 0
            else group.add(table[-1], pt)
        )
    return table


def _select_signed(group, table, mag, neg):
    """table[mag] with the sign applied to y (select chain over the
    B+1 static entries — elementwise wheres, no gather/scatter)."""
    t = table[0]
    for d in range(1, len(table)):
        t = group.select(mag == d, table[d], t)
    return group.select(neg, group.neg(t), t)


def mul_scalar_bits_windowed(group, pt, bits, c: int = WINDOW_BITS):
    """The unified signed-digit window ladder on the batch-leading
    plane: pt = (X, Y, Z) `curve.ProjectiveGroup` bundles with leading
    batch axes, bits (..., nbits) int32 LSB-first (any width). Per
    window: c complete doublings + one complete add. Same contract as
    `group.mul_scalar_bits` (identity lanes ride through)."""
    mags, negs = recode_bits(bits, c)  # (W,) + batch

    table = _window_table(group, pt, c)

    def body(acc, wd):
        mag, neg = wd
        for _ in range(c):
            acc = group.double(acc)
        return group.add(acc, _select_signed(group, table, mag, neg)), None

    acc, _ = jax.lax.scan(
        body, group.identity_like(pt), (mags, negs), reverse=True
    )
    return acc


# --------------------------------------------------- transposed kernel


def mul_scalar_bits_windowed_t(group, pt, bits, c: int = WINDOW_BITS):
    """The same window ladder on the transposed (batch-last) plane:
    pt = (X, Y, Z) `tcurve.TProjective` bundles (w, NB, B), bits
    (nbits, B) int32 LSB-first. Shares `recode_bits` and the per-window
    step with the batch-leading form via tcurve.window_table/step."""
    mags, negs = recode_bits(jnp.moveaxis(bits, 0, -1), c)  # (W, B)
    table = group.window_table(pt, c)
    B = pt[0].shape[-1]

    def body(acc, wd):
        mag, neg = wd
        return group.window_step(acc, table, mag, neg, c), None

    acc, _ = jax.lax.scan(
        body, group.identity(B), (mags, negs), reverse=True
    )
    return acc


# ------------------------------------------------------------ dispatch


def ladder_impl() -> str:
    """LIGHTHOUSE_TPU_LADDER selects the scalar-ladder kernel family
    for EVERY per-lane ladder (signature RLC, KZG lanes, transposed and
    Pallas planes): ""/unset -> "window" (the unified signed-digit
    window kernel — the default device path); "chain" -> the legacy
    per-bit double-add chain (A/B only, BENCH_IMPL=chain); "w2" -> the
    2-bit unsigned window (Pallas/transposed planes; the batch-leading
    plane maps it to "window"). Read at trace time — part of every
    dispatching jit cache key (bls/kzg `_impl_key`)."""
    import os

    # lint: allow(device-purity): trace-time knob, keyed via _impl_key
    v = os.environ.get("LIGHTHOUSE_TPU_LADDER", "")
    if v in ("", "0", "window"):
        return "window"
    if v in ("chain", "w2"):
        return v
    raise ValueError(
        f"LIGHTHOUSE_TPU_LADDER={v!r}: use window, chain, w2, or unset"
    )


def ladder(group, pt, bits, c: int = WINDOW_BITS, impl: str | None = None):
    """THE per-lane scalar-multiple entry point for the batch-leading
    plane — `ops.batch_verify`, `ops.kzg_verify`, and the sharded
    builders all dispatch here, so a ladder improvement lands in the
    signature and KZG planes at once. impl=None resolves the
    LIGHTHOUSE_TPU_LADDER knob (callers under jit are keyed by it)."""
    if impl is None:
        impl = ladder_impl()
    if impl == "chain":
        return group.mul_scalar_bits(pt, bits)
    # "w2" is a transposed/Pallas kernel choice; this plane's windowed
    # form is the signed-digit kernel either way
    return mul_scalar_bits_windowed(group, pt, bits, c=c)


def ladder_t(group, pt, bits, c: int = WINDOW_BITS, impl: str | None = None):
    """`ladder` for the transposed plane (`tcurve.TPG1`/`TPG2`):
    the XLA-level txla pipeline and the Pallas kernel wrappers."""
    if impl is None:
        impl = ladder_impl()
    if impl == "chain":
        return group.mul_scalar_bits(pt, bits)
    if impl == "w2":
        return group.mul_scalar_bits_w2(pt, bits)
    return mul_scalar_bits_windowed_t(group, pt, bits, c=c)


# jit objects per (group, c, impl, MXU form) — keyed like the bls jit
# caches by everything read at trace time, so flipping a knob
# mid-process retraces instead of silently reusing a stale trace;
# (width, lanes) shape buckets retrace INSIDE the cached jit object.
_JITTED: dict = {}


def jitted_ladder(
    group_name: str = "G1",
    c: int = WINDOW_BITS,
    impl: str | None = None,
):
    """Process-cached jitted ladder entry (bench A/B + warm scripts)."""
    if impl is None:
        impl = ladder_impl()
    key = (group_name, c, impl, fb.use_mxu_conv())
    fn = _JITTED.get(key)
    if fn is None:
        group = curve.PG2 if group_name == "G2" else curve.PG1
        fn = _JITTED[key] = jax.jit(
            functools.partial(ladder, group, c=c, impl=impl)
        )
    return fn
