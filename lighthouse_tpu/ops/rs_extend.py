"""Device Reed-Solomon blob extension: batched Fr polynomial evaluation.

PeerDAS-shaped data availability extends each blob polynomial (n Fr
coefficients — this codebase keeps blobs in coefficient form, see
`kzg.api.blob_to_polynomial`) to 2n evaluations over the 2n-th
roots-of-unity domain in Fr. Any n of the 2n evaluations then determine
the polynomial, which is what lets nodes reconstruct from 50% of
columns instead of downloading full sidecars.

The graph is one batched Horner scan over ALL (point, blob) pairs at
once — `ops.rfield` relaxed-limb Montgomery bundles, no carry
resolution on the hot path:

    acc[p, b] <- acc[p, b] * x[p] + coeff[i, b]      (i = n-1 .. 0)

Work is O(n) multiplies per point (O(n * 2n) per blob batch). That is
asymptotically worse than an FFT over the evaluation domain, but at
devnet blob sizes the whole scan is a handful of fused VPU convolutions
and the dispatch is dominated by fixed costs; the FFT restructuring for
mainnet blob counts is the ROADMAP "mainnet blob-count scaling" item.

Host-side policy (domain construction, cell slicing, oracle, guarded
dispatch) lives in `lighthouse_tpu.da.erasure`; this module is the pure
jittable graph, verified byte-identical against the host bigint Horner
oracle in tests/test_da_plane.py.
"""

import jax
import jax.numpy as jnp

from lighthouse_tpu.ops import rfield as rf

NB = rf.NB


def eval_poly_batch(coeffs, points):
    """Evaluate a batch of coefficient-form Fr polynomials at a batch
    of points.

    coeffs: (N_COEFF, BLOBS, NB) int32 — Montgomery-domain canonical
        bundles, coeffs[i] = coefficient of X^i for every blob.
    points: (PTS, NB) int32 — Montgomery-domain canonical bundles.

    Returns (PTS, BLOBS, NB) lazy Montgomery-domain evaluations
    (limbs <= LIMB_RELAX, value < 2.3r); callers `rf.canon` at the
    host boundary.

    Bound closure per Horner step (see ops.rfield docstring): acc
    < 1.53r (add output) and points < r feed mul_lazy (< 1.02r out);
    canonical coeffs (< r) feed add (< 1.53r out).
    """
    n_coeff, n_blobs, _ = coeffs.shape
    n_pts = points.shape[0]
    x = jnp.broadcast_to(points[:, None, :], (n_pts, n_blobs, NB))

    def body(i, acc):
        c = jax.lax.dynamic_index_in_dim(
            coeffs, n_coeff - 1 - i, axis=0, keepdims=False
        )
        return rf.add(rf.mul_lazy(acc, x), jnp.broadcast_to(c, acc.shape))

    acc = jnp.zeros((n_pts, n_blobs, NB), dtype=jnp.int32)
    return jax.lax.fori_loop(0, n_coeff, body, acc)


def rs_extend_graph(coeffs, points):
    """Full RS-extension graph: evaluate + leave the Montgomery domain
    + canonicalize, so hosts unpack plain ints directly.

    Returns (PTS, BLOBS, NB) canonical-limb plain-domain evaluations.
    """
    return rf.from_mont(eval_poly_batch(coeffs, points))
