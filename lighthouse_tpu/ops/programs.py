"""Bilinear multiplication programs for the tower, built symbolically.

A bilinear program is (A, B, C): left operands = A @ slots(x), right =
B @ slots(y), stacked Montgomery product, output slots = C @ products.
The programs are derived here from the SAME tower formulas as the validated
pure-Python reference (crypto/ref_fields.py): Karatsuba Fp2, the 6-mul
Fp6 schedule, Karatsuba Fp12 — so an Fp12 product is one 18-slot stacked
multiply plus two small einsums.

Slot order: Fp2 = [c0, c1]; Fp6 = [a0c0, a0c1, a1c0, a1c1, a2c0, a2c1];
Fp12 = first Fp6 then second (w) Fp6.
"""

import numpy as np


class _Lin:
    """Linear form over input slots: dict slot -> int coefficient."""

    def __init__(self, coeffs=None):
        self.c = dict(coeffs or {})

    @classmethod
    def unit(cls, i):
        return cls({i: 1})

    def __add__(self, o):
        out = dict(self.c)
        for k, v in o.c.items():
            out[k] = out.get(k, 0) + v
        return _Lin({k: v for k, v in out.items() if v})

    def __sub__(self, o):
        return self + o.scale(-1)

    def scale(self, s):
        return _Lin({k: v * s for k, v in self.c.items()})

    def __neg__(self):
        return self.scale(-1)


class _Prod:
    """Reference to one registered product (by index)."""

    def __init__(self, idx):
        self.c = {idx: 1}

    @classmethod
    def combo(cls, coeffs):
        p = cls.__new__(cls)
        p.c = dict(coeffs)
        return p

    def __add__(self, o):
        out = dict(self.c)
        for k, v in o.c.items():
            out[k] = out.get(k, 0) + v
        return _Prod.combo({k: v for k, v in out.items() if v})

    def __sub__(self, o):
        return self + o.scale(-1)

    def scale(self, s):
        return _Prod.combo({k: v * s for k, v in self.c.items()})

    def __neg__(self):
        return self.scale(-1)


class _Builder:
    def __init__(self):
        self.left = []
        self.right = []

    def mul(self, l: _Lin, r: _Lin) -> _Prod:
        self.left.append(l)
        self.right.append(r)
        return _Prod(len(self.left) - 1)

    def finish(self, outputs, s_left, s_right):
        k = len(self.left)
        A = np.zeros((k, s_left), dtype=np.int32)
        B = np.zeros((k, s_right), dtype=np.int32)
        C = np.zeros((len(outputs), k), dtype=np.int32)
        for i, lin in enumerate(self.left):
            for s, v in lin.c.items():
                A[i, s] = v
        for i, lin in enumerate(self.right):
            for s, v in lin.c.items():
                B[i, s] = v
        for o, prod in enumerate(outputs):
            for idx, v in prod.c.items():
                C[o, idx] = v
        # prune products with an all-zero operand (sparse programs): their
        # value is 0 mod p, so dropping the column is exact
        keep = [
            i
            for i in range(k)
            if A[i].any() and B[i].any() and C[:, i].any()
        ]
        return Program(A[keep], B[keep], C[:, keep])


class Program:
    def __init__(self, A, B, C):
        self.A, self.B, self.C = A, B, C

    @property
    def n_products(self):
        return self.A.shape[0]


# ---- symbolic tower formulas (mirroring ref_fields) ----


def _fp2_mul_sym(b, a, c):
    """a, c: 2-elem lists of _Lin (c0, c1). Returns 2 _Prod outputs.
    Karatsuba: t0 = a0 b0, t1 = a1 b1, t2 = (a0+a1)(b0+b1);
    out = (t0 - t1, t2 - t0 - t1)."""
    t0 = b.mul(a[0], c[0])
    t1 = b.mul(a[1], c[1])
    t2 = b.mul(a[0] + a[1], c[0] + c[1])
    return [t0 - t1, t2 - t0 - t1]


def _fp2_add(a, c):
    return [a[0] + c[0], a[1] + c[1]]


def _fp2_sub(a, c):
    return [a[0] - c[0], a[1] - c[1]]


def _fp2_mul_by_xi(a):
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return [a[0] - a[1], a[0] + a[1]]


def _fp6_mul_sym(b, a, c):
    """a, c: 3-elem lists of Fp2 (each 2 _Lin). Returns 3 Fp2 outputs
    (each 2 _Prod combos). Same 6-multiplication schedule as
    ref_fields.fp6_mul."""
    a0, a1, a2 = a
    c0, c1, c2 = c
    t0 = _fp2_mul_sym(b, a0, c0)
    t1 = _fp2_mul_sym(b, a1, c1)
    t2 = _fp2_mul_sym(b, a2, c2)
    m12 = _fp2_mul_sym(b, _fp2_add(a1, a2), _fp2_add(c1, c2))
    m01 = _fp2_mul_sym(b, _fp2_add(a0, a1), _fp2_add(c0, c1))
    m02 = _fp2_mul_sym(b, _fp2_add(a0, a2), _fp2_add(c0, c2))

    def sub2(x, y):
        return [x[0] - y[0], x[1] - y[1]]

    def add2(x, y):
        return [x[0] + y[0], x[1] + y[1]]

    def xi2(x):
        return [x[0] - x[1], x[0] + x[1]]

    out0 = add2(t0, xi2(sub2(sub2(m12, t1), t2)))
    out1 = add2(sub2(sub2(m01, t0), t1), xi2(t2))
    out2 = add2(sub2(sub2(m02, t0), t2), t1)
    return [out0, out1, out2]


def _fp6_add(a, c):
    return [_fp2_add(x, y) for x, y in zip(a, c)]


def _fp6_sub(a, c):
    return [_fp2_sub(x, y) for x, y in zip(a, c)]


def _fp6_mul_by_v(a):
    return [_fp2_mul_by_xi(a[2]), a[0], a[1]]


def _fp12_mul_sym(b, a, c):
    """Karatsuba over Fp6 pairs; 18 products total."""
    a0, a1 = a[:3], a[3:]
    c0, c1 = c[:3], c[3:]
    t0 = _fp6_mul_sym(b, a0, c0)
    t1 = _fp6_mul_sym(b, a1, c1)
    tx = _fp6_mul_sym(b, _fp6_add(a0, a1), _fp6_add(c0, c1))
    out0 = _fp6_add(t0, _fp6_mul_by_v(t1))
    out1 = _fp6_sub(_fp6_sub(tx, t0), t1)
    return out0 + out1


def _units(n, offset=0):
    return [_Lin.unit(offset + i) for i in range(n)]


def _flatten(nested):
    out = []
    for grp in nested:
        out.extend(grp)
    return out


def _build(symfn, s):
    b = _Builder()
    a = _units(s)
    c = _units(s)
    outs = symfn(b, a, c)
    return b.finish(_flatten_outputs(outs, s), s, s)


def _flatten_outputs(outs, s):
    # outputs arrive as nested lists mirroring the slot layout
    flat = []

    def rec(x):
        if isinstance(x, list):
            for y in x:
                rec(y)
        else:
            flat.append(x)

    rec(outs)
    assert len(flat) == s
    return flat


def _fp2_top(b, a, c):
    return _fp2_mul_sym(b, a, c)


FP2_MUL = _build(
    lambda b, a, c: _fp2_top(b, [a[0], a[1]], [c[0], c[1]]), 2
)
FP6_MUL = _build(
    lambda b, a, c: _fp6_mul_sym(
        b,
        [[a[0], a[1]], [a[2], a[3]], [a[4], a[5]]],
        [[c[0], c[1]], [c[2], c[3]], [c[4], c[5]]],
    ),
    6,
)


def _as6(v):
    return [[v[0], v[1]], [v[2], v[3]], [v[4], v[5]]]


FP12_MUL = _build(
    lambda b, a, c: _fp12_mul_sym(
        b,
        _as6(a[:6]) + _as6(a[6:]),
        _as6(c[:6]) + _as6(c[6:]),
    ),
    12,
)


def _fp12_sqr_sym(b, a):
    """Complex squaring over the Fp6 pair (w^2 = v): with t0 = a0 a1 and
    t1 = (a0 + a1)(a0 + v a1),
      (a0 + a1 w)^2 = (t1 - t0 - v t0) + (2 t0) w
    — 2 Fp6 multiplications (12 products) vs the generic mul's 18. The
    Miller loop squares f every iteration, so this is the hottest single
    op in the batch-verify kernel."""
    a0, a1 = a[:3], a[3:]
    t0 = _fp6_mul_sym(b, a0, a1)
    t1 = _fp6_mul_sym(
        b, _fp6_add(a0, a1), _fp6_add(a0, _fp6_mul_by_v(a1))
    )
    out0 = _fp6_sub(_fp6_sub(t1, t0), _fp6_mul_by_v(t0))
    out1 = _fp6_add(t0, t0)
    return out0 + out1


def _build_fp12_sqr():
    b = _Builder()
    x = _units(12)
    outs = _fp12_sqr_sym(b, _as6(x[:6]) + _as6(x[6:]))
    return b.finish(_flatten_outputs(outs, 12), 12, 12)


# bilinear(f, f, FP12_SQR): both operand matrices read the same bundle.
# 2 Fp6 muls = 12 Fp2 muls = 36 Fp products, vs FP12_MUL's 54.
FP12_SQR = _build_fp12_sqr()
assert FP12_SQR.n_products == 36, FP12_SQR.n_products

# Sparse line multiplication: f (12 slots) * line with only the w^0 (Fp2),
# w^2 (Fp2), w^3 (Fp2) tower slots nonzero. The line is presented as a
# 6-slot bundle [l0c0, l0c1, l2c0, l2c1, l3c0, l3c1]; as a full Fp12 its
# slot layout is: c0-part = (l0, l2, 0), c1-part = (0, l3, 0).


def _build_line_mul():
    b = _Builder()
    f = _units(12)
    line = _units(6)
    zero = _Lin()
    c_fp6_0 = [
        [line[0], line[1]],
        [line[2], line[3]],
        [zero, zero],
    ]
    c_fp6_1 = [
        [zero, zero],
        [line[4], line[5]],
        [zero, zero],
    ]
    outs = _fp12_mul_sym(
        b, _as6(f[:6]) + _as6(f[6:]), c_fp6_0 + c_fp6_1
    )
    prog = b.finish(_flatten_outputs(outs, 12), 12, 6)
    return prog


LINE_MUL = _build_line_mul()

# L1 sanity: apply_combo's offset covers rows up to L1 36
for _p in (FP2_MUL, FP6_MUL, FP12_MUL, LINE_MUL, FP12_SQR):
    assert np.abs(_p.A).sum(axis=1).max() <= 36
    assert np.abs(_p.B).sum(axis=1).max() <= 36
    assert np.abs(_p.C).sum(axis=1).max() <= 36
