"""Batched Fp6/Fp12 tower arithmetic on slot bundles.

Fp6 = (..., 6, NB), Fp12 = (..., 12, NB) int32 bundles (ops.fieldb). A full
Fp12 multiplication is ONE 18-slot stacked Montgomery multiply between two
small einsums (ops.programs.FP12_MUL) — the layout that keeps the
Miller-loop graph small and MXU-friendly.

Slot order: Fp6 = [a0c0, a0c1, a1c0, a1c1, a2c0, a2c1] (coefficients of
v^0, v^1, v^2, each an Fp2); Fp12 = [c0-part (6), c1-part (6)] over w.
Validated against crypto/ref_fields.fp6_*/fp12_*.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import FROB_GAMMA, P
from lighthouse_tpu.ops import fieldb as fb
from lighthouse_tpu.ops import fp2
from lighthouse_tpu.ops.programs import FP6_MUL, FP12_MUL, FP12_SQR

NB = fb.NB

FP6_ZERO = np.zeros((6, NB), dtype=np.int32)
FP6_ONE = np.concatenate([fp2.ONE_MONT, np.zeros((4, NB), np.int32)])
FP12_ZERO = np.zeros((12, NB), dtype=np.int32)
FP12_ONE = np.concatenate([FP6_ONE, FP6_ZERO])

# ---------------------------------------------------------- combo matrices


def _block_diag(blocks):
    n = sum(b.shape[0] for b in blocks)
    m = sum(b.shape[1] for b in blocks)
    out = np.zeros((n, m), dtype=np.int32)
    r = c = 0
    for b in blocks:
        out[r : r + b.shape[0], c : c + b.shape[1]] = b
        r += b.shape[0]
        c += b.shape[1]
    return out


_XI = np.array([[1, -1], [1, 1]], dtype=np.int32)
_I2 = np.eye(2, dtype=np.int32)
_Z2 = np.zeros((2, 2), dtype=np.int32)

# Fp6 * v: (a0, a1, a2) -> (xi*a2, a0, a1)
_MUL_BY_V6 = np.block(
    [[_Z2, _Z2, _XI], [_I2, _Z2, _Z2], [_Z2, _I2, _Z2]]
).astype(np.int32)

# Fp12 conj (negate the w-part)
_CONJ12 = _block_diag(
    [np.eye(6, dtype=np.int32), -np.eye(6, dtype=np.int32)]
)

# Fp2-conjugate every coefficient (for Frobenius)
_CONJ_EACH = _block_diag([np.array([[1, 0], [0, -1]], np.int32)] * 6)


def _gamma_bundle():
    """(6, 2, NB) Montgomery constants: FROB_GAMMA[i] per Fp2 coefficient
    in Frobenius order [g0(=1), g2, g4, g1, g3, g5]."""
    order = [0, 2, 4, 1, 3, 5]
    rows = []
    for i in order:
        g = FROB_GAMMA[i]
        rows.append(fp2.const_mont(g[0] % P, g[1] % P))
    return np.stack(rows)


_FROB_GAMMAS = _gamma_bundle()


# ---------------------------------------------------------------- Fp6 ops


def fp6_add(a, b):
    return fb.add(a, b)


def fp6_sub(a, b):
    return fb.sub(a, b)


def fp6_neg(a):
    return fb.apply_combo(a, -np.eye(6, dtype=np.int32))


def fp6_mul(a, b):
    return fp2.bilinear(a, b, FP6_MUL)


def fp6_sqr(a):
    return fp2.bilinear(a, a, FP6_MUL)


def fp6_mul_by_v(a):
    return fb.apply_combo(a, _MUL_BY_V6)


def _as_fp2_batch(a6):
    """(..., 6, NB) -> (..., 3, 2, NB) for per-coefficient Fp2 work."""
    return a6.reshape(a6.shape[:-2] + (3, 2, NB))


def _from_fp2_batch(a):
    return a.reshape(a.shape[:-3] + (6, NB))


def fp6_inv(a):
    """Tower inversion (same shape as ref_fields.fp6_inv)."""
    a2 = _as_fp2_batch(a)  # (..., 3, 2, NB): a0, a1, a2
    a0, a1, a2_ = a2[..., 0, :, :], a2[..., 1, :, :], a2[..., 2, :, :]
    # products: a0^2, a1^2, a2^2, a0a1, a1a2, a0a2 — one stacked fp2 mul
    lhs = jnp.stack([a0, a1, a2_, a0, a1, a0], axis=-3)
    rhs = jnp.stack([a0, a1, a2_, a1, a2_, a2_], axis=-3)
    prods = fp2.bilinear(lhs, rhs, fp2.FP2_MUL)
    sq0, sq1, sq2 = (
        prods[..., 0, :, :],
        prods[..., 1, :, :],
        prods[..., 2, :, :],
    )
    p01, p12, p02 = (
        prods[..., 3, :, :],
        prods[..., 4, :, :],
        prods[..., 5, :, :],
    )
    c0 = fb.sub(sq0, fp2.mul_by_xi(p12))
    c1 = fb.sub(fp2.mul_by_xi(sq2), p01)
    c2 = fb.sub(sq1, p02)
    # norm = a0 c0 + xi (a2 c1 + a1 c2)
    lhs2 = jnp.stack([a0, a2_, a1], axis=-3)
    rhs2 = jnp.stack([c0, c1, c2], axis=-3)
    pr = fp2.bilinear(lhs2, rhs2, fp2.FP2_MUL)
    norm = fb.add(
        pr[..., 0, :, :],
        fp2.mul_by_xi(fb.add(pr[..., 1, :, :], pr[..., 2, :, :])),
    )
    ninv = fp2.inv(norm)
    scaled = fp2.bilinear(
        jnp.stack([c0, c1, c2], axis=-3),
        jnp.broadcast_to(
            ninv[..., None, :, :],
            c0.shape[:-2] + (3, 2, NB),
        ),
        fp2.FP2_MUL,
    )
    return _from_fp2_batch(scaled)


def fp6_select(cond, a, b):
    return fb.select(cond, a, b)


# --------------------------------------------------------------- Fp12 ops


def fp12_add(a, b):
    return fb.add(a, b)


def fp12_mul(a, b):
    return fp2.bilinear(a, b, FP12_MUL)


def use_fp12_sqr() -> bool:
    """LIGHTHOUSE_TPU_FP12_SQR selects the Miller/final-exp squaring
    program: ""/unset -> the dedicated 12-product complex-squaring
    program (the DEFAULT device form, ~14% fewer Miller products);
    "mul" -> the legacy generic 18-product multiply, kept ONLY for A/B
    (BENCH_IMPL=mulsqr). Both forms are byte-identical on the committed
    vectors (tests/test_pairing_device.py). Read at trace time — part
    of the backend jit cache keys (_impl_key)."""
    import os

    # lint: allow(device-purity): trace-time knob, keyed via _impl_key
    v = os.environ.get("LIGHTHOUSE_TPU_FP12_SQR", "")
    if v in ("", "sqr"):
        return True
    if v == "mul":
        return False
    raise ValueError(
        f"LIGHTHOUSE_TPU_FP12_SQR={v!r}: use mul, sqr, or unset"
    )


def fp12_sqr(a):
    # dedicated complex-squaring program: 12 products vs the mul's 18
    # (the legacy generic multiply stays reachable for A/B only)
    if use_fp12_sqr():
        return fp2.bilinear(a, a, FP12_SQR)
    return fp2.bilinear(a, a, FP12_MUL)


def fp12_conj(a):
    return fb.apply_combo(a, _CONJ12)


def fp12_inv(a):
    """1/(b0 + b1 w) = (b0 - b1 w)/(b0^2 - v b1^2)."""
    b0, b1 = a[..., :6, :], a[..., 6:, :]
    sq = fp2.bilinear(
        jnp.stack([b0, b1], axis=-3),
        jnp.stack([b0, b1], axis=-3),
        FP6_MUL,
    )
    norm = fb.sub(sq[..., 0, :, :], fp6_mul_by_v(sq[..., 1, :, :]))
    ninv = fp6_inv(norm)
    scaled = fp2.bilinear(
        jnp.stack([b0, b1], axis=-3),
        jnp.broadcast_to(
            ninv[..., None, :, :], b0.shape[:-2] + (2, 6, NB)
        ),
        FP6_MUL,
    )
    return jnp.concatenate(
        [scaled[..., 0, :, :], fp6_neg(scaled[..., 1, :, :])], axis=-2
    )


def fp12_frobenius(a):
    """a^p: conjugate every Fp2 coefficient, scale by gamma powers."""
    conjed = fb.apply_combo(a, _CONJ_EACH)
    pairs = conjed.reshape(conjed.shape[:-2] + (6, 2, NB))
    gammas = jnp.broadcast_to(
        jnp.asarray(_FROB_GAMMAS), pairs.shape
    )
    out = fp2.bilinear(pairs, gammas, fp2.FP2_MUL)
    return out.reshape(a.shape)


def _gamma2_bundle():
    """(12, NB) Montgomery Fp constants for the p^2-Frobenius: each Fp2
    coefficient scales by Norm(gamma_i) = gamma_i^(p+1) in Fp (no
    conjugation — valid for ALL Fp12 elements, not just unitary ones), so
    frobenius^2 is ONE slot-wise multiply instead of two full frobenius
    applications."""
    order = [0, 2, 4, 1, 3, 5]
    rows = []
    for i in order:
        g0, g1 = FROB_GAMMA[i]
        n = (g0 * g0 + g1 * g1) % P  # Norm(g0 + g1 u), u^2 = -1
        limb = fb._limbs((n << 384) % P, NB)
        rows.append(limb)
        rows.append(limb)
    return np.stack(rows)


_FROB2_N = _gamma2_bundle()


def fp12_frobenius2(a):
    """a^(p^2) for any Fp12 element: slot-wise scale by Fp norms."""
    return fb.mul_lazy(a, jnp.broadcast_to(jnp.asarray(_FROB2_N), a.shape))


def fp12_select(cond, a, b):
    return fb.select(cond, a, b)


def fp12_eq(a, b):
    return fb.eq(a, b)


def fp12_is_one(a):
    one = jnp.broadcast_to(jnp.asarray(FP12_ONE), a.shape)
    return fb.eq(a, one)


def fp12_broadcast_one(batch_shape_or_like):
    if hasattr(batch_shape_or_like, "shape"):
        batch_shape = batch_shape_or_like.shape[:-2]
    else:
        batch_shape = tuple(batch_shape_or_like)
    return jnp.broadcast_to(
        jnp.asarray(FP12_ONE), batch_shape + (12, NB)
    )


def fp12_product_axis(a, axis: int = 0):
    """Tree-fold product along `axis` — merges per-pair Miller outputs
    before one shared final exponentiation (the reference's one
    multi-pairing per batch, crypto/bls/src/impls/blst.rs:114-116)."""
    if axis < 0:
        axis += a.ndim
    n = a.shape[axis]
    while n > 1:
        half = n // 2
        x = jax.lax.slice_in_dim(a, 0, half, axis=axis)
        y = jax.lax.slice_in_dim(a, half, 2 * half, axis=axis)
        prod = fp12_mul(x, y)
        if n % 2:
            tail = jax.lax.slice_in_dim(a, n - 1, n, axis=axis)
            prod = jnp.concatenate([prod, tail], axis=axis)
        a = prod
        n = half + (n % 2)
    return jnp.squeeze(a, axis=axis)


# ------------------------------------------------------------ host helpers


def fp12_pack(vals):
    """Host: ref-format Fp12 values -> (N, 12, NB) Montgomery bundle."""
    rows = []
    for v in vals:
        ints = []
        for part in v:  # two fp6
            for c in part:  # three fp2
                ints.extend([c[0], c[1]])
        rows.append(fb.pack_ints(ints))
    return fb.to_mont(np.stack(rows))


def fp12_unpack(a):
    """Host: Montgomery (N, 12, NB) bundle -> ref-format values."""
    arr = np.asarray(fb.from_mont(a))
    flat = arr.reshape(-1, 12, arr.shape[-1])
    out = []
    for row in flat:
        ints = fb.unpack_ints(row)
        fp6s = []
        for i in range(2):
            coeffs = []
            for j in range(3):
                coeffs.append(
                    (ints[i * 6 + 2 * j], ints[i * 6 + 2 * j + 1])
                )
            fp6s.append(tuple(coeffs))
        out.append((fp6s[0], fp6s[1]))
    return out
