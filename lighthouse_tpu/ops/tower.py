"""Batched Fp6/Fp12 tower arithmetic on device limbs.

Tower (same as the reference math, see `lighthouse_tpu.crypto.ref_fields`):
    Fp6  = Fp2[v]/(v^3 - xi),  xi = 1 + u
    Fp12 = Fp6[w]/(w^2 - v)

Representations (all JAX pytrees):
    Fp6  : 3-tuple of Fp2
    Fp12 : 2-tuple of Fp6

All multiplicative ops operate in the Montgomery domain. Validated against
`ref_fields.fp6_*` / `fp12_*`.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import FROB_GAMMA, NLIMBS, P, int_to_limbs
from lighthouse_tpu.ops import fp, fp2

# ------------------------------------------------------------------ constants


def _mont_fp2(v) -> tuple:
    """Static (c0, c1) int tuple -> Montgomery-form Fp2 limb constant."""
    return (
        np.array(int_to_limbs((v[0] << 384) % P), dtype=np.int32),
        np.array(int_to_limbs((v[1] << 384) % P), dtype=np.int32),
    )


FROB_GAMMA_MONT = [_mont_fp2(g) for g in FROB_GAMMA]

FP6_ZERO = (fp2.ZERO, fp2.ZERO, fp2.ZERO)
FP6_ONE = (fp2.ONE_MONT, fp2.ZERO, fp2.ZERO)
FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


# ---------------------------------------------------------------------- Fp6


def fp6_add(a, b):
    return tuple(fp2.add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2.sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2.neg(x) for x in a)


def fp6_mul(a, b):
    """Toom/Karatsuba-style 6-multiplication schedule."""
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2.mul(a0, b0)
    t1 = fp2.mul(a1, b1)
    t2 = fp2.mul(a2, b2)
    c0 = fp2.add(
        t0,
        fp2.mul_by_xi(
            fp2.sub(
                fp2.sub(fp2.mul(fp2.add(a1, a2), fp2.add(b1, b2)), t1), t2
            )
        ),
    )
    c1 = fp2.add(
        fp2.sub(
            fp2.sub(fp2.mul(fp2.add(a0, a1), fp2.add(b0, b1)), t0), t1
        ),
        fp2.mul_by_xi(t2),
    )
    c2 = fp2.add(
        fp2.sub(fp2.sub(fp2.mul(fp2.add(a0, a2), fp2.add(b0, b2)), t0), t2),
        t1,
    )
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    return (fp2.mul_by_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2.sub(fp2.sqr(a0), fp2.mul_by_xi(fp2.mul(a1, a2)))
    c1 = fp2.sub(fp2.mul_by_xi(fp2.sqr(a2)), fp2.mul(a0, a1))
    c2 = fp2.sub(fp2.sqr(a1), fp2.mul(a0, a2))
    norm = fp2.add(
        fp2.mul(a0, c0),
        fp2.mul_by_xi(fp2.add(fp2.mul(a2, c1), fp2.mul(a1, c2))),
    )
    ninv = fp2.inv(norm)
    return (fp2.mul(c0, ninv), fp2.mul(c1, ninv), fp2.mul(c2, ninv))


def fp6_select(cond, a, b):
    return tuple(fp2.select(cond, x, y) for x, y in zip(a, b))


# --------------------------------------------------------------------- Fp12


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(
        fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1
    )
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    norm = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    ninv = fp6_inv(norm)
    return (fp6_mul(a0, ninv), fp6_neg(fp6_mul(a1, ninv)))


def _gamma_like(i, ref):
    """Broadcast Frobenius constant i over ref's batch shape (ref: Fp limbs)."""
    return fp2.broadcast_const(FROB_GAMMA_MONT[i], ref)


def fp12_frobenius(a):
    """a^p: conjugate every Fp2 coefficient, scale by gamma powers."""
    (a00, a01, a02), (a10, a11, a12) = a
    ref = a00[0]
    c0 = (
        fp2.conj(a00),
        fp2.mul(fp2.conj(a01), _gamma_like(2, ref)),
        fp2.mul(fp2.conj(a02), _gamma_like(4, ref)),
    )
    c1 = (
        fp2.mul(fp2.conj(a10), _gamma_like(1, ref)),
        fp2.mul(fp2.conj(a11), _gamma_like(3, ref)),
        fp2.mul(fp2.conj(a12), _gamma_like(5, ref)),
    )
    return (c0, c1)


def fp12_select(cond, a, b):
    return (fp6_select(cond, a[0], b[0]), fp6_select(cond, a[1], b[1]))


def fp12_eq(a, b):
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_b = jax.tree_util.tree_leaves(b)
    acc = None
    for x, y in zip(leaves_a, leaves_b):
        e = jnp.all(x == y, axis=-1)
        acc = e if acc is None else (acc & e)
    return acc


def fp12_is_one(a):
    """Batched check a == 1 (Montgomery domain)."""
    one = fp12_broadcast_one(a)
    return fp12_eq(a, one)


def fp12_broadcast_one(like):
    ref = jax.tree_util.tree_leaves(like)[0]
    batch = ref.shape[:-1]

    def bc(c):
        return jnp.broadcast_to(jnp.asarray(c), batch + (NLIMBS,))

    return jax.tree_util.tree_map(bc, FP12_ONE)


def fp12_product_axis(a, axis: int = 0):
    """Tree-fold product of a batch of Fp12 values along `axis` — the
    reduction that merges per-pair Miller-loop outputs before one shared
    final exponentiation (reference semantics: one multi-pairing per batch,
    crypto/bls/src/impls/blst.rs verify_multiple_aggregate_signatures)."""
    n = jax.tree_util.tree_leaves(a)[0].shape[axis]
    while n > 1:
        half = n // 2
        x = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, 0, half, axis=axis), a
        )
        y = jax.tree_util.tree_map(
            lambda t: jax.lax.slice_in_dim(t, half, 2 * half, axis=axis), a
        )
        prod = fp12_mul(x, y)
        if n % 2:
            tail = jax.tree_util.tree_map(
                lambda t: jax.lax.slice_in_dim(t, n - 1, n, axis=axis), a
            )
            prod = jax.tree_util.tree_map(
                lambda p, t: jnp.concatenate([p, t], axis=axis), prod, tail
            )
        a = prod
        n = half + (n % 2)
    return jax.tree_util.tree_map(lambda t: jnp.squeeze(t, axis=axis), a)


# ------------------------------------------------------------- host helpers


def fp12_pack(vals):
    """Host: list of ref-format Fp12 values -> device batch (Montgomery)."""

    def gather(path_fn):
        return fp2.to_mont(fp2.pack([path_fn(v) for v in vals]))

    c0 = tuple(gather(lambda v, i=i: v[0][i]) for i in range(3))
    c1 = tuple(gather(lambda v, i=i: v[1][i]) for i in range(3))
    return (c0, c1)


def fp12_unpack(a):
    """Host: device Fp12 batch -> list of ref-format values."""
    c0 = [fp2.to_ints(fp2.from_mont(c)) for c in a[0]]
    c1 = [fp2.to_ints(fp2.from_mont(c)) for c in a[1]]
    n = len(c0[0])
    out = []
    for i in range(n):
        out.append(
            (
                (c0[0][i], c0[1][i], c0[2][i]),
                (c1[0][i], c1[1][i], c1[2][i]),
            )
        )
    return out
