"""Batch-last (transposed) Fp12 final exponentiation — the Pallas tail.

The verdict stage of batch verification — fold the per-pair Miller outputs
into one Fp12 product and raise it to 3*(p^12-1)/r (the role of the final
exponentiation inside the reference backend's one multi-pairing,
crypto/bls/src/impls/blst.rs:114-116) — runs on a batch of ONE value, so
on the XLA path it is pure sequential latency: ~300 small Fp12 ops, each
round-tripping HBM. This module re-expresses the whole chain on
ops.tfield `(S, NB, B)` bundles so ops.pallas_tail can run it inside one
VMEM-resident kernel. Runs in three modes:

  * pure jnp under jit (XLA; numerically validated against ops.pairing);
  * as the body of the Pallas tail kernel (ops.pallas_tail);
  * interpret-mode for CPU tests.

Bit ladders take a `get_bit(i)` accessor so the kernel can read exponent
bits from an SMEM ref while the jit path indexes captured arrays. The
Frobenius constants are passed as values (kernels cannot capture array
constants — same convention as tfield.const_overrides).
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import BLS_X_ABS, P
from lighthouse_tpu.ops import tfield as tf
from lighthouse_tpu.ops import tpairing as tp
from lighthouse_tpu.ops import tower
from lighthouse_tpu.ops.programs import FP2_MUL, FP6_MUL

NB = tf.NB

# LSB-first exponent bit arrays for the two ladders in the chain.
P_MINUS_2_BITS = np.array(
    [((P - 2) >> i) & 1 for i in range((P - 2).bit_length())], np.int32
)
X_ABS_BITS = np.array(
    [(BLS_X_ABS >> i) & 1 for i in range(BLS_X_ABS.bit_length())], np.int32
)

_XI = np.array([[1, -1], [1, 1]], dtype=np.int32)
_FP2_CONJ = np.array([[1, 0], [0, -1]], dtype=np.int32)


def frob_consts() -> np.ndarray:
    """(24, NB) int32 constant block for the kernel: rows 0..11 the
    p-Frobenius gamma scalings (6 Fp2 in Frobenius slot order = tower
    _FROB_GAMMAS), rows 12..23 the p^2-Frobenius Fp norms (_FROB2_N)."""
    g = tower._FROB_GAMMAS.reshape(12, NB)
    return np.concatenate([g, tower._FROB2_N]).astype(np.int32)


# --------------------------------------------------------------- helpers


def fp12_conj(f):
    return tf.apply_combo(f, tower._CONJ12)


def _fp2_mul(a, b):
    return tp.bilinear(a, b, FP2_MUL)


def _fp2_mul_by_xi(a):
    return tf.apply_combo(a, _XI)


def _fp6_mul_by_v(a):
    return tf.apply_combo(a, tower._MUL_BY_V6)


def _fp6_neg(a):
    return tf.apply_combo(a, -np.eye(6, dtype=np.int32))


def fp12_frobenius(f, gammas):
    """f^p. `gammas`: (12, NB, 1) batch-last gamma constants
    (frob_consts() rows 0..11)."""
    conjed = tf.apply_combo(f, tower._CONJ_EACH)
    pairs = conjed.reshape((6, 2) + conjed.shape[-2:])
    gp = gammas.reshape((6, 2) + gammas.shape[-2:])
    out = _fp2_mul(pairs, jnp.broadcast_to(gp, pairs.shape))
    return out.reshape(f.shape)


def fp12_frobenius2(f, norms):
    """f^(p^2) for any Fp12 element: slot-wise scale by Fp norms
    (frob_consts() rows 12..23, shaped (12, NB, 1))."""
    return tf.mul_lazy(f, jnp.broadcast_to(norms, f.shape))


# ----------------------------------------------------------------- ladders


def _pow_bits(mul_fn, sqr_fn, one, base, n_bits, get_bit):
    """Square-and-multiply, LSB-first bits via `get_bit(i)`. The multiply
    is under lax.cond — a skipped bit costs only the squaring (proven
    in-kernel by the Miller scan's add-step cond)."""

    def body(i, carry):
        result, b = carry
        result = jax.lax.cond(
            get_bit(i) == 1,
            lambda rb: mul_fn(rb[0], rb[1]),
            lambda rb: rb[0],
            (result, b),
        )
        return result, sqr_fn(b)

    result, _ = jax.lax.fori_loop(0, n_bits, body, (one, base))
    return result


def fp_inv(a, get_pbit=None):
    """Per-slot Fermat inverse a^(p-2) on (..., S, NB, B) bundles."""
    if get_pbit is None:
        bits = jnp.asarray(P_MINUS_2_BITS)
        get_pbit = lambda i: bits[i]  # noqa: E731
    one = jnp.broadcast_to(tf.one_col(), a.shape)
    return _pow_bits(
        tf.mul_lazy, tf.sqr_lazy, one, a, len(P_MINUS_2_BITS), get_pbit
    )


def fp2_inv(a, get_pbit=None):
    """1/(c0 + c1 u) = conj(a) / (c0^2 + c1^2) on (..., 2, NB, B)."""
    sq = tf.sqr_lazy(a)  # (c0^2, c1^2) slotwise
    norm = tf.add(sq[..., 0:1, :, :], sq[..., 1:2, :, :])
    ninv = fp_inv(norm, get_pbit)
    conj = tf.apply_combo(a, _FP2_CONJ)
    return tf.mul_lazy(conj, jnp.broadcast_to(ninv, conj.shape))


def fp6_inv(a, get_pbit=None):
    """Tower inversion on (..., 6, NB, B) (tower.fp6_inv transposed)."""
    a3 = a.reshape(a.shape[:-3] + (3, 2) + a.shape[-2:])
    a0 = a3[..., 0, :, :, :]
    a1 = a3[..., 1, :, :, :]
    a2 = a3[..., 2, :, :, :]
    lhs = jnp.stack([a0, a1, a2, a0, a1, a0], axis=-4)
    rhs = jnp.stack([a0, a1, a2, a1, a2, a2], axis=-4)
    prods = _fp2_mul(lhs, rhs)
    sq0 = prods[..., 0, :, :, :]
    sq1 = prods[..., 1, :, :, :]
    sq2 = prods[..., 2, :, :, :]
    p01 = prods[..., 3, :, :, :]
    p12 = prods[..., 4, :, :, :]
    p02 = prods[..., 5, :, :, :]
    c0 = tf.sub(sq0, _fp2_mul_by_xi(p12))
    c1 = tf.sub(_fp2_mul_by_xi(sq2), p01)
    c2 = tf.sub(sq1, p02)
    pr = _fp2_mul(
        jnp.stack([a0, a2, a1], axis=-4), jnp.stack([c0, c1, c2], axis=-4)
    )
    norm = tf.add(
        pr[..., 0, :, :, :],
        _fp2_mul_by_xi(tf.add(pr[..., 1, :, :, :], pr[..., 2, :, :, :])),
    )
    ninv = fp2_inv(norm, get_pbit)
    scaled = _fp2_mul(
        jnp.stack([c0, c1, c2], axis=-4),
        jnp.broadcast_to(
            ninv[..., None, :, :, :], c0.shape[:-3] + (3,) + ninv.shape[-3:]
        ),
    )
    return scaled.reshape(a.shape)


def fp12_inv(a, get_pbit=None):
    """1/(b0 + b1 w) = (b0 - b1 w)/(b0^2 - v b1^2) on (12, NB, B)."""
    b0 = a[..., :6, :, :]
    b1 = a[..., 6:, :, :]
    sq = tp.bilinear(
        jnp.stack([b0, b1], axis=-4), jnp.stack([b0, b1], axis=-4), FP6_MUL
    )
    norm = tf.sub(sq[..., 0, :, :, :], _fp6_mul_by_v(sq[..., 1, :, :, :]))
    ninv = fp6_inv(norm, get_pbit)
    scaled = tp.bilinear(
        jnp.stack([b0, b1], axis=-4),
        jnp.broadcast_to(
            ninv[..., None, :, :, :], b0.shape[:-3] + (2,) + ninv.shape[-3:]
        ),
        FP6_MUL,
    )
    return jnp.concatenate(
        [scaled[..., 0, :, :, :], _fp6_neg(scaled[..., 1, :, :, :])],
        axis=-3,
    )


def pow_x_abs(f, get_xbit=None):
    """f^|x| (|x| = BLS_X_ABS, Hamming weight 6 — the cond ladder skips
    58 of 64 multiplies)."""
    if get_xbit is None:
        bits = jnp.asarray(X_ABS_BITS)
        get_xbit = lambda i: bits[i]  # noqa: E731
    one = tp.fp12_one(f.shape[-1])
    return _pow_bits(
        tp.fp12_mul, tp.fp12_sqr, one, f, len(X_ABS_BITS), get_xbit
    )


# ------------------------------------------------------------- the chain


def final_exponentiation_t(f, gammas, norms, get_pbit=None, get_xbit=None):
    """f^(3 (p^12-1)/r) on a (12, NB, B) bundle — ops.pairing's addition
    chain transposed. `gammas`/`norms` are frob_consts() halves shaped
    (12, NB, 1)."""

    def pow_neg_x(g):
        return fp12_conj(pow_x_abs(g, get_xbit))

    f = tp.fp12_mul(fp12_conj(f), fp12_inv(f, get_pbit))
    f = tp.fp12_mul(fp12_frobenius2(f, norms), f)
    t0 = tp.fp12_mul(pow_neg_x(f), fp12_conj(f))
    t1 = tp.fp12_mul(pow_neg_x(t0), fp12_conj(t0))
    t2 = tp.fp12_mul(pow_neg_x(t1), fp12_frobenius(t1, gammas))
    t3 = tp.fp12_mul(
        pow_neg_x(pow_neg_x(t2)),
        tp.fp12_mul(fp12_frobenius2(t2, norms), fp12_conj(t2)),
    )
    f3 = tp.fp12_mul(tp.fp12_mul(f, f), f)
    return tp.fp12_mul(t3, f3)


def fold_lanes(f):
    """Lane-halving tree product: (12, NB, B) -> (12, NB, 1) — the lane
    axis analog of tower.fp12_product_axis (odd counts carry a tail)."""
    B = f.shape[-1]
    while B > 1:
        half = B // 2
        prod = tp.fp12_mul(f[..., :half], f[..., half : 2 * half])
        if B % 2:
            prod = jnp.concatenate([prod, f[..., B - 1 :]], axis=-1)
        f = prod
        B = half + (B % 2)
    return f
