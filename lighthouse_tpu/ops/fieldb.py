"""Array-native ("bundled") BLS12-381 field arithmetic.

The scalar-composed tower in ops.fp builds one jaxpr equation per limb-level
operation, which made the Miller-loop graph ~30k equations — infeasible to
trace/compile. This module is the TPU-native layout:

- A value bundle is an int32 array `(..., S, NB)`: S field "slots"
  (Fp2 = 2, Fp6 = 6, Fp12 = 12, a G2 coordinate = 2, ...), NB = 33 limbs of
  12 bits (one spare limb beyond 384 bits gives linear-combination
  headroom).
- LINEAR algebra over slots (Karatsuba sums, xi-multiplications, tower
  recombination, negation, small scalars) is ONE einsum against a small
  static integer matrix — `apply_combo` — instead of per-slot graphs.
- All the independent Montgomery products of a tower multiplication run as
  ONE stacked convolution (`mul_lazy`), e.g. an Fp12 product is a single
  18-slot multiply.

RELAXED-LIMB INVARIANT (the key to a small graph — no exact carry
resolution anywhere on the hot path):

  Every bundle flowing between ops has non-negative limbs <= LIMB_RELAX
  (4097) and value < 2.2p. Exact canonical limbs/values exist only inside
  `canon` (predicates / host boundaries), which runs the one Kogge-Stone
  resolve in the module.

  Why this is sound (numbers: p = 1.6256*2^380, R = 2^384, p/R = 0.1016,
  2p = 832.009*2^372, so the reduce_small divisor error per quotient unit
  is d = 833*2^372 - 2p = 0.991*2^372 = 0.00238p):
  * conv products: limbs <= 4097 give per-term products <= 4097^2 and
    column sums <= 66 * 4097^2 < 2^31 — no int32 overflow.
  * `_relax(x, n_passes)`: each partial carry pass maps limb bound L to
    4095 + (L >> 12); three passes take any L < 2^30 down to <= 4096.
    Passes preserve value exactly (shift/mask arithmetic), including for
    negative intermediates (arithmetic shift = floor division).
  * Montgomery REDC carry across the R boundary: t + m*p = 0 mod R with
    value(low 32 limbs) < 1.001*R, so value(low) is EXACTLY 0 or R.
    Non-negative limbs mean value 0 <=> all limbs 0, hence the carry into
    the high half is just `any(low != 0)` — no carry network needed.
  * `reduce_small` quotient estimate: q = floor(top_two_limbs / 833)
    satisfies q*2p <= x, and the remainder is
    < 833*2^372 + q*d + value(relaxed low limbs)
    = 2.004p + 0.00238p*q + 0.0012p.
  * Bound closure at 2.2p:
      mul_lazy: inputs < 2.2p -> T < 4.84 p^2, T/R < 4.84*(p/R)*p
        = 0.492p, output < 0.492p + 1.001p < 1.5p.
      add: x < 4.4p -> q <= 2 -> out < 2.01p.
      sub: x < 2.2p + 32p + eps < 34.3p -> q <= 17 -> out < 2.05p.
      scalar_small (k <= 12): x < 26.5p -> q <= 13 -> out < 2.04p.
      apply_combo: x < (36*2.2 + 368)p = 448p -> q <= 224 -> first
        reduce_small gives < 2.004p + 0.54p = 2.55p, so it reduces
        TWICE; second pass input < 2.55p = 1038*2^372 -> q <= 1 ->
        out < 2.01p.
    Everything stays < 2.05p < 2.2p, with ~0.15p margin (verified
    adversarially in tests/test_fieldb_bounds.py).

The multiplication *programs* (which slot combinations feed which product,
and how products recombine) are built symbolically at import time from the
same tower formulas validated in crypto/ref_fields — see ops.programs.

Parity note: this plane replaces blst's field/tower arithmetic behind the
reference's BLS boundary (crypto/bls/src/impls/blst.rs), re-laid-out for
MXU/VPU execution.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import (
    LIMB_BITS,
    LIMB_MASK,
    MONT_R_MOD_P,
    MONT_R2_MOD_P,
    NLIMBS,
    P,
    int_to_limbs,
)

NB = NLIMBS + 1  # bundle limb count (one headroom limb)
_TOP = NB - 1
LIMB_RELAX = LIMB_MASK + 2  # relaxed limb bound (4097)

_NPRIME_INT = (-pow(P, -1, 1 << (LIMB_BITS * NLIMBS))) % (
    1 << (LIMB_BITS * NLIMBS)
)
NPRIME_LIMBS = np.array(int_to_limbs(_NPRIME_INT), dtype=np.int32)
P_LIMBS32 = np.array(int_to_limbs(P), dtype=np.int32)


def _limbs(v: int, n: int) -> np.ndarray:
    return np.array(
        [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n)],
        dtype=np.int32,
    )


ZERO_B = np.zeros(NB, dtype=np.int32)
ONE_MONT_B = _limbs(MONT_R_MOD_P, NB)
R2_B = _limbs(MONT_R2_MOD_P, NB)

# 2^396 - 2p: adding q copies == subtracting q*2p mod 2^396.
COMP_2P = _limbs((1 << (LIMB_BITS * NB)) - 2 * P, NB)
# 2^396 - p (for canonicalization cond-subtract)
COMP_P = _limbs((1 << (LIMB_BITS * NB)) - P, NB)

# Offset constant for signed combos: value 368p (top limb 37 — enough to
# absorb the 37-unit spread; 365p is the minimum for that), limbs spread so
# every limb except the top is >= 37*4096 - 37 > 36*LIMB_RELAX — covers
# combos with L1 norm <= 36 over relaxed-limb inputs (the Fp12
# recombination rows reach 36). Bound chain: combo result + offset
# < (36*2.2 + 368)p = 448p < 2^390 << 2^396, within reduce_small's
# quotient-estimate domain.
_OFF_K = 36
_OFF_SPREAD = 37
OFF_CONST = _limbs(368 * P, NB)
for _i in range(NB - 1):
    OFF_CONST[_i] += _OFF_SPREAD << LIMB_BITS
    OFF_CONST[_i + 1] -= _OFF_SPREAD
assert OFF_CONST.min() >= 0
assert OFF_CONST[:-1].min() >= _OFF_K * LIMB_RELAX

# Subtraction constant: value 32p (top limb 3 — enough to absorb the
# 2-unit spread), limbs spread by two units (>= 2*4096 - 2 >= LIMB_RELAX,
# so a - b + SPREAD_SUB has non-negative limbs for any relaxed-limb b).
# Value headroom: a - b + 32p < 34.3p keeps reduce_small's q <= 17.
SPREAD_SUB = _limbs(32 * P, NB)
for _i in range(NB - 1):
    SPREAD_SUB[_i] += 2 << LIMB_BITS
    SPREAD_SUB[_i + 1] -= 2
assert SPREAD_SUB.min() >= 0 and SPREAD_SUB[:-1].min() >= LIMB_RELAX

# Convolution masks (i + j == k), full and low-truncated.
_CONV_FULL = np.zeros((NB, NB, 2 * NB - 1), dtype=np.int32)
for _i in range(NB):
    for _j in range(NB):
        _CONV_FULL[_i, _j, _i + _j] = 1
_CONV_LOW32 = np.zeros((NLIMBS, NLIMBS, NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        if _i + _j < NLIMBS:
            _CONV_LOW32[_i, _j, _i + _j] = 1
_CONV_MP = np.zeros((NLIMBS, NLIMBS, 2 * NLIMBS - 1), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_MP[_i, _j, _i + _j] = 1


# ----------------------------------------------------------- carry handling


def _pad_last(x, n):
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, n)])


def _partial_pass(x):
    c = x >> LIMB_BITS
    d = x & LIMB_MASK
    return d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])


def _relax(x, out_len, passes=3):
    """Value-preserving (mod 2^(12*out_len)) relaxation to limbs <= ~4096.

    Carries beyond out_len are dropped — callers use this deliberately for
    mod-R / mod-2^396 arithmetic. `passes` must satisfy the bound chain
    L -> 4095 + (L >> 12) from the caller's input limb bound down to
    <= LIMB_RELAX.
    """
    in_len = x.shape[-1]
    if in_len < out_len:
        x = _pad_last(x, out_len - in_len)
    elif in_len > out_len:
        x = x[..., :out_len]
    for _ in range(passes):
        x = _partial_pass(x)
    return x


def _ks_resolve(x):
    """Kogge-Stone carry resolution; limbs must be < 2*4096 (unit carries).
    Returns (canonical limbs, top carry-out). Used only by `canon`."""
    g = x > LIMB_MASK
    p = x == LIMB_MASK
    shift = 1
    L = x.shape[-1]
    gg, pp = g, p
    while shift < L:
        pad = [(0, 0)] * (x.ndim - 1) + [(shift, 0)]
        gg_prev = jnp.pad(gg[..., :-shift], pad)
        pp_prev = jnp.pad(pp[..., :-shift], pad)
        gg = gg | (pp & gg_prev)
        pp = pp & pp_prev
        shift *= 2
    carry_in = jnp.pad(
        gg[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    ).astype(jnp.int32)
    return (x + carry_in) & LIMB_MASK, gg[..., -1]


def reduce_small(x):
    """Relaxed-limbed x (NB limbs, value < ~2^24 * 2^372) -> value
    < 2.004p + 0.00238p*q_max, limbs <= 4096 (q_max = value_bound/2p; the
    callers in this module keep outputs < 2.05p — see module docstring;
    inputs above ~80p need a second pass to get back under 2.2p).

    Quotient estimate from the top two limbs against 2p (2p < 833*2^372):
    q = (x >> 372) // 833 satisfies q*2p <= x (see module docstring)."""
    t2 = x[..., _TOP] * (1 << LIMB_BITS) + x[..., _TOP - 1]
    q = t2 // 833
    return _relax(x + q[..., None] * jnp.asarray(COMP_2P), NB)


def _cond_sub(x, comp_const):
    """Subtract the complement's value iff x >= value (exact compare).
    Input limbs must be canonical (callers resolve first)."""
    s = x + jnp.asarray(comp_const)
    c = s >> LIMB_BITS
    d = s & LIMB_MASK
    top1 = c[..., -1]
    s = d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    out, top2 = _ks_resolve(s)
    ge = (top1 + top2.astype(jnp.int32)) > 0
    return jnp.where(ge[..., None], out, x)


def canon(x):
    """Lazy value (< 2.5p) -> exact canonical [0, p), canonical limbs."""
    x, _ = _ks_resolve(x)  # relaxed limbs (<= 4097, unit carries) -> exact
    x = _cond_sub(x, COMP_2P)
    return _cond_sub(x, COMP_P)


# ------------------------------------------------------------- multiplies


def use_mxu_conv() -> bool:
    """Route the limb-product contractions through int8 MXU matmuls
    (LIGHTHOUSE_TPU_MXU_CONV=1). Read at trace time — build fresh jitted
    functions after flipping it."""
    import os

    # lint: allow(device-purity): trace-time knob, keyed via _impl_key
    return os.environ.get("LIGHTHOUSE_TPU_MXU_CONV") == "1"


def _conv_contract(prod, conv_tensor):
    """Contract per-limb products (..., I, J) int32 against a 0/1
    convolution indicator (I, J, K) -> (..., K).

    Default: one int32 einsum (VPU). MXU path: the products are
    NON-NEGATIVE and < 2^28, so they decompose EXACTLY into four base-128
    digits that fit int8; each digit is contracted against the (flattened)
    indicator with an int8 x int8 -> int32 matmul — the op shape the MXU
    runs at ~394 TOPS on v5e vs ~2T int32 op/s on the VPU (PERF_NOTES
    plan item 2). Column sums stay < 2^31, so the recombination
    sum(part_n << 7n) is exact in int32 and the result is bit-identical
    to the VPU path (the relaxed-limb bound proofs are untouched)."""
    # lint: allow(device-purity): conv_tensor is a static 0/1 host constant
    conv = np.asarray(conv_tensor)
    if not use_mxu_conv():
        return jnp.einsum("...ij,ijk->...k", prod, jnp.asarray(conv))
    flat = prod.reshape(prod.shape[:-2] + (-1,))
    mat = jnp.asarray(
        conv.reshape(-1, conv.shape[-1]).astype(np.int8)
    )
    out = None
    x = flat
    for n in range(4):  # 4 * 7 = 28 bits covers max product 4097^2
        piece = (x & 127).astype(jnp.int8)
        x = x >> 7
        part = jnp.einsum(
            "...x,xk->...k",
            piece,
            mat,
            preferred_element_type=jnp.int32,
        )
        out = part if out is None else out + (part << (7 * n))
    return out


def mul_lazy(a, b):
    """Stacked Montgomery product over the slot axis: (..., S, NB) x
    (..., S, NB) -> (..., S, NB); inputs < 2.2p relaxed, output < 1.5p,
    limbs <= LIMB_RELAX."""
    t = _relax(
        _conv_contract(a[..., :, None] * b[..., None, :], _CONV_FULL),
        2 * NB,
    )
    t_low = t[..., :NLIMBS]
    m = _relax(
        _conv_contract(
            t_low[..., :, None] * jnp.asarray(NPRIME_LIMBS)[None, :],
            _CONV_LOW32,
        ),
        NLIMBS,
    )
    mp = _conv_contract(
        m[..., :, None] * jnp.asarray(P_LIMBS32)[None, :], _CONV_MP
    )
    full = _relax(t + _pad_last(mp, 2 * NB - mp.shape[-1]), 2 * NB)
    # REDC carry across the R boundary: value(low 32 limbs) is exactly 0 or
    # R (it is = 0 mod R and < 1.001R), and limbs are non-negative, so the
    # carry is any(low != 0).
    low_nonzero = jnp.any(full[..., :NLIMBS] != 0, axis=-1)
    out = full[..., NLIMBS : NLIMBS + NB]
    return out.at[..., 0].add(low_nonzero.astype(jnp.int32))


def sqr_lazy(a):
    return mul_lazy(a, a)


# --------------------------------------------------------------- combos


def apply_combo(x, matrix):
    """Static small-integer slot recombination: (..., S_in, NB) -> (...,
    S_out, NB), each output < 2.01p. Matrix L1 row norms must be <= 36.

    Reduces twice: the offset pushes the value to ~448p, where one
    quotient-estimate pass only reaches ~2.55p (see module docstring)."""
    # lint: allow(device-purity): matrix is a static recombination table
    m = np.asarray(matrix, dtype=np.int32)
    assert np.abs(m).sum(axis=1).max() <= _OFF_K, "combo L1 too large"
    y = jnp.einsum("os,...sn->...on", jnp.asarray(m), x)
    y = _relax(y + jnp.asarray(OFF_CONST), NB, passes=2)
    return reduce_small(reduce_small(y))


def add(a, b):
    return reduce_small(_partial_pass(a + b))


def sub(a, b):
    s = a - b + jnp.asarray(SPREAD_SUB)
    return reduce_small(_relax(s, NB, passes=2))


def neg(a):
    return sub(jnp.zeros_like(a), a)


def scalar_small(a, k: int):
    if k == 0:
        return jnp.zeros_like(a)
    assert k <= 12
    s = a * k  # limbs <= 12*4097 < 2^16
    return reduce_small(_relax(s, NB, passes=2))


# ------------------------------------------------------------- predicates


def is_zero(a):
    """Batched per-slot-group zero test; reduces over the slot axis."""
    return jnp.all(canon(a) == 0, axis=(-2, -1))


def eq(a, b):
    return jnp.all(canon(a) == canon(b), axis=(-2, -1))


def select(cond, a, b):
    """cond broadcasts over (slots, limbs)."""
    return jnp.where(cond[..., None, None], a, b)


# ----------------------------------------------------- static powers / inv


def pow_const(a, exponent: int):
    """a^e per slot (Montgomery), static exponent, fori_loop ladder."""
    nbits = max(1, exponent.bit_length())
    bits = jnp.asarray(
        np.array([(exponent >> i) & 1 for i in range(nbits)], np.int32)
    )

    def body(i, carry):
        result, base = carry
        mult = mul_lazy(result, base)
        result = jnp.where(bits[i] == 1, mult, result)
        base = sqr_lazy(base)
        return result, base

    one = jnp.broadcast_to(jnp.asarray(ONE_MONT_B), a.shape)
    result, _ = jax.lax.fori_loop(0, nbits, body, (one, a))
    return result


def inv(a):
    """Per-slot Fermat inverse; inv(0) == 0."""
    return pow_const(a, P - 2)


# --------------------------------------------------------- host converters


def pack_ints(values) -> np.ndarray:
    """Host: list of ints -> (S, NB) canonical limb bundle (plain domain)."""
    return np.stack([_limbs(v % P, NB) for v in values])


def unpack_ints(bundle) -> list:
    out = []
    arr = np.asarray(bundle)
    flat = arr.reshape(-1, arr.shape[-1])
    for row in flat:
        acc = 0
        for i, limb in enumerate(row):
            acc += int(limb) << (LIMB_BITS * i)
        out.append(acc % P)
    return out


def to_mont(a):
    return mul_lazy(a, jnp.broadcast_to(jnp.asarray(R2_B), a.shape))


def from_mont(a):
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return canon(mul_lazy(a, one))
