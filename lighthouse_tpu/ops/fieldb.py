"""Array-native ("bundled") BLS12-381 field arithmetic.

The scalar-composed tower in ops.fp/fp2/tower builds one jaxpr equation per
limb-level operation, which made the Miller-loop graph ~30k equations —
infeasible to trace/compile. This module is the TPU-native layout:

- A value bundle is an int32 array `(..., S, NB)`: S field "slots"
  (Fp2 = 2, Fp6 = 6, Fp12 = 12, a G2 coordinate = 2, ...), NB = 33 limbs of
  12 bits (one spare limb beyond 384 bits gives linear-combination
  headroom).
- LINEAR algebra over slots (Karatsuba sums, xi-multiplications, tower
  recombination, negation, small scalars) is ONE einsum against a small
  static integer matrix — `apply_combo` — instead of per-slot graphs.
- All the independent Montgomery products of a tower multiplication run as
  ONE stacked convolution (`mul_lazy`), e.g. an Fp12 product is a single
  18-slot multiply.
- Values are kept *lazily reduced*: canonical limbs in [0, 2^12), value in
  [0, ~2.2p). Exact canonicalization to [0, p) happens only in predicates
  (`canon`, `eq`, `is_zero`) and at host boundaries. Bound bookkeeping:
    mul_lazy inputs  < 2.2p  -> T < 4.84 p^2 < R p  (REDC valid)
    mul_lazy output  < T/R + 1.0003p < 1.5p
    apply_combo: |result before offset| < L1 * 2.2p; adding the 120p
    spread offset keeps limbs non-negative for L1 <= 12, and
    `reduce_small` (top-two-limb quotient estimate against 2p) returns
    values < 2.2p.

The multiplication *programs* (which slot combinations feed which product,
and how products recombine) are built symbolically at import time from the
same tower formulas validated in crypto/ref_fields — see `_BilinearBuilder`.

Parity note: this plane replaces blst's field/tower arithmetic behind the
reference's BLS boundary (crypto/bls/src/impls/blst.rs), re-laid-out for
MXU/VPU execution.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import (
    LIMB_BITS,
    LIMB_MASK,
    MONT_R_MOD_P,
    MONT_R2_MOD_P,
    NLIMBS,
    P,
    int_to_limbs,
)

NB = NLIMBS + 1  # bundle limb count (one headroom limb)
_TOP = NB - 1

_NPRIME_INT = (-pow(P, -1, 1 << (LIMB_BITS * NLIMBS))) % (
    1 << (LIMB_BITS * NLIMBS)
)
NPRIME_LIMBS = np.array(int_to_limbs(_NPRIME_INT), dtype=np.int32)
P_LIMBS32 = np.array(int_to_limbs(P), dtype=np.int32)


def _limbs(v: int, n: int) -> np.ndarray:
    return np.array(
        [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n)],
        dtype=np.int32,
    )


ZERO_B = np.zeros(NB, dtype=np.int32)
ONE_MONT_B = _limbs(MONT_R_MOD_P, NB)
R2_B = _limbs(MONT_R2_MOD_P, NB)

# 2^396 - 2p: adding q copies == subtracting q*2p mod 2^396.
COMP_2P = _limbs((1 << (LIMB_BITS * NB)) - 2 * P, NB)
# 2^396 - p (for canonicalization cond-subtract)
COMP_P = _limbs((1 << (LIMB_BITS * NB)) - P, NB)

# Offset constant for signed combos: value 360p, limbs spread so every limb
# except the top is >= 36*4096 - 36 (covers combos with L1 norm <= 36 — the
# Fp12 recombination rows reach 36). Bound chain: combo result + offset
# < (36*2.2 + 360)p = 439p < 2^391 << 2^396, and reduce_small's top-two-limb
# quotient estimate stays exact for values < 2^24 * 2^372.
_OFF_K = 36
OFF_CONST = _limbs(360 * P, NB)
for _i in range(NB - 1):
    OFF_CONST[_i] += _OFF_K << LIMB_BITS
    OFF_CONST[_i + 1] -= _OFF_K
assert OFF_CONST.min() >= 0 and OFF_CONST[:-1].min() >= _OFF_K * 4095

# Subtraction constant: value 16p, limbs spread by one unit (covers
# subtracting any canonical-limbed value < 2.2p... limbs <= 4095).
SPREAD_16P = _limbs(16 * P, NB)
for _i in range(NB - 1):
    SPREAD_16P[_i] += 1 << LIMB_BITS
    SPREAD_16P[_i + 1] -= 1
assert SPREAD_16P.min() >= 0 and SPREAD_16P[:-1].min() >= 4095

# Convolution masks (i + j == k), full and low-truncated.
_CONV_FULL = np.zeros((NB, NB, 2 * NB - 1), dtype=np.int32)
for _i in range(NB):
    for _j in range(NB):
        _CONV_FULL[_i, _j, _i + _j] = 1
_CONV_LOW32 = np.zeros((NLIMBS, NLIMBS, NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        if _i + _j < NLIMBS:
            _CONV_LOW32[_i, _j, _i + _j] = 1
_CONV_MP = np.zeros((NLIMBS, NLIMBS, 2 * NLIMBS - 1), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_MP[_i, _j, _i + _j] = 1


# ----------------------------------------------------------- carry handling


def _pad_last(x, n):
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, n)])


def _partial_pass(x):
    c = x >> LIMB_BITS
    d = x & LIMB_MASK
    return d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])


def _ks_resolve(x):
    """Kogge-Stone carry resolution; limbs must be in [0, 2*2^12 - 2] with
    unit carries. Returns (canonical limbs, top carry-out)."""
    g = x > LIMB_MASK
    p = x == LIMB_MASK
    shift = 1
    L = x.shape[-1]
    gg, pp = g, p
    while shift < L:
        pad = [(0, 0)] * (x.ndim - 1) + [(shift, 0)]
        gg_prev = jnp.pad(gg[..., :-shift], pad)
        pp_prev = jnp.pad(pp[..., :-shift], pad)
        gg = gg | (pp & gg_prev)
        pp = pp & pp_prev
        shift *= 2
    carry_in = jnp.pad(
        gg[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    ).astype(jnp.int32)
    return (x + carry_in) & LIMB_MASK, gg[..., -1]


def _normalize(x, out_len):
    """Non-negative limbs (< 2^30) -> canonical limbs. Value beyond
    2^(12*out_len) is truncated (callers use this deliberately for mod-R /
    mod-2^396 arithmetic)."""
    in_len = x.shape[-1]
    if in_len < out_len:
        x = _pad_last(x, out_len - in_len)
    elif in_len > out_len:
        x = x[..., :out_len]
        # carries out of the kept range are multiples of the modulus the
        # caller reduces by; dropping them is intentional
    x = _partial_pass(x)
    x = _partial_pass(x)
    x = _partial_pass(x)
    out, _ = _ks_resolve(x)
    return out


def reduce_small(x):
    """Canonical-limbed x (NB limbs, value < ~2^24 * 2^372) -> value < 2.2p.

    Quotient estimate from the top two limbs against 2p (2p < 833*2^372):
    q = (x >> 372) // 833 satisfies q*2p <= x, and the remainder is
    bounded < 2.2p (see module docstring analysis)."""
    t2 = x[..., _TOP] * (1 << LIMB_BITS) + x[..., _TOP - 1]
    q = t2 // 833
    return _normalize(x + q[..., None] * jnp.asarray(COMP_2P), NB)


def _cond_sub(x, comp_const):
    """Subtract the complement's value iff x >= value (exact compare)."""
    s = x + jnp.asarray(comp_const)
    c = s >> LIMB_BITS
    d = s & LIMB_MASK
    top1 = c[..., -1]
    s = d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    out, top2 = _ks_resolve(s)
    ge = (top1 + top2.astype(jnp.int32)) > 0
    return jnp.where(ge[..., None], out, x)


def canon(x):
    """Lazy value (< 2.2p... < 3p) -> exact canonical [0, p)."""
    x = _cond_sub(x, COMP_2P)
    return _cond_sub(x, COMP_P)


# ------------------------------------------------------------- multiplies


def mul_lazy(a, b):
    """Stacked Montgomery product over the slot axis: (..., S, NB) x
    (..., S, NB) -> (..., S, NB); inputs < 2.2p, output < 1.5p."""
    t = _normalize(
        jnp.einsum(
            "...ij,ijk->...k",
            a[..., :, None] * b[..., None, :],
            jnp.asarray(_CONV_FULL),
        ),
        2 * NB,
    )
    t_low = t[..., :NLIMBS]
    m = _normalize(
        jnp.einsum(
            "...ij,ijk->...k",
            t_low[..., :, None] * jnp.asarray(NPRIME_LIMBS)[None, :],
            jnp.asarray(_CONV_LOW32),
        ),
        NLIMBS + 1,
    )[..., :NLIMBS]
    mp = jnp.einsum(
        "...ij,ijk->...k",
        m[..., :, None] * jnp.asarray(P_LIMBS32)[None, :],
        jnp.asarray(_CONV_MP),
    )
    full = _normalize(t + _pad_last(mp, 2 * NB - mp.shape[-1]), 2 * NB)
    return full[..., NLIMBS : NLIMBS + NB]


def sqr_lazy(a):
    return mul_lazy(a, a)


# --------------------------------------------------------------- combos


def apply_combo(x, matrix):
    """Static small-integer slot recombination: (..., S_in, NB) -> (...,
    S_out, NB), each output < 2.2p. Matrix L1 row norms must be <= 12."""
    m = np.asarray(matrix, dtype=np.int32)
    assert np.abs(m).sum(axis=1).max() <= _OFF_K, "combo L1 too large"
    y = jnp.einsum("os,...sn->...on", jnp.asarray(m), x)
    y = _normalize(y + jnp.asarray(OFF_CONST), NB)
    return reduce_small(y)


def add(a, b):
    s = _partial_pass(a + b)
    out, _ = _ks_resolve(s)
    return reduce_small(out)


def sub(a, b):
    s = _partial_pass(a - b + jnp.asarray(SPREAD_16P))
    out, _ = _ks_resolve(s)
    return reduce_small(out)


def neg(a):
    return sub(jnp.zeros_like(a), a)


def scalar_small(a, k: int):
    if k == 0:
        return jnp.zeros_like(a)
    s = a * k  # limbs <= 12*4095 for k <= 12
    assert k <= _OFF_K
    return reduce_small(_normalize(s, NB))


# ------------------------------------------------------------- predicates


def is_zero(a):
    """Batched per-slot-group zero test; reduces over the slot axis."""
    return jnp.all(canon(a) == 0, axis=(-2, -1))


def eq(a, b):
    return jnp.all(canon(a) == canon(b), axis=(-2, -1))


def select(cond, a, b):
    """cond broadcasts over (slots, limbs)."""
    return jnp.where(cond[..., None, None], a, b)


# ----------------------------------------------------- static powers / inv


def pow_const(a, exponent: int):
    """a^e per slot (Montgomery), static exponent, fori_loop ladder."""
    nbits = max(1, exponent.bit_length())
    bits = jnp.asarray(
        np.array([(exponent >> i) & 1 for i in range(nbits)], np.int32)
    )

    def body(i, carry):
        result, base = carry
        mult = mul_lazy(result, base)
        result = jnp.where(bits[i] == 1, mult, result)
        base = sqr_lazy(base)
        return result, base

    one = jnp.broadcast_to(jnp.asarray(ONE_MONT_B), a.shape)
    result, _ = jax.lax.fori_loop(0, nbits, body, (one, a))
    return result


def inv(a):
    """Per-slot Fermat inverse; inv(0) == 0."""
    return pow_const(a, P - 2)


# --------------------------------------------------------- host converters


def pack_ints(values) -> np.ndarray:
    """Host: list of ints -> (S, NB) canonical limb bundle (plain domain)."""
    return np.stack([_limbs(v % P, NB) for v in values])


def unpack_ints(bundle) -> list:
    out = []
    arr = np.asarray(bundle)
    flat = arr.reshape(-1, arr.shape[-1])
    for row in flat:
        acc = 0
        for i, limb in enumerate(row):
            acc += int(limb) << (LIMB_BITS * i)
        out.append(acc % P)
    return out


def to_mont(a):
    return mul_lazy(a, jnp.broadcast_to(jnp.asarray(R2_B), a.shape))


def from_mont(a):
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return canon(mul_lazy(a, one))
