"""Device data plane for batched KZG proof verification.

Same shape discipline as `ops.batch_verify`, different workload: N blob
proof checks fold into ONE two-pair multi-pairing via per-proof RLC
scalars r_i,

    e( sum_i r_i C_i + sum_i [r_i z_i] W_i - [sum_i r_i y_i] G1,  G2 )
      * e( -sum_i r_i W_i,  [tau]G2 )  ==  1.

The lane layout carries every scalar-multiplied point of the identity
through ONE dispatch into the shared signed-digit window kernel
(`ops.window_ladder` — the same plane the signature RLC ladders use;
the legacy 3N independent 255-bit double-add ladders are retired, kept
only behind LIGHTHOUSE_TPU_LADDER=chain for A/B). Complete RCB
formulas — all inputs are host-subgroup-checked at decompression, so
the r-torsion precondition holds:

    lanes [0,   N)   : C_i  with scalar r_i
    lanes [N,  2N)   : W_i  with scalar r_i * z_i mod r
    lanes [2N, 3N)   : W_i  with scalar r_i

then two tree folds (lanes [0, 2N) + the host-computed -[sum r_i y_i]G1
auxiliary lane -> the left pair; lanes [2N, 3N) negated -> the right
pair) and the shared Miller/final-exp plane. Masked lanes ride as the
identity, exact under e(inf, .) == 1.

Host-side policy (decompression, subgroup checks, challenge hashing,
polynomial evaluation, RLC sampling) lives in `lighthouse_tpu.kzg`.
"""

import numpy as np

import jax.numpy as jnp

from lighthouse_tpu.common.tracing import span
from lighthouse_tpu.crypto.constants import G2_X, G2_Y, P, R
from lighthouse_tpu.ops import curve, fieldb as fb, pairing
from lighthouse_tpu.ops import window_ladder as wl

NB = fb.NB

SCALAR_BITS = R.bit_length()  # 255: full-width r_i * z_i products


def _mont2(v2) -> np.ndarray:
    """Fp2 int pair -> (1, 2, NB) Montgomery bundle."""
    return np.stack(
        [fb._limbs((c << 384) % P, NB) for c in v2]
    )[None, :, :]


# G2 generator, affine Montgomery — the static right side of the first
# pair (the verification equation is always against G2, only the tau
# side depends on the trusted setup).
G2_GEN_AFFINE = (_mont2(G2_X), _mont2(G2_Y))


def _expand0(pt):
    return tuple(c[None] for c in pt)


def verify_kzg_proof_batch(
    pts_g1_aff, scalar_bits, lane_mask, aux_g1_aff, aux_mask, tau_g2_aff
):
    """Scalar bool: the folded batch identity over 3N+1 G1 lanes.

    pts_g1_aff: (x, y) bundles (3N, 1, NB) in the lane layout above.
    scalar_bits: (3N, SCALAR_BITS) int32, LSB-first per lane.
    lane_mask: (3N,) bool; False lanes enter the ladder as identity.
    aux_g1_aff: (x, y) bundles (1, 1, NB) — the -[sum r_i y_i]G1 point.
    aux_mask: (1,) bool (False when sum r_i y_i == 0 -> infinity).
    tau_g2_aff: (x, y) bundles (1, 2, NB) — [tau]G2 from the setup.
    """
    L = lane_mask.shape[0]
    n = L // 3
    pts = curve.PG1.from_affine(pts_g1_aff, lane_mask)
    with span("trace/kzg_rlc_ladder"):
        # the ONE shared window kernel (ops.window_ladder.ladder), not
        # an independent per-lane double-add chain
        pts_r = wl.ladder(curve.PG1, pts, scalar_bits)

    aux = curve.PG1.from_affine(aux_g1_aff, aux_mask)
    with span("trace/kzg_pair_fold"):
        lhs_lanes = tuple(
            jnp.concatenate([c[: 2 * n], a], axis=0)
            for c, a in zip(pts_r, aux)
        )
        lhs = curve.PG1.sum_axis(lhs_lanes, axis=0)
        w_sum = curve.PG1.sum_axis(
            tuple(c[2 * n :] for c in pts_r), axis=0
        )
    lhs_x, lhs_y, lhs_inf = curve.PG1.to_affine(_expand0(lhs))
    w_x, w_y, w_inf = curve.PG1.to_affine(
        _expand0(curve.PG1.neg(w_sum))
    )

    g2_gen = (
        jnp.asarray(G2_GEN_AFFINE[0]),
        jnp.asarray(G2_GEN_AFFINE[1]),
    )
    g1_side = (
        jnp.concatenate([lhs_x, w_x], axis=0),
        jnp.concatenate([lhs_y, w_y], axis=0),
    )
    g2_side = (
        jnp.concatenate([g2_gen[0], tau_g2_aff[0]], axis=0),
        jnp.concatenate([g2_gen[1], tau_g2_aff[1]], axis=0),
    )
    pair_mask = jnp.concatenate([~lhs_inf, ~w_inf], axis=0)
    return pairing.multi_pairing_is_one(g1_side, g2_side, pair_mask)
