"""Pallas TPU kernel: the full Miller loop fused in VMEM.

The XLA-level pipeline materializes every field-op intermediate to HBM
(each stacked multiply round-trips its conv tensor), which caps the
composed graph ~20x below VPU peak. This kernel keeps f, the running
point T, and every intermediate of all 63 Miller iterations resident in
VMEM: HBM traffic is exactly one read of the pair inputs and one write of
the Fp12 outputs per batch tile.

Layout: ops.tfield batch-last bundles (S, NB, B) — limbs on sublanes,
batch on lanes; the grid tiles the lane axis in blocks of `block_b`.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lighthouse_tpu.crypto.constants import BLS_X
from lighthouse_tpu.ops import tfield as tf
from lighthouse_tpu.ops import tpairing as tp

NB = tf.NB

_BITS = np.array(tp._X_BITS, dtype=np.int32)


def _kernel(
    bits_ref, px_ref, py_ref, qx_ref, qy_ref, consts_ref, redc_ref, f_ref
):
    from lighthouse_tpu.ops.pallas_ladder import _overrides

    px, py = px_ref[:], py_ref[:]
    qx, qy = qx_ref[:], qy_ref[:]
    overrides = {
        **_overrides(consts_ref[:]),
        **tf.redc_overrides(redc_ref[:]),
    }
    with tf.const_overrides(**overrides):
        B = qx.shape[-1]
        f0 = tp.fp12_one(B)
        t0 = (qx, qy, tp.fp2_one(B))

        def body(i, carry):
            f, t = carry
            bit = bits_ref[i]
            f, t = tp.miller_body(f, t, px, py, qx, qy, bit)
            return (f, t)

        f, _ = jax.lax.fori_loop(0, len(_BITS), body, (f0, t0))
        if BLS_X < 0:
            m = np.diag([1] * 6 + [-1] * 6).astype(np.int32)
            f = tf.apply_combo(f, m)
        f_ref[:] = f


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def miller_loop_pallas(
    p_g1_affine, q_g2_affine, valid_mask=None, block_b: int = 128,
    interpret: bool = False,
):
    """Batched Miller loop on TPU via one fused VMEM kernel.

    p_g1_affine: (px, py) (1, NB, B); q_g2_affine: (qx, qy) (2, NB, B);
    B must be a multiple of `block_b`. Returns f (12, NB, B).
    """
    px, py = p_g1_affine
    qx, qy = q_g2_affine
    B = qx.shape[-1]
    assert B % block_b == 0, (B, block_b)
    grid = (B // block_b,)

    def spec(s):
        return pl.BlockSpec(
            (s, NB, block_b),
            lambda i: (0, 0, i),
            memory_space=pltpu.VMEM,
        )

    from lighthouse_tpu.ops.pallas_ladder import _consts_array

    consts = _consts_array()
    bits = jnp.asarray(_BITS)

    f = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((12, NB, B), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # bits
            spec(1),
            spec(1),
            spec(2),
            spec(2),
            pl.BlockSpec(
                (4, NB, 1), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                tf.REDC_MATS_SHAPE, lambda i: (0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_specs=spec(12),
        interpret=interpret,
    )(bits, px, py, qx, qy, consts, tf.redc_mats_array())
    if valid_mask is not None:
        f = tf.select(valid_mask, f, tp.fp12_one(B))
    return f
