"""Transposed-layout (batch-last) Miller loop — the Pallas kernel body.

Same math as ops.pairing (inversion-free Jacobian twist Miller loop, fused
double/line and add/line steps, one scan over the 63 bits of |x|), re-laid
onto ops.tfield bundles `(S, NB, B)`: slots lead, limbs on sublanes, batch
on lanes. Runs in three modes:
  * pure jnp under jit (XLA; this module's public miller_loop_t);
  * as the body of the Pallas VMEM kernel (ops.pallas_miller);
  * numerically validated against ops.pairing in tests.

Values are bundles with NO leading batch axes — the batch IS the lane
axis. Stacked groups of n Fp2 values are `(n, 2, NB, B)`.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import BLS_X, BLS_X_ABS
from lighthouse_tpu.ops import tfield as tf
from lighthouse_tpu.ops.programs import (
    FP2_MUL,
    FP12_MUL,
    FP12_SQR,
    LINE_MUL,
)

NB = tf.NB

_X_BITS = np.array([int(b) for b in bin(BLS_X_ABS)[3:]], dtype=np.int32)


def bilinear(x, y, prog):
    return tf.apply_combo(
        tf.mul_lazy(tf.apply_combo(x, prog.A), tf.apply_combo(y, prog.B)),
        prog.C,
    )


def fp12_sqr(f):
    # dedicated complex-squaring program: 12 products vs the mul's 18
    # (knob + default shared with the batch-leading plane: tower.py)
    from lighthouse_tpu.ops.tower import use_fp12_sqr

    if use_fp12_sqr():
        return bilinear(f, f, FP12_SQR)
    return bilinear(f, f, FP12_MUL)


def fp12_mul(a, b):
    return bilinear(a, b, FP12_MUL)


def _mul_by_line(f, line):
    """f (12, NB, B) times the sparse line (6, NB, B)."""
    return bilinear(f, line, LINE_MUL)


def _mul2(pairs):
    """One stacked Fp2 multiply over a list of ((2,NB,B), (2,NB,B))."""
    A = jnp.stack([a for a, _ in pairs])
    B = jnp.stack([b for _, b in pairs])
    out = bilinear(A, B, FP2_MUL)
    return [out[i] for i in range(len(pairs))]


def _combo2(vals, coeffs):
    """One apply_combo over a list of Fp2 bundles; `coeffs` (n_out, n_in)
    acts Fp2-componentwise."""
    x = jnp.concatenate(vals, axis=-3)
    # lint: allow(device-purity): coeffs is a static integer matrix
    m = np.kron(np.asarray(coeffs, dtype=np.int64), np.eye(2, dtype=np.int64))
    y = tf.apply_combo(x, m.astype(np.int32))
    return [y[..., 2 * i : 2 * i + 2, :, :] for i in range(coeffs.shape[0])]


def _line_scale(ca, cb, px, py):
    """(ca*px, cb*py) as one 4-slot raw multiply (Fp acting componentwise
    on Fp2)."""
    lhs = jnp.concatenate([ca, cb], axis=-3)
    rhs = jnp.concatenate(
        [
            jnp.broadcast_to(px, ca.shape),
            jnp.broadcast_to(py, cb.shape),
        ],
        axis=-3,
    )
    out = tf.mul_lazy(lhs, rhs)
    return out[..., 0:2, :, :], out[..., 2:4, :, :]


def _dbl_step(t, px, py):
    """Fused tangent-line + doubling (ops.pairing._dbl_step transposed)."""
    X, Y, Z = t
    a, b, z2, yz = _mul2([(X, X), (Y, Y), (Z, Z), (Y, Z)])
    xb, e = _combo2(
        [X, a, b],
        np.array([[1, 0, 1], [0, 3, 0]]),
    )
    c, xb2, f, x3c, x2z2, yz3 = _mul2(
        [(b, b), (xb, xb), (e, e), (X, a), (a, z2), (yz, z2)]
    )
    x3, dmx, c0, m3xz, c3p, z3 = _combo2(
        [xb2, a, c, f, x3c, b, x2z2, yz3, yz],
        np.array(
            [
                [-4, 4, 4, 1, 0, 0, 0, 0, 0],
                [6, -6, -6, -1, 0, 0, 0, 0, 0],
                [0, 0, 0, 0, 3, -2, 0, 0, 0],
                [0, 0, 0, 0, 0, 0, -3, 0, 0],
                [0, 0, 0, 0, 0, 0, 0, 2, 0],
                [0, 0, 0, 0, 0, 0, 0, 0, 2],
            ]
        ),
    )
    (edmx,) = _mul2([(e, dmx)])
    c2, c3 = _line_scale(m3xz, c3p, px, py)
    (y3,) = _combo2([edmx, c], np.array([[1, -8]]))
    line = jnp.concatenate([c0, c2, c3], axis=-3)
    return (x3, y3, z3), line


def _add_step(t, q_affine, px, py):
    """Fused chord-line + mixed addition (ops.pairing._add_step)."""
    X1, Y1, Z1 = t
    qx, qy = q_affine
    (z1s,) = _mul2([(Z1, Z1)])
    u2, z1c = _mul2([(qx, z1s), (z1s, Z1)])
    (gamma,) = _combo2([u2, X1], np.array([[1, -1]]))
    qyz, hh, z1gam = _mul2([(qy, z1c), (gamma, gamma), (Z1, gamma)])
    (theta,) = _combo2([qyz, Y1], np.array([[1, -1]]))
    tt, hhh, v, tqx, qyz3 = _mul2(
        [(theta, theta), (gamma, hh), (X1, hh), (theta, qx), (qy, z1gam)]
    )
    x3, vmx, c0, mtheta = _combo2(
        [tt, hhh, v, tqx, qyz3, theta],
        np.array(
            [
                [1, -1, -2, 0, 0, 0],
                [-1, 1, 3, 0, 0, 0],
                [0, 0, 0, 1, -1, 0],
                [0, 0, 0, 0, 0, -1],
            ]
        ),
    )
    tvmx, y1hhh = _mul2([(theta, vmx), (Y1, hhh)])
    c2, c3 = _line_scale(mtheta, z1gam, px, py)
    (y3,) = _combo2([tvmx, y1hhh], np.array([[1, -1]]))
    line = jnp.concatenate([c0, c2, c3], axis=-3)
    return (x3, y3, z1gam), line


def _one_slot0(slots: int, batch: int):
    """Montgomery 1 in slot 0, zero elsewhere — built from tf.one_col()
    so a Pallas kernel can substitute a ref-read constant. The slots == 1
    case must NOT build a (0, NB, 1) pad: Mosaic rejects zero-sized
    vectors when lowering on hardware (interpret mode tolerates them)."""
    col = tf.one_col()[None, :, :]  # (1, NB, 1)
    if slots > 1:
        pad = jnp.zeros((slots - 1, NB, 1), dtype=jnp.int32)
        col = jnp.concatenate([col, pad], axis=0)
    return jnp.broadcast_to(col, (slots, NB, batch))


def fp12_one(batch: int):
    return _one_slot0(12, batch)


def fp2_one(batch: int):
    return _one_slot0(2, batch)


def miller_body(f, t, px, py, qx, qy, bit):
    """One Miller iteration (shared between the XLA scan and the Pallas
    in-kernel fori_loop). `bit` is a traced scalar."""
    f = fp12_sqr(f)
    t, line = _dbl_step(t, px, py)
    f = _mul_by_line(f, line)

    def do_add(op):
        f_, t_ = op
        t_next, line_add = _add_step(t_, (qx, qy), px, py)
        return _mul_by_line(f_, line_add), t_next

    f, t = jax.lax.cond(bit == 1, do_add, lambda op: op, (f, t))
    return f, t


def miller_loop_t(p_g1_affine, q_g2_affine, valid_mask=None):
    """Batched Miller loop in transposed layout.

    p_g1_affine: (px, py) Fp bundles (1, NB, B), Montgomery.
    q_g2_affine: (qx, qy) Fp2 bundles (2, NB, B).
    valid_mask: optional (B,) bool; False pairs contribute f = 1.
    Returns f (12, NB, B).
    """
    px, py = p_g1_affine
    qx, qy = q_g2_affine
    B = qx.shape[-1]
    t0 = (qx, qy, fp2_one(B))
    f0 = fp12_one(B)
    bits = jnp.asarray(_X_BITS)

    def step(carry, bit):
        f, t = carry
        f, t = miller_body(f, t, px, py, qx, qy, bit)
        return (f, t), None

    (f, _), _ = jax.lax.scan(step, (f0, t0), bits)
    if BLS_X < 0:
        # conj: negate the w-part (slots 6..12)
        m = np.diag([1] * 6 + [-1] * 6).astype(np.int32)
        f = tf.apply_combo(f, m)
    if valid_mask is not None:
        f = tf.select(valid_mask, f, fp12_one(B))
    return f
