"""Pallas TPU kernel: the final exponentiation fused in VMEM.

After the Miller loop and the (batched, XLA-friendly) product fold, the
batch-verify verdict is ~300 sequential Fp12 ops plus one Fp inversion on
a batch of ONE value — the tail of the reference's one multi-pairing per
batch (crypto/bls/src/impls/blst.rs:114-119). On the XLA path each of
those small ops is its own HBM round-trip; this kernel keeps every chain
intermediate in VMEM, with exponent bits in SMEM and the field/Frobenius
constants passed as inputs (kernels cannot capture array constants —
tfield.const_overrides convention).

The product FOLD deliberately stays at the XLA level: its lane-halving
tree slices the lane axis at sub-tile offsets, which Mosaic rejects
("result/input offset mismatch on non-concat dimension" — measured on
v5e 2026-07-31); XLA handles those slices fine and the fold is batched
work it already does well.
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lighthouse_tpu.ops import tfexp
from lighthouse_tpu.ops import tfield as tf

NB = tf.NB


from lighthouse_tpu.ops.pallas_ladder import _consts_array, _overrides


def use_fused_tail() -> bool:
    """LIGHTHOUSE_TPU_TAIL=1 runs the product fold + final
    exponentiation inside this fused VMEM kernel on the Pallas verify
    path (BENCH_IMPL=ptail); ""/unset keeps them at the XLA level
    (measured equal on v5e — PERF_NOTES: ptail ~= pallas, the final
    exp is not the bottleneck — so the simpler XLA tail stays the
    default and the kernel is one knob away). Read at trace time —
    part of the backend jit cache key (_impl_key), so the tail choice
    rides the same unified dispatch as the ladder/REDC/squaring
    knobs."""
    import os

    # lint: allow(device-purity): trace-time knob, keyed via _impl_key
    v = os.environ.get("LIGHTHOUSE_TPU_TAIL", "")
    if v in ("", "0"):
        return False
    if v == "1":
        return True
    raise ValueError(f"LIGHTHOUSE_TPU_TAIL={v!r}: use 1, 0, or unset")


def _kernel(
    pbits_ref, xbits_ref, f_ref, consts_ref, frob_ref, redc_ref, out_ref
):
    overrides = {
        **_overrides(consts_ref[:]),
        **tf.redc_overrides(redc_ref[:]),
    }
    with tf.const_overrides(**overrides):
        frob = frob_ref[:]
        res = tfexp.final_exponentiation_t(
            f_ref[:],
            frob[:12],
            frob[12:],
            get_pbit=lambda j: pbits_ref[j],
            get_xbit=lambda j: xbits_ref[j],
        )
        out_ref[:] = res


@functools.partial(jax.jit, static_argnames=("interpret",))
def final_exp_pallas(f1_t, interpret: bool = False):
    """(12, NB, 1) folded Miller product -> (12, NB, 1) final-exp'd
    value, the whole addition chain in one VMEM-resident kernel."""
    assert f1_t.shape == (12, NB, 1), f1_t.shape

    pbits = jnp.asarray(tfexp.P_MINUS_2_BITS)
    xbits = jnp.asarray(tfexp.X_ABS_BITS)

    return pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((12, NB, 1), jnp.int32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # p-2 bits
            pl.BlockSpec(memory_space=pltpu.SMEM),  # |x| bits
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(
        pbits,
        xbits,
        f1_t,
        _consts_array(),
        jnp.asarray(tfexp.frob_consts())[:, :, None],
        tf.redc_mats_array(),
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def fold_final_exp_pallas(f_t, interpret: bool = False):
    """(12, NB, B) per-pair Miller outputs -> (12, NB, 1) final-exp'd
    product. XLA lane-tree fold + the final-exp kernel; any B (odd
    fold levels carry a tail)."""
    return final_exp_pallas(tfexp.fold_lanes(f_t), interpret=interpret)
