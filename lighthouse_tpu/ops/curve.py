"""Batched, branchless Jacobian point arithmetic for BLS12-381 G1 and G2,
on slot bundles.

A point is a 3-tuple `(X, Y, Z)` of coordinate bundles — `(..., 1, NB)`
for G1 (Fp) or `(..., 2, NB)` for G2 (Fp2) — Montgomery domain, lazily
reduced. Infinity is Z == 0 (value-exact test via canonicalizing
predicates). All ops broadcast over leading batch axes; no Python branches
on traced values.

The group formulas are the same unified Jacobian ones validated against
crypto/ref_curve in the scalar implementation; here each formula step runs
its independent field multiplies as ONE stacked bundle multiply.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import (
    B_G1,
    B_G2,
    G1_X,
    G1_Y,
    G2_X,
    G2_Y,
    P,
)
from lighthouse_tpu.crypto.constants import R as R_SUBGROUP
from lighthouse_tpu.ops import fieldb as fb
from lighthouse_tpu.ops import fp2 as fp2m
from lighthouse_tpu.ops.programs import FP2_MUL

NB = fb.NB


def _mont1(v: int) -> np.ndarray:
    return fb._limbs((v << 384) % P, NB)[None, :]  # (1, NB)


class FieldW:
    """Width-w field namespace over bundles: w=1 (Fp) or w=2 (Fp2)."""

    def __init__(self, w: int):
        self.w = w
        if w == 1:
            self.ONE = np.asarray(fb.ONE_MONT_B)[None, :]
        else:
            self.ONE = np.asarray(fp2m.ONE_MONT)
        self.ZERO = np.zeros((w, NB), dtype=np.int32)

    def mul(self, a, b):
        if self.w == 1:
            return fb.mul_lazy(a, b)
        return fp2m.bilinear(a, b, FP2_MUL)

    def sqr(self, a):
        return self.mul(a, a)

    add = staticmethod(fb.add)
    sub = staticmethod(fb.sub)

    def neg(self, a):
        return fb.apply_combo(a, -np.eye(self.w, dtype=np.int32))

    scalar_small = staticmethod(fb.scalar_small)
    select = staticmethod(fb.select)
    is_zero = staticmethod(fb.is_zero)
    eq = staticmethod(fb.eq)

    def inv(self, a):
        if self.w == 1:
            return fb.inv(a)
        return fp2m.inv(a)

    def inv_batched(self, a):
        """Simultaneous inversion over the leading batch axis (n, w, NB):
        Montgomery's trick as a product tree — log2(n) levels of batched
        muls up, ONE Fermat chain at the root, log2(n) levels down —
        ~3n muls total instead of the ~2*381*n of running the Fermat
        ladder on every lane. inv(0) == 0 is preserved by substituting 1
        for zero inputs and masking the outputs (a single zero must not
        poison the whole tree)."""
        n0 = a.shape[0]
        zero = self.is_zero(a)
        one = jnp.broadcast_to(jnp.asarray(self.ONE), a.shape)
        a = self.select(~zero, a, one)
        n = 1 << max(0, n0 - 1).bit_length()
        if n != n0:
            pad = jnp.broadcast_to(
                jnp.asarray(self.ONE), (n - n0,) + a.shape[1:]
            )
            a = jnp.concatenate([a, pad], axis=0)
        levels = [a]
        cur = a
        while cur.shape[0] > 1:
            cur = self.mul(cur[0::2], cur[1::2])
            levels.append(cur)
        inv = self.inv(cur)  # (1, w, NB) root
        for lvl in reversed(levels[:-1]):
            # one stacked multiply per level: (m, 2, w, NB) where slot 0
            # is inv*right (the left child's inverse) and slot 1 inv*left
            sib = jnp.stack([lvl[1::2], lvl[0::2]], axis=1)
            both = self.mul(
                jnp.broadcast_to(inv[:, None], sib.shape), sib
            )
            inv = both.reshape(lvl.shape)
        inv = inv[:n0]
        return self.select(~zero, inv, jnp.zeros_like(inv))


F1 = FieldW(1)
F2 = FieldW(2)


def _tree_fold_sum(group, pts, axis: int):
    """Log-depth tree fold of points along a batch axis; odd tails are
    carried to the next level. Shared by both group planes."""
    n = pts[0].shape[axis]
    while n > 1:
        half = n // 2
        a = tuple(
            jax.lax.slice_in_dim(c, 0, half, axis=axis) for c in pts
        )
        b = tuple(
            jax.lax.slice_in_dim(c, half, 2 * half, axis=axis)
            for c in pts
        )
        s = group.add(a, b)
        if n % 2:
            tail = tuple(
                jax.lax.slice_in_dim(c, n - 1, n, axis=axis) for c in pts
            )
            s = tuple(
                jnp.concatenate([x, t], axis=axis)
                for x, t in zip(s, tail)
            )
        pts = s
        n = half + (n % 2)
    return tuple(jnp.squeeze(c, axis=axis) for c in pts)


class JacobianGroup:
    def __init__(self, F: FieldW, b_mont, gen_affine_mont, name):
        self.F = F
        self.b = b_mont
        self.name = name
        self.gen = (gen_affine_mont[0], gen_affine_mont[1], F.ONE)

    # -- representation helpers ------------------------------------------

    def infinity_like(self, pt):
        x = pt[0]
        one = jnp.broadcast_to(jnp.asarray(self.F.ONE), x.shape)
        return (one, one, jnp.zeros_like(x))

    def generator_like(self, batch_shape):
        def bc(c):
            c = jnp.asarray(c)
            return jnp.broadcast_to(c, tuple(batch_shape) + c.shape)

        return tuple(bc(c) for c in self.gen)

    def is_infinity(self, pt):
        return self.F.is_zero(pt[2])

    # -- group ops -------------------------------------------------------

    def neg(self, pt):
        return (pt[0], self.F.neg(pt[1]), pt[2])

    def double(self, pt):
        """dbl-2001-b: total — Z=0 or Y=0 inputs yield Z3=0. Independent
        multiplies stacked per layer."""
        F = self.F
        x, y, z = pt
        # layer 1: a = x^2, b = y^2, yz = y*z
        l1 = F.mul(
            jnp.stack([x, y, y], axis=-3),
            jnp.stack([x, y, z], axis=-3),
        )
        a, b, yz = l1[..., 0, :, :], l1[..., 1, :, :], l1[..., 2, :, :]
        # layer 2: c = b^2, xb2 = (x+b)^2, f = (3a)^2
        e = F.scalar_small(a, 3)
        xb = F.add(x, b)
        l2 = F.mul(
            jnp.stack([b, xb, e], axis=-3),
            jnp.stack([b, xb, e], axis=-3),
        )
        c, xb2, f = l2[..., 0, :, :], l2[..., 1, :, :], l2[..., 2, :, :]
        d = F.scalar_small(F.sub(F.sub(xb2, a), c), 2)
        x3 = F.sub(f, F.scalar_small(d, 2))
        # layer 3: y3 = e*(d - x3) - 8c
        y3 = F.sub(
            F.mul(e, F.sub(d, x3)), F.scalar_small(c, 8)
        )
        z3 = F.scalar_small(yz, 2)
        return (x3, y3, z3)

    def add(self, p, q):
        """Unified add handling p==q, p==-q, and infinities via selects."""
        F = self.F
        x1, y1, z1 = p
        x2, y2, z2 = q
        inf_p = self.is_infinity(p)
        inf_q = self.is_infinity(q)

        # layer 1: z1^2, z2^2
        l1 = F.mul(
            jnp.stack([z1, z2], axis=-3), jnp.stack([z1, z2], axis=-3)
        )
        z1s, z2s = l1[..., 0, :, :], l1[..., 1, :, :]
        # layer 2: u1 = x1 z2s, u2 = x2 z1s, z2c' = z2s*z2, z1c' = z1s*z1
        l2 = F.mul(
            jnp.stack([x1, x2, z2s, z1s], axis=-3),
            jnp.stack([z2s, z1s, z2, z1], axis=-3),
        )
        u1, u2 = l2[..., 0, :, :], l2[..., 1, :, :]
        z2c, z1c = l2[..., 2, :, :], l2[..., 3, :, :]
        # layer 3: s1 = y1 z2c, s2 = y2 z1c
        l3 = F.mul(
            jnp.stack([y1, y2], axis=-3), jnp.stack([z2c, z1c], axis=-3)
        )
        s1, s2 = l3[..., 0, :, :], l3[..., 1, :, :]

        h = F.sub(u2, u1)
        r = F.sub(s2, s1)
        same_x = F.is_zero(h)
        same_y = F.is_zero(r)

        h2 = F.scalar_small(h, 2)
        rr = F.scalar_small(r, 2)
        zz = F.mul(z1, z2)
        # layer 4: i = (2h)^2, rr2 = rr^2, z3' = zz*h
        l4 = F.mul(
            jnp.stack([h2, rr, zz], axis=-3),
            jnp.stack([h2, rr, h], axis=-3),
        )
        i = l4[..., 0, :, :]
        rr2 = l4[..., 1, :, :]
        z3 = F.scalar_small(l4[..., 2, :, :], 2)
        # layer 5: j = h*i, v = u1*i
        l5 = F.mul(
            jnp.stack([h, u1], axis=-3), jnp.stack([i, i], axis=-3)
        )
        j, v = l5[..., 0, :, :], l5[..., 1, :, :]
        x3 = F.sub(F.sub(rr2, j), F.scalar_small(v, 2))
        # layer 6: rr*(v - x3), s1*j
        l6 = F.mul(
            jnp.stack([rr, s1], axis=-3),
            jnp.stack([F.sub(v, x3), j], axis=-3),
        )
        y3 = F.sub(l6[..., 0, :, :], F.scalar_small(l6[..., 1, :, :], 2))
        generic = (x3, y3, z3)

        dbl = self.double(p)
        use_dbl = (~inf_p) & (~inf_q) & same_x & same_y
        out = self.select(use_dbl, dbl, generic)
        out = self.select(inf_q, p, out)
        out = self.select(inf_p, q, out)
        return out

    def add_nonexceptional(self, p, q):
        """Lean Jacobian-Jacobian add: assumes p != +-q and that garbage
        outputs are acceptable when either input is infinity or p == +-q
        (callers select those lanes away). Used by the scalar ladder, where
        acc = k*base and addend = 2^i*base with k < 2^i < r can never
        collide. ~3x fewer equations than the unified `add`."""
        F = self.F
        x1, y1, z1 = p
        x2, y2, z2 = q

        def unpack(stack, n):
            return [stack[..., i, :, :] for i in range(n)]

        z1z1, z2z2, z1z2 = unpack(
            F.mul(
                jnp.stack([z1, z2, z1], axis=-3),
                jnp.stack([z1, z2, z2], axis=-3),
            ),
            3,
        )
        u1, u2, z2c, z1c = unpack(
            F.mul(
                jnp.stack([x1, x2, z2z2, z1z1], axis=-3),
                jnp.stack([z2z2, z1z1, z2, z1], axis=-3),
            ),
            4,
        )
        s1, s2 = unpack(
            F.mul(
                jnp.stack([y1, y2], axis=-3),
                jnp.stack([z2c, z1c], axis=-3),
            ),
            2,
        )
        h = F.sub(u2, u1)
        r = F.sub(s2, s1)
        hh, z3 = unpack(
            F.mul(
                jnp.stack([h, z1z2], axis=-3),
                jnp.stack([h, h], axis=-3),
            ),
            2,
        )
        hhh, v, rr = unpack(
            F.mul(
                jnp.stack([h, u1, r], axis=-3),
                jnp.stack([hh, hh, r], axis=-3),
            ),
            3,
        )
        x3 = F.sub(F.sub(rr, hhh), F.scalar_small(v, 2))
        t1, t2 = unpack(
            F.mul(
                jnp.stack([r, s1], axis=-3),
                jnp.stack([F.sub(v, x3), hhh], axis=-3),
            ),
            2,
        )
        y3 = F.sub(t1, t2)
        return (x3, y3, z3)

    def select(self, cond, a, b):
        F = self.F
        return tuple(F.select(cond, ca, cb) for ca, cb in zip(a, b))

    def eq(self, p, q):
        F = self.F
        inf_p, inf_q = self.is_infinity(p), self.is_infinity(q)
        l1 = F.mul(
            jnp.stack([p[2], q[2]], axis=-3),
            jnp.stack([p[2], q[2]], axis=-3),
        )
        z1s, z2s = l1[..., 0, :, :], l1[..., 1, :, :]
        l2 = F.mul(
            jnp.stack([p[0], q[0], z2s, z1s], axis=-3),
            jnp.stack([z2s, z1s, q[2], p[2]], axis=-3),
        )
        ex = F.eq(l2[..., 0, :, :], l2[..., 1, :, :])
        l3 = F.mul(
            jnp.stack([p[1], q[1]], axis=-3),
            jnp.stack([l2[..., 2, :, :], l2[..., 3, :, :]], axis=-3),
        )
        ey = F.eq(l3[..., 0, :, :], l3[..., 1, :, :])
        return (inf_p & inf_q) | ((~inf_p) & (~inf_q) & ex & ey)

    def to_affine(self, pt):
        """(x_affine, y_affine, is_infinity); infinity maps to (0, 0)."""
        F = self.F
        x, y, z = pt
        zinv = F.inv_batched(z) if z.ndim == 3 else F.inv(z)
        zinv2 = F.sqr(zinv)
        l = F.mul(
            jnp.stack([x, zinv2], axis=-3),
            jnp.stack([zinv2, zinv], axis=-3),
        )
        x_aff = l[..., 0, :, :]
        y_aff = F.mul(y, l[..., 1, :, :])
        return (x_aff, y_aff, self.is_infinity(pt))

    # -- scalar multiplication -------------------------------------------

    def mul_scalar_bits(self, pt, bits):
        """bits: (..., nbits) int32 LSB-first; one lax.scan ladder.

        Uses the lean `add_nonexceptional` (acc = k*base vs addend =
        2^i*base with k < 2^i can never be equal/opposite/infinite for a
        finite base); a started-flag handles the running-infinity lanes and
        an infinite base is restored by the final select."""
        # add_nonexceptional's no-collision argument needs 2^i < r for
        # every ladder step; 2^254 < r (r is 255 bits, ~1.81*2^254).
        assert bits.shape[-1] <= 254, (
            "mul_scalar_bits: scalars must be < 2^254 (< subgroup order); "
            "reduce mod r first"
        )
        bits_seq = jnp.moveaxis(bits, -1, 0)
        base_inf = self.is_infinity(pt)
        batch = pt[0].shape[:-2]

        def step(carry, bit):
            acc, addend, started = carry
            added = self.add_nonexceptional(acc, addend)
            use = jnp.broadcast_to(bit == 1, batch)
            acc = self.select(
                use, self.select(started, added, addend), acc
            )
            started = started | use
            addend = self.double(addend)
            return (acc, addend, started), None

        init = (
            self.infinity_like(pt),
            pt,
            jnp.zeros(batch, dtype=bool),
        )
        (acc, _, started), _ = jax.lax.scan(step, init, bits_seq)
        return self.select(started & ~base_inf, acc, self.infinity_like(pt))

    def mul_scalar_static(self, pt, k: int):
        if k < 0:
            return self.mul_scalar_static(self.neg(pt), -k)
        k %= R_SUBGROUP  # points have order r
        if k >= 1 << 254:
            # ladder precondition is k < 2^254; r is 255 bits, so fold the
            # top ~45% of residues to the negative side: k*P = -(r-k)*P
            # with r - k < r - 2^254 < 2^254.
            return self.mul_scalar_static(self.neg(pt), R_SUBGROUP - k)
        if k == 0:
            return self.infinity_like(pt)
        nbits = k.bit_length()
        batch = pt[0].shape[:-2]
        bits = jnp.broadcast_to(
            jnp.asarray(
                np.array([(k >> i) & 1 for i in range(nbits)], np.int32)
            ),
            batch + (nbits,),
        )
        return self.mul_scalar_bits(pt, bits)

    # -- reductions ------------------------------------------------------

    def sum_axis(self, pts, axis: int = 0):
        """Log-depth tree fold of points along a batch axis."""
        return _tree_fold_sum(self, pts, axis)

    def masked_sum_axis(self, pts, mask, axis: int = 0):
        inf = self.infinity_like(pts)
        masked = self.select(mask, pts, inf)
        return self.sum_axis(masked, axis=axis)


class ProjectiveGroup:
    """Branchless-complete homogeneous-projective point arithmetic for
    y^2 = x^3 + b (a = 0) — the Renes–Costello–Batina complete formulas
    (EUROCRYPT 2016, Algorithms 7 & 9).

    This is the TPU-native group plane for everything outside the Miller
    loop (MSM folds, RLC scalar ladders): ONE uniform formula covers
    doubling, identity inputs, and inverse inputs, so there are no
    exceptional-case selects, no started-flags, and the add/ladder graphs
    are ~5x smaller than the unified Jacobian path — which is what the
    XLA compile time of the whole verify program scales with.

    A point is (X, Y, Z) bundles with x = X/Z, y = Y/Z; the identity is
    (0 : 1 : 0). Completeness holds on the odd-order r-torsion — all
    callers feed subgroup-checked points or the identity. Each formula
    stage runs its independent field multiplies as ONE stacked multiply
    and all linear recombination as ONE combo.
    """

    def __init__(self, F: FieldW, b3_block, gen_affine_mont, name):
        self.F = F
        # component-space action of multiplication by 3b (integer matrix)
        self.b3_block = np.asarray(b3_block, dtype=np.int64)
        self.name = name
        self.gen = (gen_affine_mont[0], gen_affine_mont[1], F.ONE)
        w = F.w
        self._identity = np.stack(
            [
                np.zeros((w, NB), np.int32),
                np.asarray(F.ONE, np.int32),
                np.zeros((w, NB), np.int32),
            ]
        )

        def kron(m):
            return np.kron(
                np.asarray(m, np.int64), np.eye(w, dtype=np.int64)
            ).astype(np.int32)

        # add stage-1 operand rows over [X, Y, Z]:
        #   X; Y; Z; X+Y; Y+Z; X+Z
        self._ADD_OPS = kron(
            np.array(
                [
                    [1, 0, 0],
                    [0, 1, 0],
                    [0, 0, 1],
                    [1, 1, 0],
                    [0, 1, 1],
                    [1, 0, 1],
                ]
            )
        )
        # add stage-1 recombination over [m0..m5] =
        # [X1X2, Y1Y2, Z1Z2, (X1+Y1)(X2+Y2), (Y1+Z1)(Y2+Z2),
        #  (X1+Z1)(X2+Z2)]:
        #   t3  = m3 - m0 - m1          (X1Y2 + X2Y1)
        #   t4  = m4 - m1 - m2          (Y1Z2 + Y2Z1)
        #   t5  = m5 - m0 - m2          (X1Z2 + X2Z1)
        #   T0  = 3 m0
        #   Z3s = m1 + b3 m2
        #   t1m = m1 - b3 m2
        b3 = self.b3_block
        Iw = np.eye(w, dtype=np.int64)

        def rows(spec):
            m = np.zeros((len(spec) * w, 6 * w), np.int64)
            for r, row in enumerate(spec):
                for idx, coeff, use_b3 in row:
                    blk = coeff * (b3 if use_b3 else Iw)
                    m[r * w : (r + 1) * w, idx * w : (idx + 1) * w] += blk
            return m.astype(np.int32)

        self._ADD_C1 = rows(
            [
                [(3, 1, False), (0, -1, False), (1, -1, False)],
                [(4, 1, False), (1, -1, False), (2, -1, False)],
                [(5, 1, False), (0, -1, False), (2, -1, False)],
                [(0, 3, False)],
                [(1, 1, False), (2, 1, True)],
                [(1, 1, False), (2, -1, True)],
            ]
        )
        # Y3c = b3 * t5 (own combo: folding b3 into t5's row would exceed
        # the combo L1 budget)
        self._B3_ROW = rows([[(0, 1, True)]])[:, : w]
        # add final combo over [X3a, t2x, Y3a, t1z, t0t, Z3a]:
        #   X3 = t2x - X3a;  Y3 = t1z + Y3a;  Z3 = Z3a + t0t
        self._ADD_C3 = rows(
            [
                [(1, 1, False), (0, -1, False)],
                [(3, 1, False), (2, 1, False)],
                [(5, 1, False), (4, 1, False)],
            ]
        )
        # dbl stage-1 recombination over [m0..m3] = [YY, YZ, ZZ, XY]:
        #   Z8  = 8 m0;  t2v = b3 m2;  Y3s = m0 + b3 m2
        m4 = np.zeros((3 * w, 4 * w), np.int64)
        for r, row in enumerate(
            [
                [(0, 8, False)],
                [(2, 1, True)],
                [(0, 1, False), (2, 1, True)],
            ]
        ):
            for idx, coeff, use_b3 in row:
                blk = coeff * (b3 if use_b3 else Iw)
                m4[r * w : (r + 1) * w, idx * w : (idx + 1) * w] += blk
        self._DBL_C1 = m4.astype(np.int32)
        # t0f = m0 - 3 t2v  over [m0, t2v]
        self._DBL_C2 = kron(np.array([[1, -3]]))
        # dbl final over [X3m, Z3f, Y3f, X3h]:
        #   X3 = 2 X3h;  Y3 = X3m + Y3f;  Z3 = Z3f
        self._DBL_C3 = kron(
            np.array([[0, 0, 0, 2], [1, 0, 1, 0], [0, 1, 0, 0]])
        )

    # -- representation helpers ------------------------------------------

    def identity_like(self, pt):
        x = pt[0]
        ident = jnp.asarray(self._identity)
        return tuple(
            jnp.broadcast_to(ident[i], x.shape) for i in range(3)
        )

    def generator_like(self, batch_shape):
        def bc(c):
            c = jnp.asarray(c)
            return jnp.broadcast_to(c, tuple(batch_shape) + c.shape)

        return tuple(bc(c) for c in self.gen)

    def is_infinity(self, pt):
        return self.F.is_zero(pt[2])

    def from_affine(self, aff, valid):
        """(x, y) affine bundles + validity mask -> projective points;
        invalid lanes become the identity (0 : 1 : 0)."""
        x, y = aff
        F = self.F
        one = jnp.broadcast_to(jnp.asarray(F.ONE), x.shape)
        zero = jnp.zeros_like(x)
        v = valid
        return (
            F.select(v, x, zero),
            F.select(v, y, one),
            F.select(v, one, zero),
        )

    def neg(self, pt):
        return (pt[0], self.F.neg(pt[1]), pt[2])

    def select(self, cond, a, b):
        F = self.F
        return tuple(F.select(cond, ca, cb) for ca, cb in zip(a, b))

    def _combo(self, vals, matrix, n_out):
        w = self.F.w
        x = jnp.concatenate(vals, axis=-2)
        y = fb.apply_combo(x, matrix)
        return [y[..., w * i : w * (i + 1), :] for i in range(n_out)]

    def _stack_mul(self, avals, bvals):
        A = jnp.stack(avals, axis=-3)
        B = jnp.stack(bvals, axis=-3)
        out = self.F.mul(A, B)
        return [out[..., i, :, :] for i in range(len(avals))]

    # -- group ops -------------------------------------------------------

    def add(self, p, q):
        """RCB Algorithm 7 (a = 0): complete for all subgroup inputs —
        p == q, p == -q, and identities all flow through the same code."""
        w = self.F.w
        a_ops = self._combo(list(p), self._ADD_OPS, 6)
        b_ops = self._combo(list(q), self._ADD_OPS, 6)
        m = self._stack_mul(a_ops, b_ops)
        t3, t4, t5, T0, Z3s, t1m = self._combo(m, self._ADD_C1, 6)
        (y3c,) = self._combo([t5], self._B3_ROW, 1)
        prods = self._stack_mul(
            [t4, t3, y3c, t1m, T0, Z3s],
            [y3c, t1m, T0, Z3s, t3, t4],
        )
        x3, y3, z3 = self._combo(prods, self._ADD_C3, 3)
        return (x3, y3, z3)

    def double(self, pt):
        """RCB Algorithm 9 (a = 0): complete doubling (identity -> identity)."""
        X, Y, Z = pt
        m0, m1, m2, m3 = self._stack_mul([Y, Y, Z, X], [Y, Z, Z, Y])
        z8, t2v, y3s = self._combo([m0, m1, m2, m3], self._DBL_C1, 3)
        (t0f,) = self._combo([m0, t2v], self._DBL_C2, 1)
        prods = self._stack_mul([t2v, m1, t0f, t0f], [z8, z8, y3s, m3])
        x3, y3, z3 = self._combo(prods, self._DBL_C3, 3)
        return (x3, y3, z3)

    def to_affine(self, pt):
        """(x_affine, y_affine, is_infinity); the identity maps to (0, 0)."""
        F = self.F
        X, Y, Z = pt
        zinv = F.inv_batched(Z) if Z.ndim == 3 else F.inv(Z)
        prods = self._stack_mul([X, Y], [zinv, zinv])
        return (prods[0], prods[1], self.is_infinity(pt))

    def eq(self, p, q):
        """Cross-multiplied projective equality (identity == identity)."""
        F = self.F
        prods = self._stack_mul(
            [p[0], q[0], p[1], q[1]], [q[2], p[2], q[2], p[2]]
        )
        ex = F.eq(prods[0], prods[1])
        ey = F.eq(prods[2], prods[3])
        inf_p, inf_q = self.is_infinity(p), self.is_infinity(q)
        return (inf_p & inf_q) | ((~inf_p) & (~inf_q) & ex & ey)

    # -- scalar multiplication -------------------------------------------

    def mul_scalar_bits(self, pt, bits):
        """bits: (..., nbits) int32 LSB-first; one lax.scan double-add
        ladder. Complete formulas: no started-flag, no collision
        precondition — any scalar width up to the subgroup order works."""
        bits_seq = jnp.moveaxis(bits, -1, 0)
        batch = pt[0].shape[:-2]

        def step(carry, bit):
            acc, addend = carry
            added = self.add(acc, addend)
            use = jnp.broadcast_to(bit == 1, batch)
            acc = self.select(use, added, acc)
            addend = self.double(addend)
            return (acc, addend), None

        init = (self.identity_like(pt), pt)
        (acc, _), _ = jax.lax.scan(step, init, bits_seq)
        return acc

    def mul_scalar_static(self, pt, k: int):
        if k < 0:
            return self.mul_scalar_static(self.neg(pt), -k)
        k %= R_SUBGROUP
        if k == 0:
            return self.identity_like(pt)
        nbits = k.bit_length()
        batch = pt[0].shape[:-2]
        bits = jnp.broadcast_to(
            jnp.asarray(
                np.array([(k >> i) & 1 for i in range(nbits)], np.int32)
            ),
            batch + (nbits,),
        )
        return self.mul_scalar_bits(pt, bits)

    # -- reductions ------------------------------------------------------

    def sum_axis(self, pts, axis: int = 0):
        """Log-depth tree fold of points along a batch axis."""
        return _tree_fold_sum(self, pts, axis)

    def masked_sum_axis(self, pts, mask, axis: int = 0):
        ident = self.identity_like(pts)
        masked = self.select(mask, pts, ident)
        return self.sum_axis(masked, axis=axis)


# -- host conversion helpers ---------------------------------------------


def g1_pack(ref_pts):
    """Host: ref Jacobian G1 points -> device bundles (Montgomery)."""
    coords = []
    for idx in range(3):
        arr = np.stack(
            [fb.pack_ints([p[idx]]) for p in ref_pts]
        )  # (N, 1, NB)
        coords.append(fb.to_mont(jnp.asarray(arr)))
    return tuple(coords)


def g1_unpack(pt):
    xs, ys, zs = (np.asarray(fb.from_mont(c)) for c in pt)
    out = []
    for x, y, z in zip(
        xs.reshape(-1, NB), ys.reshape(-1, NB), zs.reshape(-1, NB)
    ):
        vals = fb.unpack_ints(np.stack([x, y, z]))
        out.append((vals[0], vals[1], vals[2]))
    return out


def g2_pack(ref_pts):
    coords = []
    for idx in range(3):
        arr = np.stack(
            [fb.pack_ints([p[idx][0], p[idx][1]]) for p in ref_pts]
        )  # (N, 2, NB)
        coords.append(fb.to_mont(jnp.asarray(arr)))
    return tuple(coords)


def g2_unpack(pt):
    comps = []
    for c in pt:
        arr = np.asarray(fb.from_mont(c)).reshape(-1, 2, NB)
        comps.append([tuple(fb.unpack_ints(row)) for row in arr])
    return list(zip(*comps))


def scalars_to_bits(scalars, nbits: int) -> np.ndarray:
    return np.array(
        [[(s >> i) & 1 for i in range(nbits)] for s in scalars],
        dtype=np.int32,
    )


# -- concrete groups -------------------------------------------------------

G1 = JacobianGroup(
    F1,
    _mont1(B_G1),
    (_mont1(G1_X), _mont1(G1_Y)),
    "G1",
)

G2 = JacobianGroup(
    F2,
    fp2m.const_mont(B_G2[0], B_G2[1]),
    (
        fp2m.const_mont(G2_X[0], G2_X[1]),
        fp2m.const_mont(G2_Y[0], G2_Y[1]),
    ),
    "G2",
)

# Complete-formula projective groups (the MSM/ladder plane). b3 = 3*b as a
# component-space matrix: G1 b = 4 -> 12; G2 b = 4 + 4u -> 12 + 12u, whose
# action on (a + b u) is (12a - 12b) + (12a + 12b) u.
PG1 = ProjectiveGroup(
    F1,
    [[12]],
    (_mont1(G1_X), _mont1(G1_Y)),
    "PG1",
)

PG2 = ProjectiveGroup(
    F2,
    [[12, -12], [12, 12]],
    (
        fp2m.const_mont(G2_X[0], G2_X[1]),
        fp2m.const_mont(G2_Y[0], G2_Y[1]),
    ),
    "PG2",
)
