"""Batched, branchless Jacobian point arithmetic for BLS12-381 G1 and G2.

A point is a 3-tuple `(X, Y, Z)` of field elements — Fp limb arrays for G1,
Fp2 tuples for G2 — in **Montgomery form**. Infinity is marked by Z == 0
(coordinates at infinity may be garbage; every op treats Z == 0 as the
definitive flag). All ops broadcast over leading batch axes and are valid
inside jit/vmap/scan: no Python branches on traced values anywhere.

The exceptional cases the reference handles with branches
(reference crypto/bls/src/impls/blst.rs delegating to blst's C point ops)
are handled here with lane-wise selects: unified `add` computes the generic
chord result, the doubling result, and the infinity cases, then selects.

Validated against `lighthouse_tpu.crypto.ref_curve`.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import (
    B_G1,
    B_G2,
    G1_X,
    G1_Y,
    G2_X,
    G2_Y,
    P,
    int_to_limbs,
)
from lighthouse_tpu.ops import fp, fp2


def _mont(v: int) -> np.ndarray:
    """Static python int -> Montgomery-form limb constant."""
    return np.array(int_to_limbs((v << 384) % P), dtype=np.int32)


class JacobianGroup:
    """Short-Weierstrass y^2 = x^3 + b in Jacobian coordinates over a device
    field module (`ops.fp` or `ops.fp2`)."""

    def __init__(self, F, b_mont, gen_affine_mont, name):
        self.F = F
        self.b = b_mont  # Montgomery-form static constant
        self.name = name
        self.gen = (gen_affine_mont[0], gen_affine_mont[1], F.ONE_MONT)

    # -- representation helpers ------------------------------------------------

    def const(self, elem):
        """Identity hook: static constants are numpy arrays/tuples that JAX
        treats as leaves; nothing to do."""
        return elem

    def infinity_like(self, pt):
        """Infinity with the same batch shape as `pt`."""
        F = self.F
        x, y, z = pt
        one = jax.tree_util.tree_map(
            lambda c, ref: jnp.broadcast_to(jnp.asarray(c), ref.shape),
            F.ONE_MONT,
            x,
        )
        zero = jax.tree_util.tree_map(jnp.zeros_like, x)
        return (one, one, zero)

    def generator_like(self, batch_shape):
        """Generator broadcast to the given leading batch shape."""
        def bc(c):
            c = jnp.asarray(c)
            return jnp.broadcast_to(c, tuple(batch_shape) + c.shape)

        return jax.tree_util.tree_map(bc, self.gen)

    def is_infinity(self, pt):
        return self.F.is_zero(pt[2])

    # -- group ops -------------------------------------------------------------

    def neg(self, pt):
        return (pt[0], self.F.neg(pt[1]), pt[2])

    def double(self, pt):
        """2001 Bernstein dbl: total — Z=0 or Y=0 inputs yield Z3=0."""
        F = self.F
        x, y, z = pt
        a = F.sqr(x)
        b = F.sqr(y)
        c = F.sqr(b)
        d = F.scalar_small(F.sub(F.sub(F.sqr(F.add(x, b)), a), c), 2)
        e = F.scalar_small(a, 3)
        f = F.sqr(e)
        x3 = F.sub(f, F.scalar_small(d, 2))
        y3 = F.sub(F.mul(e, F.sub(d, x3)), F.scalar_small(c, 8))
        z3 = F.scalar_small(F.mul(y, z), 2)
        return (x3, y3, z3)

    def add(self, p, q):
        """Unified add: handles p==q, p==-q, and either side at infinity via
        branchless selects."""
        F = self.F
        x1, y1, z1 = p
        x2, y2, z2 = q
        inf_p = self.is_infinity(p)
        inf_q = self.is_infinity(q)

        z1s = F.sqr(z1)
        z2s = F.sqr(z2)
        u1 = F.mul(x1, z2s)
        u2 = F.mul(x2, z1s)
        s1 = F.mul(y1, F.mul(z2s, z2))
        s2 = F.mul(y2, F.mul(z1s, z1))
        h = F.sub(u2, u1)
        r = F.sub(s2, s1)
        same_x = F.is_zero(h)
        same_y = F.is_zero(r)

        # generic chord
        i = F.sqr(F.scalar_small(h, 2))
        j = F.mul(h, i)
        rr = F.scalar_small(r, 2)
        v = F.mul(u1, i)
        x3 = F.sub(F.sub(F.sqr(rr), j), F.scalar_small(v, 2))
        y3 = F.sub(
            F.mul(rr, F.sub(v, x3)), F.scalar_small(F.mul(s1, j), 2)
        )
        z3 = F.scalar_small(F.mul(F.mul(z1, z2), h), 2)
        generic = (x3, y3, z3)

        dbl = self.double(p)
        # p == -q (same x, different y) -> generic already yields z3 == 0.
        use_dbl = (~inf_p) & (~inf_q) & same_x & same_y
        out = self.select(use_dbl, dbl, generic)
        out = self.select(inf_q, p, out)
        out = self.select(inf_p, q, out)
        return out

    def select(self, cond, a, b):
        F = self.F
        return tuple(F.select(cond, ca, cb) for ca, cb in zip(a, b))

    def eq(self, p, q):
        F = self.F
        inf_p, inf_q = self.is_infinity(p), self.is_infinity(q)
        z1s, z2s = F.sqr(p[2]), F.sqr(q[2])
        ex = F.eq(F.mul(p[0], z2s), F.mul(q[0], z1s))
        ey = F.eq(
            F.mul(p[1], F.mul(z2s, q[2])), F.mul(q[1], F.mul(z1s, p[2]))
        )
        return (inf_p & inf_q) | ((~inf_p) & (~inf_q) & ex & ey)

    def to_affine(self, pt):
        """Batched Jacobian -> affine: (x, y, is_infinity).

        Uses the field inv(0) == 0 convention, so infinity maps to the
        harmless sentinel (0, 0) with its mask bit set; downstream pairing
        code masks those lanes out.
        """
        F = self.F
        x, y, z = pt
        zinv = F.inv(z)
        zinv2 = F.sqr(zinv)
        return (
            F.mul(x, zinv2),
            F.mul(y, F.mul(zinv2, zinv)),
            self.is_infinity(pt),
        )

    # -- scalar multiplication -------------------------------------------------

    def mul_scalar_bits(self, pt, bits):
        """Variable-scalar multiplication.

        `bits` is an int32 array of shape (..., nbits), LSB-first, matching
        pt's batch shape. One lax.scan over the bit axis: double-and-add with
        a select per step.
        """
        F = self.F
        nbits = bits.shape[-1]
        bits_seq = jnp.moveaxis(bits, -1, 0)  # (nbits, ...)

        def step(carry, bit):
            acc, addend = carry
            added = self.add(acc, addend)
            acc = self.select(bit == 1, added, acc)
            addend = self.double(addend)
            return (acc, addend), None

        init = (self.infinity_like(pt), pt)
        (acc, _), _ = jax.lax.scan(step, init, bits_seq)
        return acc

    def mul_scalar_static(self, pt, k: int):
        """Static-scalar multiplication via the same one-step scan graph as
        `mul_scalar_bits` (a Python-unrolled ladder would inflate the HLO by
        the bit length and blow up compile time)."""
        if k < 0:
            return self.mul_scalar_static(self.neg(pt), -k)
        if k == 0:
            return self.infinity_like(pt)
        nbits = k.bit_length()
        batch = jax.tree_util.tree_leaves(pt)[0].shape[:-1]
        bits = jnp.broadcast_to(
            jnp.asarray(
                np.array([(k >> i) & 1 for i in range(nbits)], np.int32)
            ),
            batch + (nbits,),
        )
        return self.mul_scalar_bits(pt, bits)

    # -- reductions ------------------------------------------------------------

    def sum_axis(self, pts, axis: int = 0):
        """Tree-fold sum of points along `axis` (log-depth batched adds).

        Works on any length; odd levels carry the tail element through.
        """
        n = jax.tree_util.tree_leaves(pts)[0].shape[axis]
        while n > 1:
            half = n // 2
            a = jax.tree_util.tree_map(
                lambda x: jax.lax.slice_in_dim(x, 0, half, axis=axis), pts
            )
            b = jax.tree_util.tree_map(
                lambda x: jax.lax.slice_in_dim(x, half, 2 * half, axis=axis),
                pts,
            )
            s = self.add(a, b)
            if n % 2:
                tail = jax.tree_util.tree_map(
                    lambda x: jax.lax.slice_in_dim(x, n - 1, n, axis=axis),
                    pts,
                )
                s = jax.tree_util.tree_map(
                    lambda x, t: jnp.concatenate([x, t], axis=axis), s, tail
                )
            pts = s
            n = half + (n % 2)
        return jax.tree_util.tree_map(
            lambda x: jnp.squeeze(x, axis=axis), pts
        )

    def masked_sum_axis(self, pts, mask, axis: int = 0):
        """Sum with a boolean mask (False lanes contribute infinity)."""
        inf = self.infinity_like(pts)
        masked = self.select(mask, pts, inf)
        return self.sum_axis(masked, axis=axis)


# -- host conversion helpers ----------------------------------------------------


def g1_pack(ref_pts):
    """Host: list of ref Jacobian G1 points (int tuples) -> device batch in
    Montgomery form."""
    xs = fp.to_mont(fp.pack([p[0] for p in ref_pts]))
    ys = fp.to_mont(fp.pack([p[1] for p in ref_pts]))
    zs = fp.to_mont(fp.pack([p[2] for p in ref_pts]))
    return (xs, ys, zs)


def g1_unpack(pt):
    """Host: device G1 batch -> list of ref Jacobian int tuples."""
    xs, ys, zs = (np.asarray(fp.from_mont(c)) for c in pt)
    flat = lambda a: a.reshape(-1, a.shape[-1])
    return [
        (fp.to_int(x), fp.to_int(y), fp.to_int(z))
        for x, y, z in zip(flat(xs), flat(ys), flat(zs))
    ]


def g2_pack(ref_pts):
    """Host: list of ref Jacobian G2 points (Fp2 tuples) -> device batch."""
    comps = []
    for idx in range(3):
        comps.append(fp2.to_mont(fp2.pack([p[idx] for p in ref_pts])))
    return tuple(comps)


def g2_unpack(pt):
    out = []
    comps = [fp2.to_ints(fp2.from_mont(c)) for c in pt]
    for x, y, z in zip(*comps):
        out.append((x, y, z))
    return out


def scalars_to_bits(scalars, nbits: int) -> np.ndarray:
    """Host: list of ints -> (N, nbits) int32 LSB-first bit array."""
    return np.array(
        [[(s >> i) & 1 for i in range(nbits)] for s in scalars],
        dtype=np.int32,
    )


# -- concrete groups -------------------------------------------------------------

G1 = JacobianGroup(
    fp,
    _mont(B_G1),
    (_mont(G1_X), _mont(G1_Y)),
    "G1",
)

G2 = JacobianGroup(
    fp2,
    (_mont(B_G2[0]), _mont(B_G2[1])),
    (
        (_mont(G2_X[0]), _mont(G2_X[1])),
        (_mont(G2_Y[0]), _mont(G2_Y[1])),
    ),
    "G2",
)
