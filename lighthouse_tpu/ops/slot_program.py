"""Chained slot-program: one guarded dispatch for a whole import.

PR 17's dispatch-gap ledger put a number on the one-dispatch-slot item:
a blob import pays TWO serial host<->device round trips (the DA
checker's KZG settle, then the verification bus's signature fold) with
a multi-millisecond host gap between them — and on hardware every
extra serial dispatch costs ~90 ms fixed (PERF_NOTES scaling model).
This module is the fusion: a `SlotProgram` collects the import's
co-resident device work — tree-hash Merkle branch checks, the
signature RLC fold, and the KZG/blob settle — and runs ALL of it
inside ONE `GUARD.dispatch` crossing, so the import uploads its inputs
once, runs one scheduled device program, and downloads one verdict
bundle (the fully pipelined verification datapath of the FPGA
verification-engine design, arxiv 2112.02229).

Guard-rail contract (identical to the bus's shared signature verify,
`verification_bus/bus.py::_guarded_shared_verify`):

  * the program dispatches on the "bls" plane (the pairing plane every
    segment folds over), so it shares the breaker, canary, and fault-
    injection state with the plain signature path — a quarantined
    plane fails the CHAINED program over to the serial host tiers
    exactly like it fails a plain batch;
  * when the canary is active, the known-answer sentinel pair is
    checked FIRST inside the same guarded attempt and the valid
    sentinel rides the signature fold as an attribution-free extra
    set — a lying plane is caught before any segment verdict escapes;
  * every verdict the program produces routes through the attempt's
    `InjectionPlan.verdict`, so a flip injection flips the settle and
    Merkle verdicts too — which is exactly how the canary catches it;
  * failover order mirrors the serial path: tpu -> xla-host (same
    graphs pinned to the host device) -> ref; host backends get the
    ref tier with `fault_types=(DeviceFaultError,)` so data-dependent
    exceptions keep their caller-visible semantics.

Byte-identity: each settle work keeps its OWN folded batch (per-
submission verdict isolation — one import's invalid blob can never
fail a coterminous import's settle), delivered via `work.deliver`, and
a False/"error" settle verdict makes the DA checker fall back to the
same per-sidecar host recovery the serial path uses. The signature
fold is the unchanged `bls.verify_signature_sets_shared` boundary.

`run_slot_program_segments` is the RAW chained executor: it must only
ever run inside `SlotProgram.run`'s guarded attempt (or its failover
tiers) — the guarded-dispatch lint pass pins it to this module the
same way it pins `verify_signature_sets_tpu`.
"""

from lighthouse_tpu.common import slot_budget


def _settle_tier_backend(work_backend: str, tier: str) -> str:
    """Map a settle work's own backend onto a failover tier: the device
    attempt and the xla-host tier keep the work's backend (xla-host
    re-runs the same graphs pinned to the host device); the ref tier
    drops a device backend to the reference fold, while host stubs
    (fake) stay themselves — they ARE the host equivalent."""
    if tier == "ref" and work_backend == "tpu":
        return "ref"
    return work_backend


def run_slot_program_segments(
    program, sig_backend, tier, plan, extra_sets, seed
):
    """Execute every segment of `program` as one chained device
    program: KZG settle works first (each its own folded batch, verdict
    delivered per work), then Merkle branch checks, then the signature
    RLC fold spanning every submission. Returns `(ok, record)` where
    `ok` is the signature+Merkle verdict (settle verdicts fan back via
    `work.deliver`) and `record` is the signature batch economics.

    RAW entry point: callers reach it only through `SlotProgram.run`
    (guarded attempt + failover tiers) — see the lint pass."""
    from lighthouse_tpu import bls, kzg

    for work in program.settles:
        blobs, commitments, proofs, work_backend = work.payload()
        try:
            ok = kzg.verify_blob_kzg_proof_batch(
                blobs,
                commitments,
                proofs,
                backend=_settle_tier_backend(work_backend, tier),
                consumer="kzg",
            )
        except kzg.KzgError:
            # same recovery the serial settle uses: a malformed
            # candidate must not sink the rest — the checker falls
            # back to per-sidecar verdicts on finalize
            work.deliver("error")
        else:
            work.deliver(plan.verdict(bool(ok)))
    program.merkle_results = []
    merkle_ok = True
    if program.merkle_segments:
        from lighthouse_tpu.ops import merkle_proof

        for queries, roots, consumer in program.merkle_segments:
            verdicts = [
                plan.verdict(bool(v))
                for v in merkle_proof.batch_verify_branches(
                    queries, roots, consumer=consumer
                )
            ]
            program.merkle_results.append(verdicts)
            merkle_ok = merkle_ok and all(verdicts)
    if not program.signature_submissions:
        # settle/Merkle-only program (the sync path's deferred settle):
        # the group verdict is the non-signature segments' conjunction
        return plan.verdict(True) and merkle_ok, None
    ok, record = bls.verify_signature_sets_shared(
        program.signature_submissions,
        backend=sig_backend,
        seed=seed,
        extra_sets=extra_sets,
    )
    return plan.verdict(bool(ok)) and merkle_ok, record


class SlotProgram:
    """Builder for one import's chained device program. Compose with
    `add_settle` (a DA checker `PendingSettle` — or anything exposing
    `payload() -> (blobs, commitments, proofs, backend)` and
    `deliver(verdict)`), `add_signatures`, and `add_merkle`; then one
    `run()` is one guarded host<->device crossing for everything."""

    def __init__(self, seed=None):
        self.seed = seed
        self.settles: list = []
        self.signature_submissions: list = []  # (sets, consumer)
        self.merkle_segments: list = []  # (queries, roots, consumer)
        self.merkle_results: list = []

    def add_settle(self, work):
        self.settles.append(work)
        return self

    def add_signatures(self, sets, consumer: str):
        sets = list(sets)
        if sets:
            self.signature_submissions.append((sets, consumer))
        return self

    def add_merkle(self, queries, roots, consumer: str = "bench"):
        queries = list(queries)
        if queries:
            self.merkle_segments.append((queries, list(roots), consumer))
        return self

    @property
    def empty(self) -> bool:
        return not (
            self.settles
            or self.signature_submissions
            or self.merkle_segments
        )

    def total_live(self) -> int:
        return (
            sum(len(s) for s, _ in self.signature_submissions)
            + sum(len(w.payload()[0]) for w in self.settles)
            + sum(len(q) for q, _, _ in self.merkle_segments)
        )

    def run(
        self,
        backend: str | None = None,
        journal=None,
        slot=None,
        predicted_s=None,
    ):
        """One guarded dispatch for the whole program: watchdog +
        breaker + canary + deterministic injection around the chained
        segments, serial host failover on any device fault. Returns
        `(ok, record)` like the bus's shared verify; settle verdicts
        fan back through each work's `deliver`."""
        from lighthouse_tpu import bls
        from lighthouse_tpu.device_plane import (
            GUARD,
            DeviceFaultError,
            canary,
            host_device_scope,
            pow2_bucket,
        )
        from lighthouse_tpu.device_plane.executor import NULL_PLAN

        effective = backend or bls.default_backend()
        canary_on = GUARD.canary_active(effective)
        extra = (
            [canary.bls_sentinels()[0]]
            if canary_on and self.signature_submissions
            else None
        )

        def attempt(plan):
            if canary_on:
                canary.check_pair(effective, plan)
            return run_slot_program_segments(
                self, backend, "device", plan, extra, self.seed
            )

        def host_tier(tier_backend, tier, scoped=False):
            def run_tier():
                if scoped:
                    with host_device_scope():
                        return run_slot_program_segments(
                            self, tier_backend, tier, NULL_PLAN, None,
                            self.seed,
                        )
                return run_slot_program_segments(
                    self, tier_backend, tier, NULL_PLAN, None, self.seed
                )

            return run_tier

        if effective == "tpu":
            fallbacks = [
                ("xla-host", host_tier("tpu", "xla-host", scoped=True)),
                ("ref", host_tier("ref", "ref")),
            ]
            fault_types = None  # any escape from a device dispatch
        else:
            fallbacks = [("ref", host_tier("ref", "ref"))]
            fault_types = (DeviceFaultError,)
        # the fused dispatch interval belongs to the bus's caller-side
        # "fused" mark (or, driven directly, to this outermost open)
        tok = slot_budget.open_dispatch("slot_program", kind="fused")
        try:
            return GUARD.dispatch(
                "bls",
                pow2_bucket(max(1, self.total_live())),
                attempt,
                fallbacks=fallbacks,
                journal=journal,
                slot=slot,
                predicted_s=predicted_s,
                fault_types=fault_types,
            )
        finally:
            slot_budget.close_dispatch(tok)
