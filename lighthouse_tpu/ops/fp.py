"""Batched BLS12-381 base-field (Fp) arithmetic on 12-bit limbs in int32 lanes.

Parity note: replaces the role of the `blst` C field arithmetic behind the
reference client's BLS boundary (reference crypto/bls/src/impls/blst.rs); the
math here is validated against `lighthouse_tpu.crypto.ref_fields`.

Design (tpu-first):
- An Fp element is `(..., NLIMBS)` int32, little-endian base-2^12 limbs,
  canonical (every limb in [0, 2^12), value in [0, p)) at every op boundary.
  12-bit limbs keep every intermediate of a schoolbook 32x32-limb product
  below 2^30, so all accumulation fits native int32 lanes — no 64-bit
  emulation anywhere on the hot path.
- Multiplication is Montgomery (R = 2^384) in *full-word REDC* form: three
  32x32-limb convolutions (a*b; m = T*N' mod R; m*P) which XLA maps to dense
  batched contractions.
- Carry handling contains NO sequential loops (a `lax.scan` per carry chain
  made every multiply a compile-time and run-time serial bottleneck).
  Instead: a fixed number of vectorized partial-carry passes squeezes limbs
  into [0, 2^12], then a Kogge-Stone generate/propagate pass (log2(NLIMBS)
  steps of boolean ops) resolves the remaining 0/1 carries exactly — the
  classic carry-lookahead adder, laid out across vector lanes.
- Comparisons/subtractions use complement-add form (x - y computed as
  x + (2^384 - y) with the exact carry-out as the borrow bit), keeping all
  limbs unsigned.
- Elements on the device live in the Montgomery domain; conversion happens
  at the host boundary.

All public ops broadcast over leading batch axes.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lighthouse_tpu.crypto.constants import (
    LIMB_BITS,
    LIMB_MASK,
    MONT_R2_MOD_P,
    MONT_R_MOD_P,
    NLIMBS,
    P,
    int_to_limbs,
)

# ----------------------------------------------------------------- constants

PROD_LIMBS = 2 * NLIMBS - 1  # length of a full limb convolution

# N' = -P^{-1} mod R, as limbs (full-word Montgomery factor).
_NPRIME_INT = (-pow(P, -1, 1 << (LIMB_BITS * NLIMBS))) % (1 << (LIMB_BITS * NLIMBS))

P_LIMBS = np.array(int_to_limbs(P), dtype=np.int32)
NPRIME_LIMBS = np.array(int_to_limbs(_NPRIME_INT), dtype=np.int32)

# Anti-diagonal one-hot mask: MASK[i, j, k] = 1 iff i + j == k. Contracting
# the outer product of two limb vectors against it yields the polynomial
# (convolution) product — a dense einsum XLA can tile.
_CONV_MASK = np.zeros((NLIMBS, NLIMBS, PROD_LIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_MASK[_i, _j, _i + _j] = 1

# Low-half-only variant (k < NLIMBS): the mod-R product used by the REDC
# m-step — coefficients at k >= NLIMBS are multiples of R and carries flow
# strictly upward, so they never influence the low half.
_CONV_MASK_LOW = _CONV_MASK[:, :, :NLIMBS].copy()

ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE_MONT = np.array(int_to_limbs(MONT_R_MOD_P), dtype=np.int32)  # 1 in Mont form
R2 = np.array(int_to_limbs(MONT_R2_MOD_P), dtype=np.int32)


def _complement_limbs(v: int, nlimbs: int) -> np.ndarray:
    """Limbs of (2^(12*nlimbs) - v): adding them == subtracting v, with the
    exact top carry-out flagging v <= x."""
    comp = (1 << (LIMB_BITS * nlimbs)) - v
    return np.array(
        [(comp >> (LIMB_BITS * i)) & LIMB_MASK for i in range(nlimbs)],
        dtype=np.int32,
    )


_NEG_P = {n: _complement_limbs(P, n) for n in (NLIMBS, NLIMBS + 1)}


# ------------------------------------------------------------- host helpers


def from_int(v: int) -> np.ndarray:
    """Host: python int -> canonical limb vector (NOT Montgomery form)."""
    return np.array(int_to_limbs(v % P), dtype=np.int32)


def to_int(limbs) -> int:
    """Host: limb vector -> python int. No implicit mod-p: device ops
    guarantee canonical outputs, and tests must see a violation if that
    regresses."""
    from lighthouse_tpu.crypto.constants import limbs_to_int

    return limbs_to_int(np.asarray(limbs).reshape(-1))


def pack(values) -> np.ndarray:
    """Host: iterable of ints -> (N, NLIMBS) canonical limb array."""
    return np.stack([from_int(v) for v in values])


# ------------------------------------------------------------ carry handling


def _partial_pass(x):
    """One vectorized carry pass: limb -> [0, 2^12), carries move one limb
    up (top carry dropped — callers size arrays so it is always zero)."""
    c = x >> LIMB_BITS
    d = x & LIMB_MASK
    return d + jnp.pad(
        c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    )


def _ks_resolve(x):
    """Kogge-Stone carry resolution: (canonical limbs, top carry-out).

    Precondition: limbs in [0, 2*2^12 - 2] with at most one unit of carry
    flowing between adjacent limbs (i.e. (x_i + 1) >> 12 <= 1) — the state
    after partial-carry passes. log2(L) boolean steps.
    """
    g = x > LIMB_MASK  # this limb generates a carry
    p = x == LIMB_MASK  # this limb propagates an incoming carry
    # prefix combine: carry_out[i] = g[i] | (p[i] & carry_out[i-1])
    shift = 1
    L = x.shape[-1]
    gg, pp = g, p
    while shift < L:
        pad = [(0, 0)] * (x.ndim - 1) + [(shift, 0)]
        g_prev = jnp.pad(gg[..., :-shift], pad)
        p_prev = jnp.pad(pp[..., :-shift], pad)
        gg = gg | (pp & g_prev)
        pp = pp & p_prev
        shift *= 2
    carry_in = jnp.pad(
        gg[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    ).astype(jnp.int32)
    return (x + carry_in) & LIMB_MASK, gg[..., -1]


def _resolve_carries(x):
    """Exact canonicalization (see _ks_resolve); top carry must be zero by
    the caller's value bound."""
    out, _ = _ks_resolve(x)
    return out


def _normalize(x, out_len):
    """Propagate carries so every limb lands in [0, 2^12).

    `x` must hold non-negative int32 limbs with value < 2^(12*out_len).
    Returns an (..., out_len) canonical array.
    """
    in_len = x.shape[-1]
    if in_len < out_len:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, out_len - in_len)]
        x = jnp.pad(x, pad)
    elif in_len > out_len:
        raise ValueError("normalize: would truncate")
    # limbs < 2^30 -> pass1 brings carries <= 2^18, pass2 <= 2^6, pass3
    # leaves limbs in [0, 2^12]; Kogge-Stone finishes exactly.
    x = _partial_pass(x)
    x = _partial_pass(x)
    x = _partial_pass(x)
    return _resolve_carries(x)


def _add_complement(x, comp_const):
    """x + comp(v): returns (sum_mod_2^(12L) canonical, no_borrow) where
    no_borrow == True iff x >= v. x must be canonical."""
    s = x + jnp.asarray(comp_const)
    # limbs <= 2*4095: one partial pass (capturing the top carry), then
    # exact resolve; total carry out of the top limb == 1 iff x >= v.
    c = s >> LIMB_BITS
    d = s & LIMB_MASK
    top_carry1 = c[..., -1]
    s = d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    out, top_carry2 = _ks_resolve(s)
    no_borrow = (top_carry1 + top_carry2.astype(jnp.int32)) > 0
    return out, no_borrow


def _cond_sub_p(x):
    """Map canonical x in [0, 2p) to x mod p (branchless)."""
    sub, ge = _add_complement(x, _NEG_P[x.shape[-1]])
    return jnp.where(ge[..., None], sub, x)


def _conv(a, b_or_const):
    """Full polynomial product of limb vectors: (..., N) x (..., N) -> (..., 2N-1).

    Products of 12-bit limbs are <= 2^24 and at most 32 stack per output
    coefficient, so int32 accumulation is exact.
    """
    outer = a[..., :, None] * b_or_const[..., None, :]
    return jnp.einsum("...ij,ijk->...k", outer, jnp.asarray(_CONV_MASK))


# ----------------------------------------------------------------- field ops


def add(a, b):
    """(a + b) mod p for canonical inputs."""
    s = _partial_pass(a + b)  # limbs <= 2*4095 -> one pass + resolve
    return _cond_sub_p(_resolve_carries(s))


# Borrow-proof subtraction constant: 2p plus a value-zero "spread"
# (+4096 at limb 0, +4095 at limbs 1..30, -1 at limb 31; the spread
# telescopes to zero value). Every limb of (a + _2P_SPREAD - b) is then
# non-negative for canonical a, b: limbs 0..30 get >= 4095 headroom, and at
# limb 31, 2p's top limb (832) minus the spread's 1 still dominates b's top
# limb (<= 416 since b < p).
_2P_SPREAD = np.array(int_to_limbs(2 * P), dtype=np.int32)
for _i in range(NLIMBS - 1):
    _2P_SPREAD[_i] += 1 << LIMB_BITS
    _2P_SPREAD[_i + 1] -= 1


def sub(a, b):
    """(a - b) mod p for canonical inputs: a - b + 2p (limbwise
    non-negative via the spread constant), then two conditional
    subtractions bring [0, 3p) into [0, p).

    Bound note: pre-pass limbs reach 4095 + 4095 + 4096 = 12286, so the
    partial pass hands _resolve_carries limbs up to 4095 + 2 = 4097 — just
    inside _ks_resolve's stated [0, 2*2^12 - 2] precondition.
    """
    s = _partial_pass(a - b + jnp.asarray(_2P_SPREAD))
    return _cond_sub_p(_cond_sub_p(_resolve_carries(s)))


def neg(a):
    """(-a) mod p. Maps 0 -> 0 (p - 0 = p reduces to 0 via cond-subtract)."""
    zero = jnp.zeros_like(a)
    return sub(zero, a)


def scalar_small(a, k: int):
    """a * k mod p for a small static non-negative int k (k <= 8 used)."""
    if k == 0:
        return jnp.zeros_like(a)
    x = _normalize(a * k, NLIMBS + 1)  # value < 8p < 2^384 * ... fits
    # reduce [0, k*p) -> [0, p) by repeated conditional subtraction
    for _ in range(max(1, k - 1)):
        s, ge = _add_complement(x, _NEG_P[NLIMBS + 1])
        x = jnp.where(ge[..., None], s, x)
    return x[..., :NLIMBS]


def mont_mul(a, b):
    """Montgomery product: (a * b * R^{-1}) mod p, canonical in/out.

    Full-word REDC:  T = a*b;  m = (T mod R) * N' mod R;  out = (T + m*P)/R.
    """
    t = _normalize(_conv(a, b), 2 * NLIMBS)
    m_raw = jnp.einsum(
        "...ij,ijk->...k",
        t[..., :NLIMBS, None] * jnp.asarray(NPRIME_LIMBS)[..., None, :],
        jnp.asarray(_CONV_MASK_LOW),
    )
    m = _normalize(m_raw, NLIMBS + 1)[..., :NLIMBS]
    mp = _conv(m, jnp.asarray(P_LIMBS))
    # T + m*P is divisible by R = 2^384; its high half is the candidate
    # result. Sum limbwise (values < 2^30), normalize across all 2N limbs so
    # low-half carries flow into the high half, then drop the (zero) low half.
    # T + m*P < 2pR < 2^768, so 64 limbs suffice and the low 32 are zero.
    full = _normalize(
        t + jnp.pad(mp, [(0, 0)] * (mp.ndim - 1) + [(0, 1)]), 2 * NLIMBS
    )
    return _cond_sub_p(full[..., NLIMBS:])


def mont_sqr(a):
    return mont_mul(a, a)


# Uniform field-module interface (shared with ops.fp2) for generic curve code.
mul = mont_mul
sqr = mont_sqr


def to_mont(a):
    """Canonical residue -> Montgomery form."""
    return mont_mul(a, jnp.asarray(R2))


def from_mont(a):
    """Montgomery form -> canonical residue."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def is_zero(a):
    """Canonical limb vector == 0 (batched bool)."""
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """Branchless select: cond is (...,) bool; a/b are (..., NLIMBS)."""
    return jnp.where(cond[..., None], a, b)


def _pow_const(a_mont, exponent: int):
    """a^e in Montgomery form for a static exponent, via fori_loop over the
    fixed bit string (LSB-first square-and-multiply with masked multiplies).
    """
    nbits = max(1, exponent.bit_length())
    bits = np.array(
        [(exponent >> i) & 1 for i in range(nbits)], dtype=np.int32
    )
    bits_c = jnp.asarray(bits)

    def body(i, carry):
        result, base = carry
        mult = mont_mul(result, base)
        result = jnp.where(bits_c[i] == 1, mult, result)
        base = mont_sqr(base)
        return result, base

    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), a_mont.shape)
    result, _ = jax.lax.fori_loop(0, nbits, body, (one, a_mont))
    return result


def inv(a_mont):
    """Modular inverse in Montgomery form via Fermat: a^(p-2).

    inv(0) = 0 (used as a guarded value behind infinity selects upstream).
    """
    return _pow_const(a_mont, P - 2)


def pow_p_plus_1_over_4(a_mont):
    """a^((p+1)/4): square root candidate in Fp (p % 4 == 3)."""
    return _pow_const(a_mont, (P + 1) // 4)
