"""Batched BLS12-381 base-field (Fp) arithmetic on 12-bit limbs in int32 lanes.

Parity note: replaces the role of the `blst` C field arithmetic behind the
reference client's BLS boundary (reference crypto/bls/src/impls/blst.rs); the
math here is validated against `lighthouse_tpu.crypto.ref_fields`.

Design (tpu-first):
- An Fp element is `(..., NLIMBS)` int32, little-endian base-2^12 limbs.
  12-bit limbs keep every intermediate of a schoolbook 32x32-limb product
  below 2^30, so all accumulation fits native int32 lanes — no 64-bit
  emulation anywhere on the hot path.
- Multiplication is Montgomery (R = 2^384) in *full-word REDC* form:
  three 32x32-limb convolutions (a*b, m = T*N' mod R, m*P) which XLA maps to
  dense batched contractions, plus short sequential carry scans. This avoids
  the serial 32-step CIOS recurrence entirely — the only sequential pieces
  are carry propagations, which are cheap `lax.scan`s over 12-bit shifts.
- Elements on the device live in the Montgomery domain; conversion happens
  at the host boundary.

All public ops broadcast over leading batch axes.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lighthouse_tpu.crypto.constants import (
    LIMB_BITS,
    LIMB_MASK,
    MONT_R2_MOD_P,
    MONT_R_MOD_P,
    NLIMBS,
    P,
    int_to_limbs,
)

# ----------------------------------------------------------------- constants

PROD_LIMBS = 2 * NLIMBS - 1  # length of a full limb convolution

# N' = -P^{-1} mod R, as limbs (full-word Montgomery factor).
_NPRIME_INT = (-pow(P, -1, 1 << (LIMB_BITS * NLIMBS))) % (1 << (LIMB_BITS * NLIMBS))

P_LIMBS = np.array(int_to_limbs(P), dtype=np.int32)
NPRIME_LIMBS = np.array(int_to_limbs(_NPRIME_INT), dtype=np.int32)

# Anti-diagonal one-hot mask: MASK[i, j, k] = 1 iff i + j == k. Contracting
# the outer product of two limb vectors against it yields the polynomial
# (convolution) product — a dense einsum XLA can tile.
_CONV_MASK = np.zeros((NLIMBS, NLIMBS, PROD_LIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_MASK[_i, _j, _i + _j] = 1

ZERO = np.zeros(NLIMBS, dtype=np.int32)
ONE_MONT = np.array(int_to_limbs(MONT_R_MOD_P), dtype=np.int32)  # 1 in Mont form
R2 = np.array(int_to_limbs(MONT_R2_MOD_P), dtype=np.int32)


# ------------------------------------------------------------- host helpers


def from_int(v: int) -> np.ndarray:
    """Host: python int -> canonical limb vector (NOT Montgomery form)."""
    return np.array(int_to_limbs(v % P), dtype=np.int32)


def to_int(limbs) -> int:
    """Host: limb vector -> python int."""
    acc = 0
    for i, limb in enumerate(np.asarray(limbs).reshape(-1)):
        acc += int(limb) << (LIMB_BITS * i)
    return acc % P


def pack(values) -> np.ndarray:
    """Host: iterable of ints -> (N, NLIMBS) canonical limb array."""
    return np.stack([from_int(v) for v in values])


# ------------------------------------------------------------ carry handling


def _normalize(x, out_len):
    """Propagate carries so every limb lands in [0, 2^12).

    `x` may hold any int32 values (including negatives, via arithmetic
    shift) as long as the represented integer is in [0, 2^(12*out_len)).
    Returns an (..., out_len) array of canonical limbs.
    """
    in_len = x.shape[-1]
    if in_len < out_len:
        pad = [(0, 0)] * (x.ndim - 1) + [(0, out_len - in_len)]
        x = jnp.pad(x, pad)
    xs = jnp.moveaxis(x, -1, 0)

    def step(carry, v):
        t = v + carry
        return t >> LIMB_BITS, t & LIMB_MASK

    _, limbs = jax.lax.scan(step, jnp.zeros(x.shape[:-1], jnp.int32), xs)
    return jnp.moveaxis(limbs, 0, -1)[..., :out_len]


def _conv(a, b_or_const):
    """Full polynomial product of limb vectors: (..., N) x (..., N) -> (..., 2N-1).

    Products of 12-bit limbs are <= 2^24 and at most 32 stack per output
    coefficient, so int32 accumulation is exact.
    """
    outer = a[..., :, None] * b_or_const[..., None, :]
    return jnp.einsum("...ij,ijk->...k", outer, jnp.asarray(_CONV_MASK))


def _cond_sub_p(x):
    """Map x in [0, 2p) to x mod p: subtract p iff x >= p (branchless)."""
    d = x - jnp.asarray(P_LIMBS)
    ds = jnp.moveaxis(d, -1, 0)

    def step(borrow, v):
        t = v + borrow
        return t >> LIMB_BITS, t & LIMB_MASK

    borrow, limbs = jax.lax.scan(
        step, jnp.zeros(x.shape[:-1], jnp.int32), ds
    )
    sub = jnp.moveaxis(limbs, 0, -1)
    return jnp.where((borrow < 0)[..., None], x, sub)


# ----------------------------------------------------------------- field ops


def add(a, b):
    """(a + b) mod p for canonical inputs."""
    return _cond_sub_p(_normalize(a + b, NLIMBS))


def sub(a, b):
    """(a - b) mod p for canonical inputs: a - b + p, then reduce."""
    return _cond_sub_p(_normalize(a - b + jnp.asarray(P_LIMBS), NLIMBS))


def neg(a):
    """(-a) mod p. Maps 0 -> 0 (p - 0 = p reduces to 0 via cond-subtract)."""
    return _cond_sub_p(_normalize(jnp.asarray(P_LIMBS) - a, NLIMBS))


def scalar_small(a, k: int):
    """a * k for a small static non-negative int k (k * 4095 * 32 < 2^31)."""
    return _cond_n_sub(_normalize(a * k, NLIMBS + 1), k)


def _cond_n_sub(x, k: int):
    """Reduce x in [0, (k)*p) to [0, p) by repeated conditional subtraction.

    x has NLIMBS+1 limbs; k is a small static bound (<= 8 in practice).
    """
    p_ext = jnp.pad(jnp.asarray(P_LIMBS), (0, 1))
    for _ in range(max(1, k - 1)):
        d = _signed_sub(x, p_ext)
        x = jnp.where(_is_negative(d)[..., None], x, _normalize_signed(d))
    return x[..., :NLIMBS]


def _signed_sub(a, b):
    return a - b


def _is_negative(d):
    """True iff the integer represented by (possibly non-canonical) limb
    vector d is negative. Requires limbs in (-2^13, 2^13)."""
    ds = jnp.moveaxis(d, -1, 0)

    def step(borrow, v):
        t = v + borrow
        return t >> LIMB_BITS, t & LIMB_MASK

    borrow, _ = jax.lax.scan(step, jnp.zeros(d.shape[:-1], jnp.int32), ds)
    return borrow < 0


def _normalize_signed(d):
    """Canonicalize a limb vector known to represent a non-negative value."""
    return _normalize(d, d.shape[-1])


def mont_mul(a, b):
    """Montgomery product: (a * b * R^{-1}) mod p, canonical in/out.

    Full-word REDC:  T = a*b;  m = (T mod R) * N' mod R;  out = (T + m*P)/R.
    """
    t = _normalize(_conv(a, b), 2 * NLIMBS)
    m = _normalize(_conv(t[..., :NLIMBS], jnp.asarray(NPRIME_LIMBS)), 2 * NLIMBS)[
        ..., :NLIMBS
    ]
    mp = _conv(m, jnp.asarray(P_LIMBS))
    # T + m*P is divisible by R = 2^384; its high half is the candidate
    # result. Sum limbwise (values < 2^30), normalize across all 2N limbs so
    # low-half carries flow into the high half, then drop the (zero) low half.
    # T + m*P < 2pR < 2^768, so 64 limbs suffice and the low 32 are zero.
    full = _normalize(
        t + jnp.pad(mp, [(0, 0)] * (mp.ndim - 1) + [(0, 1)]), 2 * NLIMBS
    )
    return _cond_sub_p(full[..., NLIMBS:])


def mont_sqr(a):
    return mont_mul(a, a)


def to_mont(a):
    """Canonical residue -> Montgomery form."""
    return mont_mul(a, jnp.asarray(R2))


def from_mont(a):
    """Montgomery form -> canonical residue."""
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return mont_mul(a, one)


def is_zero(a):
    """Canonical limb vector == 0 (batched bool)."""
    return jnp.all(a == 0, axis=-1)


def eq(a, b):
    return jnp.all(a == b, axis=-1)


def select(cond, a, b):
    """Branchless select: cond is (...,) bool; a/b are (..., NLIMBS)."""
    return jnp.where(cond[..., None], a, b)


def _pow_const(a_mont, exponent: int):
    """a^e in Montgomery form for a static exponent, via fori_loop over the
    fixed bit string (LSB-first square-and-multiply with masked multiplies).
    """
    nbits = max(1, exponent.bit_length())
    bits = np.array(
        [(exponent >> i) & 1 for i in range(nbits)], dtype=np.int32
    )
    bits_c = jnp.asarray(bits)

    def body(i, carry):
        result, base = carry
        mult = mont_mul(result, base)
        result = jnp.where(bits_c[i] == 1, mult, result)
        base = mont_sqr(base)
        return result, base

    one = jnp.broadcast_to(jnp.asarray(ONE_MONT), a_mont.shape)
    result, _ = jax.lax.fori_loop(0, nbits, body, (one, a_mont))
    return result


def inv(a_mont):
    """Modular inverse in Montgomery form via Fermat: a^(p-2).

    inv(0) = 0 (used as a guarded value behind infinity selects upstream).
    """
    return _pow_const(a_mont, P - 2)


def pow_p_plus_1_over_4(a_mont):
    """a^((p+1)/4): square root candidate in Fp (p % 4 == 3)."""
    return _pow_const(a_mont, (P + 1) // 4)
