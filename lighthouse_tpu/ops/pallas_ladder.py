"""Pallas TPU kernel: per-lane RLC scalar ladders fused in VMEM.

The G2 ladder (sum_i r_i * sig_i) is the second-hottest stage of batch
verification after the Miller loop. This kernel keeps the accumulator,
the multiple table, and all intermediates in VMEM for the whole ladder;
the XLA level then tree-folds the per-lane multiples. Works for G1
(w=1) and G2 (w=2) via ops.tcurve.

Three kernel bodies, selected by `ops.window_ladder.ladder_impl()`
(the one LIGHTHOUSE_TPU_LADDER knob shared with the XLA planes):

  * "window" (DEFAULT) — the unified signed-digit window kernel: the
    scalar bits are recoded to window-major signed digits at the XLA
    level (`window_ladder.recode_bits`, one cheap int32 scan) and the
    kernel runs W windows of c doublings + ONE complete add against a
    VMEM multiple table (tcurve.window_table/window_step) — ~17 adds +
    72 doublings for 64-bit scalars vs the chain's 64 + 64;
  * "w2" — the earlier 2-bit unsigned window (kept for A/B);
  * "chain" — the legacy per-bit double-add (A/B via BENCH_IMPL=chain).
"""

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from lighthouse_tpu.ops import tcurve, tfield as tf
from lighthouse_tpu.ops import window_ladder as wl

NB = tf.NB


def _consts_array():
    return jnp.asarray(
        np.stack(
            [
                np.array(tf._OFF, np.int32)[:, None],
                np.array(tf._SPREAD_SUB, np.int32)[:, None],
                np.array(tf._COMP_2P, np.int32)[:, None],
                np.array(tf.fb.ONE_MONT_B, np.int32)[:, None],
            ]
        )
    )  # (4, NB, 1)


def _overrides(consts):
    return {
        "off": consts[0],
        "spread_sub": consts[1],
        "comp_2p": consts[2],
        "one": consts[3],
    }


def _ladder_kernel(group, n_bits, x_ref, y_ref, z_ref, bits_ref,
                   consts_ref, redc_ref, ox_ref, oy_ref, oz_ref):
    with tf.const_overrides(
        **_overrides(consts_ref[:]), **tf.redc_overrides(redc_ref[:])
    ):
        pt = (x_ref[:], y_ref[:], z_ref[:])
        B = pt[0].shape[-1]
        acc0 = group.identity(B)

        def body(i, carry):
            acc, addend = carry
            bit = bits_ref[i]  # (B,) int32
            return group.ladder_step(acc, addend, bit)

        acc, _ = jax.lax.fori_loop(0, n_bits, body, (acc0, pt))
        ox_ref[:], oy_ref[:], oz_ref[:] = acc


def _ladder_kernel_w2(group, n_bits, x_ref, y_ref, z_ref, bits_ref,
                      consts_ref, redc_ref, ox_ref, oy_ref, oz_ref):
    """Windowed-2 MSB-first ladder: per window 2 doubles + ONE complete
    add from a {identity, P, 2P, 3P} VMEM table — ~25% fewer group ops
    than the double-add chain (tcurve.window2_step)."""
    assert n_bits % 2 == 0, n_bits
    with tf.const_overrides(
        **_overrides(consts_ref[:]), **tf.redc_overrides(redc_ref[:])
    ):
        pt = (x_ref[:], y_ref[:], z_ref[:])
        B = pt[0].shape[-1]
        table = group.window2_table(pt)
        n_windows = n_bits // 2

        def body(j, acc):
            # window j covers bits (n_bits-2j-2, n_bits-2j-1), MSB-first
            lo = n_bits - 2 * j - 2
            digit = bits_ref[lo] + 2 * bits_ref[lo + 1]
            return group.window2_step(acc, table, digit)

        acc = jax.lax.fori_loop(0, n_windows, body, group.identity(B))
        ox_ref[:], oy_ref[:], oz_ref[:] = acc


def _ladder_kernel_w4(group, n_windows, c, x_ref, y_ref, z_ref, mags_ref,
                      negs_ref, consts_ref, redc_ref, ox_ref, oy_ref,
                      oz_ref):
    """The unified signed-digit window kernel (MSB-first): per window
    c doublings + ONE complete add against the in-VMEM multiple table
    [0..2^(c-1)]·P, digit sign applied by negating y. Digits arrive
    pre-recoded (window_ladder.recode_bits at the XLA level)."""
    with tf.const_overrides(
        **_overrides(consts_ref[:]), **tf.redc_overrides(redc_ref[:])
    ):
        pt = (x_ref[:], y_ref[:], z_ref[:])
        B = pt[0].shape[-1]
        table = group.window_table(pt, c)

        def body(j, acc):
            w_i = n_windows - 1 - j  # MSB-first over LSB-first storage
            return group.window_step(
                acc, table, mags_ref[w_i], negs_ref[w_i] == 1, c
            )

        acc = jax.lax.fori_loop(0, n_windows, body, group.identity(B))
        ox_ref[:], oy_ref[:], oz_ref[:] = acc


def ladder_pallas(
    pt,
    bits,
    group_name: str = "G2",
    block_b: int = 128,
    interpret: bool = False,
    kind: str | None = None,
):
    """Per-lane scalar ladder on PROJECTIVE inputs: pt = (X, Y, Z)
    bundles (w, NB, B) (identity lanes pass through as the identity),
    bits (n_bits, B) int32 LSB-first. Returns projective (X, Y, Z).

    `kind` None resolves LIGHTHOUSE_TPU_LADDER HERE
    (window_ladder.ladder_impl — "window" default / "w2" / "chain"),
    outside the jit — the kernel choice must be part of the jit key, or
    flipping the env var after a first trace would silently reuse the
    old kernel."""
    if kind is None:
        kind = wl.ladder_impl()
    return _ladder_pallas(
        pt, bits, group_name=group_name, block_b=block_b,
        interpret=interpret, kind=kind,
    )


@functools.partial(
    jax.jit,
    static_argnames=("group_name", "block_b", "interpret", "kind"),
)
def _ladder_pallas(
    pt,
    bits,
    group_name: str = "G2",
    block_b: int = 128,
    interpret: bool = False,
    kind: str = "window",
):
    group = tcurve.TPG2 if group_name == "G2" else tcurve.TPG1
    w = group.w
    X, Y, Z = pt
    B = X.shape[-1]
    n_bits = bits.shape[0]
    if kind == "w2" and n_bits % 2:
        bits = jnp.concatenate(
            [bits, jnp.zeros((1, B), bits.dtype)]
        )
        n_bits += 1
    assert B % block_b == 0, (B, block_b)
    grid = (B // block_b,)

    def spec(s):
        return pl.BlockSpec(
            (s, NB, block_b), lambda i: (0, 0, i),
            memory_space=pltpu.VMEM,
        )

    const_spec = pl.BlockSpec(
        (4, NB, 1), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
    )
    redc_spec = pl.BlockSpec(
        tf.REDC_MATS_SHAPE, lambda i: (0, 0), memory_space=pltpu.VMEM
    )

    shape = jax.ShapeDtypeStruct((w, NB, B), jnp.int32)
    if kind == "window":
        c = wl.WINDOW_BITS
        # recode at the XLA level (cheap int32 scan); the kernel reads
        # window-major digit magnitudes + sign flags from VMEM
        mags, negs = wl.recode_bits(jnp.moveaxis(bits, 0, -1), c)
        n_windows = mags.shape[0]
        dig_spec = pl.BlockSpec(
            (n_windows, block_b), lambda i: (0, i),
            memory_space=pltpu.VMEM,
        )
        ox, oy, oz = pl.pallas_call(
            functools.partial(_ladder_kernel_w4, group, n_windows, c),
            out_shape=(shape, shape, shape),
            grid=grid,
            in_specs=[spec(w), spec(w), spec(w), dig_spec, dig_spec,
                      const_spec, redc_spec],
            out_specs=(spec(w), spec(w), spec(w)),
            interpret=interpret,
        )(
            X, Y, Z, mags, negs.astype(jnp.int32), _consts_array(),
            tf.redc_mats_array(),
        )
        return ox, oy, oz

    bits_spec = pl.BlockSpec(
        (n_bits, block_b), lambda i: (0, i), memory_space=pltpu.VMEM
    )
    kernel = _ladder_kernel_w2 if kind == "w2" else _ladder_kernel
    ox, oy, oz = pl.pallas_call(
        functools.partial(kernel, group, n_bits),
        out_shape=(shape, shape, shape),
        grid=grid,
        in_specs=[spec(w), spec(w), spec(w), bits_spec, const_spec,
                  redc_spec],
        out_specs=(spec(w), spec(w), spec(w)),
        interpret=interpret,
    )(X, Y, Z, bits, _consts_array(), tf.redc_mats_array())
    return ox, oy, oz
