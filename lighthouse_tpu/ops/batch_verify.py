"""Device data plane for batched BLS signature-set verification.

This is the TPU-native core of the framework's north-star boundary — the
role of `verify_signature_sets` in the reference client
(crypto/bls/src/impls/blst.rs:36-119): given S signature sets, each with a
message point H(m) in G2, a signature in G2, and up to K public keys in G1,
verify all of them with ONE multi-pairing using the random-linear-combination
trick (same scheme as the reference: >=64-bit random scalars, one
multi-pairing for the whole batch):

    prod_i [ e(r_i * agg_pk_i, H_i) ] * e(-G1, sum_i r_i * sig_i)  ==  1

All inputs are device arrays with static shapes (S sets x K padded keys);
variable real sizes are carried by boolean masks — the TPU-native
replacement for the reference's per-set heap-allocated pubkey vectors.
A pair whose RLC'd aggregate pubkey is infinity is masked out of the
multi-pairing, which is exact (e(inf, H) == 1); a forged or missing
signature still breaks the identity through the signature-sum pair.

Host-side policy (empty-set rejection, infinity-pubkey rejection, point
decompression, subgroup checks, RLC scalar sampling) lives in
`lighthouse_tpu.bls`; this module is pure device math.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import G1_X, G1_Y, NLIMBS, P, int_to_limbs
from lighthouse_tpu.ops import curve, fp, fp2, pairing


def _mont(v: int) -> np.ndarray:
    return np.array(int_to_limbs((v << 384) % P), dtype=np.int32)


# -G1 generator, affine Montgomery (static constant for the signature pair).
NEG_G1_AFFINE = (_mont(G1_X), _mont((P - G1_Y) % P))

RAND_BITS = 64  # >= 64-bit RLC scalars, matching the reference's coefficients


def _lift_g1(aff, valid):
    """Affine G1 + validity mask -> Jacobian (Z = 1, or Z = 0 => infinity)."""
    x, y = aff
    z = jnp.where(
        valid[..., None],
        jnp.broadcast_to(jnp.asarray(fp.ONE_MONT), x.shape),
        jnp.zeros_like(x),
    )
    return (x, y, z)


def _lift_g2(aff, valid):
    x, y = aff
    one = fp2.broadcast_const(fp2.ONE_MONT, x[0])
    zero = (jnp.zeros_like(x[0]), jnp.zeros_like(x[1]))
    return (x, y, fp2.select(valid, one, zero))


def _expand0(tree):
    return jax.tree_util.tree_map(lambda t: t[None], tree)


def _concat0(a, b):
    return jax.tree_util.tree_map(
        lambda x, y: jnp.concatenate([x, y], axis=0), a, b
    )


def aggregate_pubkeys(pubkeys_g1_aff, key_mask):
    """Per-set pubkey aggregation: (S, K) affine G1 + mask -> (S,) Jacobian.

    The reference aggregates per-set pubkeys by serial point addition on the
    CPU; here it is a masked log-depth tree fold over the padded key axis.
    """
    pts = _lift_g1(pubkeys_g1_aff, key_mask)
    return curve.G1.masked_sum_axis(pts, key_mask, axis=1)


def rlc_combined_signature(sigs_g2_aff, rand_bits, set_mask):
    """sum_i r_i * sig_i over the set axis -> single Jacobian G2 point."""
    sig_jac = _lift_g2(sigs_g2_aff, set_mask)
    sig_r = curve.G2.mul_scalar_bits(sig_jac, rand_bits)
    return curve.G2.masked_sum_axis(sig_r, set_mask, axis=0)


def miller_inputs(
    msgs_g2_aff, sigs_g2_aff, pubkeys_g1_aff, key_mask, rand_bits, set_mask
):
    """Everything up to the Miller loop: build the (S+1)-pair multi-pairing
    inputs. Split out so the sharded path can run it per-shard."""
    agg_pk = aggregate_pubkeys(pubkeys_g1_aff, key_mask)
    agg_pk_r = curve.G1.mul_scalar_bits(agg_pk, rand_bits)
    pk_x, pk_y, pk_inf = curve.G1.to_affine(agg_pk_r)

    sig_acc = rlc_combined_signature(sigs_g2_aff, rand_bits, set_mask)
    s_x, s_y, s_inf = curve.G2.to_affine(_expand0(sig_acc))

    neg_g1 = (
        jnp.asarray(NEG_G1_AFFINE[0])[None],
        jnp.asarray(NEG_G1_AFFINE[1])[None],
    )
    g1_side = (
        jnp.concatenate([pk_x, neg_g1[0]], axis=0),
        jnp.concatenate([pk_y, neg_g1[1]], axis=0),
    )
    g2_side = _concat0(msgs_g2_aff, (s_x, s_y))
    pair_mask = jnp.concatenate([set_mask & ~pk_inf, ~s_inf], axis=0)
    return g1_side, g2_side, pair_mask


def verify_signature_sets(
    msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
):
    """One-shot batched verification of S signature sets on one chip.

    Args:
      msgs_g2_aff:    affine Montgomery G2 message points H(m_i), Fp2 pair
                      of (S, NLIMBS) limb arrays per coordinate.
      sigs_g2_aff:    affine G2 signatures, same layout.
      pubkeys_g1_aff: ((S, K, NLIMBS), (S, K, NLIMBS)) affine G1 pubkeys.
      key_mask:       (S, K) bool — real pubkeys per set.
      rand_bits:      (S, RAND_BITS) int32 LSB-first RLC scalar bits
                      (sampled host-side so device code stays deterministic).
      set_mask:       (S,) bool — real sets (padding sets are skipped).

    Returns: scalar bool — True iff every real set verifies.
    """
    g1_side, g2_side, pair_mask = miller_inputs(
        msgs_g2_aff, sigs_g2_aff, pubkeys_g1_aff, key_mask, rand_bits, set_mask
    )
    return pairing.multi_pairing_is_one(g1_side, g2_side, pair_mask)
