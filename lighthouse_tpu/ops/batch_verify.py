"""Device data plane for batched BLS signature-set verification (bundles).

The TPU-native core of the north-star boundary — the role of
`verify_signature_sets` in the reference client
(crypto/bls/src/impls/blst.rs:36-119): S signature sets, each with a
message point H(m) in G2, a signature in G2, and up to K public keys in
G1, verified with ONE multi-pairing via the random-linear-combination
trick:

    prod_i [ e(r_i * agg_pk_i, H_i) ] * e(-G1, sum_i r_i * sig_i)  ==  1

Static shapes (S sets x K padded keys) with boolean masks — the TPU-native
replacement for per-set heap vectors. A pair whose RLC'd aggregate pubkey
is infinity is masked out (exact: e(inf, H) == 1); a forged signature
still breaks the identity through the signature-sum pair.

Shapes: G2 affine = pair of (..., 2, NB) bundles; G1 affine = pair of
(..., 1, NB); pubkeys = ((S, K, 1, NB), (S, K, 1, NB)).

Host-side policy (empty-set rejection, infinity-pubkey rejection, point
decompression, subgroup checks, RLC sampling) lives in `lighthouse_tpu.bls`.
"""

import numpy as np

import jax.numpy as jnp

from lighthouse_tpu.common.tracing import span
from lighthouse_tpu.crypto.constants import G1_X, G1_Y, P, R
from lighthouse_tpu.ops import curve, fieldb as fb, pairing
from lighthouse_tpu.ops import window_ladder as wl

NB = fb.NB

# group order bits (LSB-first) for the device subgroup check
_R_BITS = curve.scalars_to_bits([R], R.bit_length())


def _mont1(v: int) -> np.ndarray:
    return fb._limbs((v << 384) % P, NB)[None, :]


# -G1 generator, affine Montgomery (static constant for the signature pair).
NEG_G1_AFFINE = (_mont1(G1_X), _mont1((P - G1_Y) % P))

RAND_BITS = 64  # >= 64-bit RLC scalars, matching the reference


def _expand0(pt):
    return tuple(c[None] for c in pt)


def aggregate_pubkeys(pubkeys_g1_aff, key_mask):
    """(S, K) affine G1 + mask -> (S,) projective aggregate per set
    (log-depth tree fold over the key axis, complete-formula plane).
    from_affine already maps masked-out lanes to the identity."""
    pts = curve.PG1.from_affine(pubkeys_g1_aff, key_mask)
    return curve.PG1.sum_axis(pts, axis=1)


def rlc_combined_signature(sigs_g2_aff, rand_bits, set_mask):
    """sum_i r_i * sig_i -> single projective G2 point. Masked-out lanes
    enter as the identity and stay the identity through the ladder
    (the shared window kernel — ops.window_ladder)."""
    sig_proj = curve.PG2.from_affine(sigs_g2_aff, set_mask)
    sig_r = wl.ladder(curve.PG2, sig_proj, rand_bits)
    return curve.PG2.sum_axis(sig_r, axis=0)


def _assemble_pairs(
    msgs_g2_aff, set_mask, pk_aff, sig_aff
):
    """Assemble the (S+1)-pair multi-pairing inputs from the affinized
    RLC'd pubkeys and signature sum — shared by the XLA and Pallas
    input builders so the pair/mask rules cannot diverge."""
    pk_x, pk_y, pk_inf = pk_aff
    s_x, s_y, s_inf = sig_aff
    neg_g1 = (
        jnp.asarray(NEG_G1_AFFINE[0])[None],
        jnp.asarray(NEG_G1_AFFINE[1])[None],
    )
    g1_side = (
        jnp.concatenate([pk_x, neg_g1[0]], axis=0),
        jnp.concatenate([pk_y, neg_g1[1]], axis=0),
    )
    g2_side = (
        jnp.concatenate([msgs_g2_aff[0], s_x], axis=0),
        jnp.concatenate([msgs_g2_aff[1], s_y], axis=0),
    )
    pair_mask = jnp.concatenate([set_mask & ~pk_inf, ~s_inf], axis=0)
    return g1_side, g2_side, pair_mask


def miller_inputs(
    msgs_g2_aff, sigs_g2_aff, pubkeys_g1_aff, key_mask, rand_bits, set_mask
):
    """Build the (S+1)-pair multi-pairing inputs; shared with the sharded
    path. The `trace/*` spans attribute JAX TRACE time per stage — they
    fire once per (re)compile of the enclosing jit, not per dispatch."""
    with span("trace/pubkey_aggregation"):
        agg_pk = aggregate_pubkeys(pubkeys_g1_aff, key_mask)
    with span("trace/rlc_ladder_g1"):
        agg_pk_r = wl.ladder(curve.PG1, agg_pk, rand_bits)
    pk_aff = curve.PG1.to_affine(agg_pk_r)

    with span("trace/rlc_ladder_g2"):
        sig_acc = rlc_combined_signature(sigs_g2_aff, rand_bits, set_mask)
    sig_aff = curve.PG2.to_affine(_expand0(sig_acc))
    return _assemble_pairs(msgs_g2_aff, set_mask, pk_aff, sig_aff)


def verify_signature_sets(
    msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
):
    """One-shot batched verification of S signature sets on one chip.
    Returns a scalar bool — True iff every real set verifies."""
    g1_side, g2_side, pair_mask = miller_inputs(
        msgs_g2_aff, sigs_g2_aff, pubkeys_g1_aff, key_mask, rand_bits,
        set_mask,
    )
    return pairing.multi_pairing_is_one(g1_side, g2_side, pair_mask)


def _grouped_pair_inputs(pk_aff, sig_aff, group_msgs_g2_aff, group_mask):
    return _assemble_pairs(group_msgs_g2_aff, group_mask, pk_aff, sig_aff)


def grouped_miller_inputs(
    group_msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
    group_mask,
):
    """Multi-pairing inputs for the MESSAGE-GROUPED batch check.

    Sets sharing one message merge into one pair by bilinearity:

        prod_i e(r_i*pk_i, H(m_i))
          = prod_g e( sum_{i in g} r_i*pk_i, H(M_g) )

    so G distinct messages need G Miller loops instead of S — the real
    mainnet slot load is ~64 committees over >=30k attestation sets
    (SURVEY §3.3), a ~500x reduction of the dominant pairing work. The
    RLC stays PER SET (r_i sampled per set, exactly the ungrouped
    check's product reassociated), so soundness is unchanged.

    Grid layout (host bins sets by message): sigs/pubkeys/key_mask/
    rand_bits/set_mask carry leading (G, Sg) axes; group_msgs and
    group_mask are (G,)-shaped. Padding sets have all-False key masks
    and set_mask False; their aggregates enter group folds as the
    identity."""
    G_, Sg = set_mask.shape

    # per-set aggregate over K keys, then the per-set RLC ladder — all
    # on the (G, Sg) grid (the group primitives take any leading batch)
    with span("trace/pubkey_aggregation"):
        agg_pk = curve.PG1.sum_axis(
            curve.PG1.from_affine(pubkeys_g1_aff, key_mask), axis=2
        )
    with span("trace/rlc_ladder_g1"):
        agg_pk_r = wl.ladder(curve.PG1, agg_pk, rand_bits)
    # fold each group's RLC'd pubkeys into one point per message
    with span("trace/msm_group_fold"):
        grp_pk = curve.PG1.sum_axis(agg_pk_r, axis=1)  # (G,)
    pk_aff = curve.PG1.to_affine(grp_pk)

    # signature side is unchanged by grouping: one global RLC sum
    with span("trace/rlc_ladder_g2"):
        sig_proj = curve.PG2.from_affine(sigs_g2_aff, set_mask)
        sig_r = wl.ladder(curve.PG2, sig_proj, rand_bits)
        sig_acc = curve.PG2.sum_axis(
            curve.PG2.sum_axis(sig_r, axis=1), axis=0
        )
    sig_aff = curve.PG2.to_affine(_expand0(sig_acc))
    return _grouped_pair_inputs(
        pk_aff, sig_aff, group_msgs_g2_aff, group_mask
    )


def verify_signature_sets_grouped(
    group_msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
    group_mask,
):
    """Batched verification with message-grouped pairing merge: (G+1)
    Miller loops for S sets over G distinct messages. Verdict-equivalent
    to verify_signature_sets on the flattened sets (tested)."""
    g1_side, g2_side, pair_mask = grouped_miller_inputs(
        group_msgs_g2_aff, sigs_g2_aff, pubkeys_g1_aff, key_mask,
        rand_bits, set_mask, group_mask,
    )
    return pairing.multi_pairing_is_one(g1_side, g2_side, pair_mask)


def verify_signature_sets_grouped_pallas(
    group_msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
    group_mask,
    block_b: int = 128,
    interpret: bool = False,
    tail: bool = False,
):
    """The grouped check with the RLC ladders and the (G+1)-pair Miller
    loop running as the same fused Pallas kernels the flat path uses —
    ladders over the flattened (G*Sg) lane axis, Miller over the G+1
    merged pairs (via the shared _pairs_to_verdict_pallas tail; with
    `tail=True` the fold + final exponentiation run in-kernel, same
    knob as the flat path — part of the backend's unified dispatch)."""
    from lighthouse_tpu.ops import tcurve, tfield as tf
    from lighthouse_tpu.ops.pallas_ladder import ladder_pallas

    G_, Sg = set_mask.shape
    S = G_ * Sg

    def flat(c):
        return c.reshape((S,) + c.shape[2:])

    bits_t = jnp.transpose(
        rand_bits.reshape(S, rand_bits.shape[-1])
    ).astype(jnp.int32)

    # G1 ladders over all S sets (padding sets ride as identities)
    agg_pk = curve.PG1.sum_axis(
        curve.PG1.from_affine(pubkeys_g1_aff, key_mask), axis=2
    )
    agg_t = tuple(tf.from_batchlead(flat(c)) for c in agg_pk)
    agg_t = _pad_lanes_projective(agg_t, block_b, tcurve.TPG1)
    padded = agg_t[0].shape[-1] - S
    bits_pad = jnp.pad(bits_t, ((0, 0), (0, padded)))
    pk_r_t = ladder_pallas(
        agg_t, bits_pad, group_name="G1", block_b=block_b,
        interpret=interpret,
    )
    pk_r = tuple(tf.to_batchlead(c)[:S] for c in pk_r_t)
    pk_r = tuple(c.reshape((G_, Sg) + c.shape[1:]) for c in pk_r)
    grp_pk = curve.PG1.sum_axis(pk_r, axis=1)  # (G,)
    pk_aff = curve.PG1.to_affine(grp_pk)

    # G2 ladders over the signatures + global fold
    sx, sy = (tf.from_batchlead(flat(c)) for c in sigs_g2_aff)
    sig_t = tcurve.TPG2.from_affine((sx, sy), set_mask.reshape(S))
    sig_t = _pad_lanes_projective(sig_t, block_b, tcurve.TPG2)
    sig_r_t = ladder_pallas(
        sig_t, bits_pad, group_name="G2", block_b=block_b,
        interpret=interpret,
    )
    sig_r = tuple(tf.to_batchlead(c)[:S] for c in sig_r_t)
    sig_acc = curve.PG2.sum_axis(sig_r, axis=0)
    sig_aff = curve.PG2.to_affine(_expand0(sig_acc))

    g1_side, g2_side, pair_mask = _grouped_pair_inputs(
        pk_aff, sig_aff, group_msgs_g2_aff, group_mask
    )
    return _pairs_to_verdict_pallas(
        g1_side, g2_side, pair_mask, block_b=block_b,
        interpret=interpret, tail=tail,
    )


def _pairs_to_verdict_pallas(
    g1_side, g2_side, pair_mask, block_b: int = 128,
    interpret: bool = False, tail: bool = False,
):
    """Pad the pair axis to a lane-tile multiple, run the fused Miller
    kernel, fold + final-exp (in-kernel with tail=True) — the shared
    back half of every Pallas verify variant."""
    from lighthouse_tpu.ops import tfield as tf, tower
    from lighthouse_tpu.ops.pallas_miller import miller_loop_pallas

    n_pairs = g1_side[0].shape[0]
    pad = (-n_pairs) % block_b
    if pad:
        def pad0(c):
            widths = [(0, pad)] + [(0, 0)] * (c.ndim - 1)
            return jnp.pad(c, widths)

        g1_side = tuple(pad0(c) for c in g1_side)
        g2_side = tuple(pad0(c) for c in g2_side)
        pair_mask = jnp.pad(pair_mask, (0, pad))
    p_t = tuple(tf.from_batchlead(c) for c in g1_side)
    q_t = tuple(tf.from_batchlead(c) for c in g2_side)
    f_t = miller_loop_pallas(
        p_t, q_t, pair_mask, block_b=block_b, interpret=interpret
    )
    if tail:
        from lighthouse_tpu.ops.pallas_tail import fold_final_exp_pallas

        res_t = fold_final_exp_pallas(f_t, interpret=interpret)
        res = tf.to_batchlead(res_t)[0]  # (12, NB)
        return tower.fp12_is_one(res)
    f = tf.to_batchlead(f_t)
    prod = tower.fp12_product_axis(f, axis=0)
    return pairing.final_exp_is_one(prod)


def verify_signature_sets_individual(
    msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    set_mask,
):
    """Per-set verdicts in ONE device call (the batch-failure fallback —
    SURVEY §7 hard part 5, attestation batch.rs:115-131 semantics without
    the per-set round trips): set i passes iff

        e(agg_pk_i, H_i) * e(-G1, sig_i) == 1.

    No RLC is needed — each set is its own independent pairing check; the
    Miller loop runs over 2S pairs (so one poisoned batch costs ~2x a
    full batch verify — accepted: batch failures are rare and the
    alternative, residue bisection, would cost device round trips the
    <=2-call bound forbids) and the final exponentiation is
    batched per set. Returns a (S,) bool array (padding lanes True)."""
    S = set_mask.shape[0]
    agg_pk = aggregate_pubkeys(pubkeys_g1_aff, key_mask)
    pk_x, pk_y, pk_inf = curve.PG1.to_affine(agg_pk)

    neg_g1 = (
        jnp.broadcast_to(jnp.asarray(NEG_G1_AFFINE[0]), pk_x.shape),
        jnp.broadcast_to(jnp.asarray(NEG_G1_AFFINE[1]), pk_y.shape),
    )
    g1_side = (
        jnp.concatenate([pk_x, neg_g1[0]], axis=0),
        jnp.concatenate([pk_y, neg_g1[1]], axis=0),
    )
    g2_side = (
        jnp.concatenate([msgs_g2_aff[0], sigs_g2_aff[0]], axis=0),
        jnp.concatenate([msgs_g2_aff[1], sigs_g2_aff[1]], axis=0),
    )
    # e(inf, .) == 1 exactly; a masked padding lane contributes 1 to both
    # of its pairs and trivially passes
    pair_mask = jnp.concatenate(
        [set_mask & ~pk_inf, set_mask], axis=0
    )
    f = pairing.miller_loop(g1_side, g2_side, valid_mask=pair_mask)
    from lighthouse_tpu.ops import tower

    f_set = tower.fp12_mul(f[:S], f[S:])
    ok = tower.fp12_is_one(pairing.final_exponentiation(f_set))
    return ok | ~set_mask


def _next_pow2(n: int) -> int:
    return 1 << max(0, n - 1).bit_length()


def verify_signature_sets_t(
    msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
):
    """Same verdict as verify_signature_sets, computed entirely in the
    transposed batch-on-lanes layout (ops.tfield/tcurve/tpairing/tfexp)
    at the XLA level — no Pallas. The batch-leading layout's trailing
    33-limb axis wastes ~3/4 of each VPU register row; here the batch
    rides the 128-lane axis end to end (RLC ladders, Miller loop, pair
    fold, final exponentiation). Only the per-set K-key aggregation and
    the two to-affine inversions stay batch-leading (they are small and
    already lane-efficient over S)."""
    from lighthouse_tpu.ops import tcurve, tfexp, tfield as tf
    from lighthouse_tpu.ops import tower
    from lighthouse_tpu.ops import tpairing as tp

    S = set_mask.shape[0]
    bits_t = jnp.transpose(rand_bits).astype(jnp.int32)  # (64, S)

    # G1: per-set aggregate (tree fold over K), transposed RLC ladder
    # (the shared window kernel via the transposed dispatcher)
    agg_pk = aggregate_pubkeys(pubkeys_g1_aff, key_mask)
    agg_t = tuple(tf.from_batchlead(c) for c in agg_pk)
    pk_r_t = wl.ladder_t(tcurve.TPG1, agg_t, bits_t)
    pk_r = tuple(tf.to_batchlead(c) for c in pk_r_t)
    pk_aff = curve.PG1.to_affine(pk_r)

    # G2: transposed RLC ladder over the signatures + lane-tree fold.
    # The ladder runs on exactly S lanes; only sum_lanes needs a
    # power-of-two count, so identity-pad its INPUT, not the ladder's.
    sx, sy = (tf.from_batchlead(c) for c in sigs_g2_aff)
    sig_t = tcurve.TPG2.from_affine((sx, sy), set_mask)
    sig_r_t = wl.ladder_t(tcurve.TPG2, sig_t, bits_t)
    pad = _next_pow2(S) - S
    if pad:
        ident = tcurve.TPG2.identity(pad)
        sig_r_t = tuple(
            jnp.concatenate([c, i], axis=-1)
            for c, i in zip(sig_r_t, ident)
        )
    sig_folded = tcurve.TPG2.sum_lanes(sig_r_t)  # 1-lane bundles
    sig_acc = tuple(tf.to_batchlead(c)[0] for c in sig_folded)
    sig_aff = curve.PG2.to_affine(_expand0(sig_acc))

    g1_side, g2_side, pair_mask = _assemble_pairs(
        msgs_g2_aff, set_mask, pk_aff, sig_aff
    )

    # transposed Miller loop on exactly S+1 pair lanes — no padding:
    # tfexp.fold_lanes carries odd counts, and pow2-padding here would
    # nearly double the dominant Miller work at S=1024 (1025 -> 2048)
    p_t = tuple(tf.from_batchlead(c) for c in g1_side)
    q_t = tuple(tf.from_batchlead(c) for c in g2_side)
    f_t = tp.miller_loop_t(p_t, q_t, pair_mask)
    prod_t = tfexp.fold_lanes(f_t)
    frob = jnp.asarray(tfexp.frob_consts())[:, :, None]
    res_t = tfexp.final_exponentiation_t(prod_t, frob[:12], frob[12:])
    return tower.fp12_is_one(tf.to_batchlead(res_t)[0])


def g2_points_in_subgroup(points_g2_aff, mask):
    """(S,) bool — [r]·P == identity per lane, the batched device form of
    the host-side signature subgroup check (blst.rs:72-81 policy;
    ref_curve.in_subgroup is the ground truth).

    Runs a fully-general double-add ladder on the UNIFIED Jacobian
    plane: the inputs are by definition UNCHECKED points. Neither the
    RCB complete formulas (complete only on the odd-order r-torsion) nor
    the lean `add_nonexceptional` ladder (whose no-collision argument
    assumes the base has order r — an adversarial small-order twist
    point breaks it) may be used here; `JacobianGroup.add` handles every
    exceptional case. Masked lanes pass."""
    import jax

    G = curve.G2
    x, y = points_g2_aff
    F = G.F
    one = jnp.broadcast_to(jnp.asarray(F.ONE), x.shape)
    zero = jnp.zeros_like(x)
    m = mask
    # affine -> Jacobian (z = 1); masked lanes to infinity (z = 0)
    pt = (
        F.select(m, x, zero),
        F.select(m, y, one),
        F.select(m, one, zero),
    )
    batch = pt[0].shape[:-2]
    bits_seq = jnp.asarray(_R_BITS[0], dtype=jnp.int32)  # (255,) LSB-first

    def step(carry, bit):
        acc, addend = carry
        added = G.add(acc, addend)
        use = jnp.broadcast_to(bit == 1, batch)
        acc = G.select(use, added, acc)
        addend = G.double(addend)
        return (acc, addend), None

    init = (G.infinity_like(pt), pt)
    (acc, _), _ = jax.lax.scan(step, init, bits_seq)
    return G.is_infinity(acc) | ~mask


def _pad_lanes_projective(pt_t, block_b: int, group):
    """Pad the lane axis of a transposed projective point to a block
    multiple with identity lanes."""
    B = pt_t[0].shape[-1]
    pad = (-B) % block_b
    if not pad:
        return pt_t
    ix, iy, iz = group.identity(pad)
    return tuple(
        jnp.concatenate([c, i], axis=-1)
        for c, i in zip(pt_t, (ix, iy, iz))
    )


def miller_inputs_pallas(
    msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
    block_b: int = 128,
    interpret: bool = False,
):
    """miller_inputs with the per-set G1 and per-signature G2 RLC ladders
    running as fused Pallas VMEM kernels (ops.pallas_ladder); MSM folds
    and the to-affine inversions stay on the XLA path."""
    from lighthouse_tpu.ops import tcurve, tfield as tf
    from lighthouse_tpu.ops.pallas_ladder import ladder_pallas

    bits_t = jnp.transpose(rand_bits).astype(jnp.int32)  # (64, S)

    # ---- G1: aggregate per set (XLA fold), then the pallas ladder
    agg_pk = aggregate_pubkeys(pubkeys_g1_aff, key_mask)  # (S,) projective
    agg_t = tuple(tf.from_batchlead(c) for c in agg_pk)
    agg_t = _pad_lanes_projective(agg_t, block_b, tcurve.TPG1)
    padded = agg_t[0].shape[-1] - agg_pk[0].shape[0]
    bits_pad = jnp.pad(bits_t, ((0, 0), (0, padded)))
    pk_r_t = ladder_pallas(
        agg_t, bits_pad, group_name="G1", block_b=block_b,
        interpret=interpret,
    )
    n_sets = agg_pk[0].shape[0]
    pk_r = tuple(tf.to_batchlead(c)[:n_sets] for c in pk_r_t)
    pk_aff = curve.PG1.to_affine(pk_r)

    # ---- G2: pallas ladder over the signatures, then the XLA fold
    # (sliced back to the real lane count first — folding identity
    # padding would widen every tree level for nothing)
    sx, sy = (tf.from_batchlead(c) for c in sigs_g2_aff)
    sig_t = tcurve.TPG2.from_affine((sx, sy), set_mask)
    sig_t = _pad_lanes_projective(sig_t, block_b, tcurve.TPG2)
    sig_r_t = ladder_pallas(
        sig_t, bits_pad, group_name="G2", block_b=block_b,
        interpret=interpret,
    )
    sig_r = tuple(tf.to_batchlead(c)[:n_sets] for c in sig_r_t)
    sig_acc = curve.PG2.sum_axis(sig_r, axis=0)
    sig_aff = curve.PG2.to_affine(_expand0(sig_acc))
    return _assemble_pairs(msgs_g2_aff, set_mask, pk_aff, sig_aff)


def verify_signature_sets_pallas(
    msgs_g2_aff,
    sigs_g2_aff,
    pubkeys_g1_aff,
    key_mask,
    rand_bits,
    set_mask,
    block_b: int = 128,
    interpret: bool = False,
    tail: bool = False,
):
    """Same verdict as verify_signature_sets, with the Miller loop AND
    the RLC scalar ladders running as fused Pallas VMEM kernels. The
    pair axis is padded to a lane-tile multiple with masked identity
    pairs; MSM folds and the to-affine inversions stay on the XLA path.
    With `tail=True` the product fold + final exponentiation also run
    in-kernel (ops.pallas_tail) — without it they stay on XLA."""
    g1_side, g2_side, pair_mask = miller_inputs_pallas(
        msgs_g2_aff, sigs_g2_aff, pubkeys_g1_aff, key_mask, rand_bits,
        set_mask, block_b=block_b, interpret=interpret,
    )
    return _pairs_to_verdict_pallas(
        g1_side, g2_side, pair_mask, block_b=block_b,
        interpret=interpret, tail=tail,
    )
