"""Array-native scalar-field (mod r) arithmetic for the DA plane.

`ops.fieldb` bundles BLS12-381 *base*-field values (mod p, 381 bits).
Reed-Solomon blob extension works in the *scalar* field (mod r, 255
bits) — blob polynomials have Fr coefficients and are evaluated over
roots-of-unity domains in Fr. This module is the same relaxed-limb
Montgomery machine re-parameterized for r:

- A value is an int32 array `(..., NB)`: NB = 23 limbs of 12 bits
  (22 limbs cover the 264-bit Montgomery radix, one headroom limb).
- No tower, no slot axis: Fr is prime. All leading axes are batch.

RELAXED-LIMB INVARIANT (mirrors ops.fieldb — see its docstring for the
shared machinery; only the numbers differ):

  Every bundle flowing between ops has non-negative limbs <= LIMB_RELAX
  (4097) and value < 2.3r. Exact canonical limbs/values exist only
  inside `canon`.

  Why this is sound (numbers: r = 7.2453*2^252, Montgomery radix
  R_mont = 2^264, r/R_mont = 0.0017688; the reduce_small divisor is 8,
  with per-quotient-unit error d = 8*2^252 - r = 0.7547*2^252
  = 0.10417r):
  * conv products: limbs <= 4097 give per-term products <= 4097^2 and
    column sums <= 23 * 4097^2 < 2^29 — no int32 overflow.
  * `reduce_small` subtracts q*r with q = floor(top_two_limbs / 8).
    Soundness: t2*2^252 <= x (non-negative limbs) and 8*2^252 > r, so
    q*r <= q*8*2^252 <= x. Remainder: t2 <= 8q + 7 and the relaxed low
    21 limbs contribute < 1.0005*2^252, so
    x - q*r < q*d + 8.0005*2^252 < 1.105r + 0.1042r*q.
  * Montgomery REDC carry across the 2^264 boundary: value(low 22
    limbs) is = 0 mod 2^264 and < 1.0003*2^264, so it is EXACTLY 0 or
    2^264 and the carry into the high half is `any(low != 0)`.
  * Bound closure at 2.3r:
      mul_lazy: inputs < 2.3r -> T < 5.29 r^2,
        T/R_mont < 5.29*(r/R_mont)*r = 0.0094r, output
        < 0.0094r + 1.001r < 1.02r.
      add: x < 4.6r = 33.4*2^252 -> q <= 4 -> out < 1.53r.
      sub: x < 2.3r + 32r < 34.3r = 248.6*2^252 -> q <= 31 -> first
        reduce_small gives < 1.105r + 3.23r = 4.34r, so it reduces
        TWICE; second pass input < 4.34r = 31.5*2^252 -> q <= 3 ->
        out < 1.42r.
    Everything stays < 1.53r < 2.3r, with wide margin (verified
    adversarially in tests/test_da_plane.py).
  * SPREAD_SUB (value 32r) spreads its 2-unit limb offsets over limbs
    0..20 ONLY: any invariant-satisfying value (< 2.3r < 17*2^252,
    non-negative limbs) has limb 22 == 0 and limb 21 <= 16, so the
    spread constant needs no headroom above limb 21 — its own limb 21
    (floor(32r/2^252) - 2 = 229) absorbs the largest possible b limb.

Parity note: the reference client does Fr arithmetic for erasure
coding inside c-kzg-4844 / rust-eth-kzg; this is that plane re-laid-out
for VPU execution behind the guarded `rs_extend` dispatch.
"""

import numpy as np

import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import LIMB_BITS, LIMB_MASK, R

NLIMBS = 22  # Montgomery radix limbs: 2^264 (22 * 12) > 2^255 > r
NB = NLIMBS + 1  # bundle limb count (one headroom limb -> 2^276)
_TOP = NB - 1
LIMB_RELAX = LIMB_MASK + 2  # relaxed limb bound (4097)

_R_MONT = 1 << (LIMB_BITS * NLIMBS)  # 2^264
MONT_ONE = _R_MONT % R
MONT_R2 = (_R_MONT * _R_MONT) % R

_NPRIME_INT = (-pow(R, -1, _R_MONT)) % _R_MONT


def _limbs(v: int, n: int) -> np.ndarray:
    return np.array(
        [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(n)],
        dtype=np.int32,
    )


NPRIME_LIMBS = _limbs(_NPRIME_INT, NLIMBS)
R_LIMBS32 = _limbs(R, NLIMBS)

ZERO_B = np.zeros(NB, dtype=np.int32)
ONE_MONT_B = _limbs(MONT_ONE, NB)
R2_B = _limbs(MONT_R2, NB)

# 2^276 - r: adding q copies == subtracting q*r mod 2^276.
COMP_R = _limbs((1 << (LIMB_BITS * NB)) - R, NB)
# Canonicalization cond-subtract constants (values < 2.3r need one
# conditional -2r then one conditional -r).
COMP_2R = _limbs((1 << (LIMB_BITS * NB)) - 2 * R, NB)

# Subtraction constant: value 32r, limbs spread by two units over limbs
# 0..20 so a - b + SPREAD_SUB has non-negative limbs for any
# relaxed-limb b satisfying the invariant (b limb 21 <= 16, limb 22
# == 0 — see module docstring). Value headroom: a - b + 32r < 34.3r
# keeps reduce_small's q <= 31.
SPREAD_SUB = _limbs(32 * R, NB)
for _i in range(NB - 2):
    SPREAD_SUB[_i] += 2 << LIMB_BITS
    SPREAD_SUB[_i + 1] -= 2
assert SPREAD_SUB.min() >= 0
assert SPREAD_SUB[: NB - 2].min() >= LIMB_RELAX
assert SPREAD_SUB[NB - 2] >= 18 and SPREAD_SUB[NB - 1] == 0
# Invariant premise for the limb-0..20-only spread: 2.3r < 17*2^252.
assert 23 * R < 170 * (1 << 252)

# Convolution masks (i + j == k), full and low-truncated.
_CONV_FULL = np.zeros((NB, NB, 2 * NB - 1), dtype=np.int32)
for _i in range(NB):
    for _j in range(NB):
        _CONV_FULL[_i, _j, _i + _j] = 1
_CONV_LOW = np.zeros((NLIMBS, NLIMBS, NLIMBS), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        if _i + _j < NLIMBS:
            _CONV_LOW[_i, _j, _i + _j] = 1
_CONV_MR = np.zeros((NLIMBS, NLIMBS, 2 * NLIMBS - 1), dtype=np.int32)
for _i in range(NLIMBS):
    for _j in range(NLIMBS):
        _CONV_MR[_i, _j, _i + _j] = 1


# ----------------------------------------------------------- carry handling


def _pad_last(x, n):
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, n)])


def _partial_pass(x):
    c = x >> LIMB_BITS
    d = x & LIMB_MASK
    return d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])


def _relax(x, out_len, passes=3):
    """Value-preserving (mod 2^(12*out_len)) relaxation to limbs <= ~4096.

    Same bound chain as ops.fieldb._relax: each pass maps limb bound L
    to 4095 + (L >> 12); three passes take any L < 2^30 to <= 4096."""
    in_len = x.shape[-1]
    if in_len < out_len:
        x = _pad_last(x, out_len - in_len)
    elif in_len > out_len:
        x = x[..., :out_len]
    for _ in range(passes):
        x = _partial_pass(x)
    return x


def _ks_resolve(x):
    """Kogge-Stone carry resolution; limbs must be < 2*4096 (unit
    carries). Returns (canonical limbs, top carry-out)."""
    g = x > LIMB_MASK
    p = x == LIMB_MASK
    shift = 1
    L = x.shape[-1]
    gg, pp = g, p
    while shift < L:
        pad = [(0, 0)] * (x.ndim - 1) + [(shift, 0)]
        gg_prev = jnp.pad(gg[..., :-shift], pad)
        pp_prev = jnp.pad(pp[..., :-shift], pad)
        gg = gg | (pp & gg_prev)
        pp = pp & pp_prev
        shift *= 2
    carry_in = jnp.pad(
        gg[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)]
    ).astype(jnp.int32)
    return (x + carry_in) & LIMB_MASK, gg[..., -1]


def reduce_small(x):
    """Relaxed-limbed x (NB limbs) -> value < 1.105r + 0.1042r*q_max,
    limbs <= 4096. Quotient estimate from the top two limbs against r
    (r < 8*2^252): q = (x >> 252) // 8 satisfies q*r <= x (see module
    docstring)."""
    t2 = x[..., _TOP] * (1 << LIMB_BITS) + x[..., _TOP - 1]
    q = t2 // 8
    return _relax(x + q[..., None] * jnp.asarray(COMP_R), NB)


def _cond_sub(x, comp_const):
    """Subtract the complement's value iff x >= value (exact compare).
    Input limbs must be canonical (callers resolve first)."""
    s = x + jnp.asarray(comp_const)
    c = s >> LIMB_BITS
    d = s & LIMB_MASK
    top1 = c[..., -1]
    s = d + jnp.pad(c[..., :-1], [(0, 0)] * (x.ndim - 1) + [(1, 0)])
    out, top2 = _ks_resolve(s)
    ge = (top1 + top2.astype(jnp.int32)) > 0
    return jnp.where(ge[..., None], out, x)


def canon(x):
    """Lazy value (< 2.3r) -> exact canonical [0, r), canonical limbs."""
    x, _ = _ks_resolve(x)
    x = _cond_sub(x, COMP_2R)
    return _cond_sub(x, COMP_R)


# ------------------------------------------------------------- multiplies


def mul_lazy(a, b):
    """Batched Montgomery product: (..., NB) x (..., NB) -> (..., NB);
    inputs < 2.3r relaxed, output < 1.02r, limbs <= LIMB_RELAX."""
    t = _relax(
        jnp.einsum(
            "...ij,ijk->...k",
            a[..., :, None] * b[..., None, :],
            jnp.asarray(_CONV_FULL),
        ),
        2 * NB,
    )
    t_low = t[..., :NLIMBS]
    m = _relax(
        jnp.einsum(
            "...ij,ijk->...k",
            t_low[..., :, None] * jnp.asarray(NPRIME_LIMBS)[None, :],
            jnp.asarray(_CONV_LOW),
        ),
        NLIMBS,
    )
    mr = jnp.einsum(
        "...ij,ijk->...k",
        m[..., :, None] * jnp.asarray(R_LIMBS32)[None, :],
        jnp.asarray(_CONV_MR),
    )
    full = _relax(t + _pad_last(mr, 2 * NB - mr.shape[-1]), 2 * NB)
    # REDC carry across the 2^264 boundary: value(low 22 limbs) is
    # exactly 0 or 2^264, so the carry is any(low != 0).
    low_nonzero = jnp.any(full[..., :NLIMBS] != 0, axis=-1)
    out = full[..., NLIMBS : NLIMBS + NB]
    return out.at[..., 0].add(low_nonzero.astype(jnp.int32))


def sqr_lazy(a):
    return mul_lazy(a, a)


# ------------------------------------------------------------ add / sub


def add(a, b):
    return reduce_small(_partial_pass(a + b))


def sub(a, b):
    s = a - b + jnp.asarray(SPREAD_SUB)
    # 34.3r input needs two quotient-estimate passes (see docstring).
    return reduce_small(reduce_small(_relax(s, NB, passes=2)))


def neg(a):
    return sub(jnp.zeros_like(a), a)


# ------------------------------------------------------------- predicates


def is_zero(a):
    return jnp.all(canon(a) == 0, axis=-1)


def eq(a, b):
    return jnp.all(canon(a) == canon(b), axis=-1)


# --------------------------------------------------------- host converters


def pack_ints(values) -> np.ndarray:
    """Host: list of ints -> (len, NB) canonical limb bundle (plain
    domain, values reduced mod r)."""
    return np.stack([_limbs(v % R, NB) for v in values])


def unpack_ints(bundle) -> list:
    out = []
    arr = np.asarray(bundle)
    flat = arr.reshape(-1, arr.shape[-1])
    for row in flat:
        acc = 0
        for i, limb in enumerate(row):
            acc += int(limb) << (LIMB_BITS * i)
        out.append(acc % R)
    return out


def to_mont(a):
    return mul_lazy(a, jnp.broadcast_to(jnp.asarray(R2_B), a.shape))


def from_mont(a):
    one = jnp.zeros_like(a).at[..., 0].set(1)
    return canon(mul_lazy(a, one))
