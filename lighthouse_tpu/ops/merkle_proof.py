"""Batched device Merkle-proof plane: lane-parallel SHA-256 branch folds.

Role: generalize the incremental tree-hash substrate into a PROOF
engine for the light-client serving plane (ROADMAP "Light-client
serving + a device Merkle-proof plane"). The host side gathers sibling
paths out of retained chunk-tree layers (ssz/gindex.TreeOracle); the
device side folds thousands of (leaf, branch, gindex) queries to roots
in one dispatch — each fold level is two SHA-256 compressions per lane,
vectorized over all lanes on the VPU.

Discipline (the established ops conventions):

  * SHA-256 is computed in uint32 exactly — device results are
    BYTE-IDENTICAL to the hashlib host oracle (`fold_branches_host`),
    enforced by the committed conformance vectors
    (tests/vectors/merkle_proof + tests/test_conformance_vectors.py);
  * bucketed dispatch: queries are grouped by branch depth (a static
    trace dimension) and lane counts are padded to power-of-two
    buckets, so the jit cache holds one executable per
    (depth, lane-bucket) instead of one per request shape;
  * jit objects live in the module-level `_JITTED` cache (the jit-cache
    lint rule), and the traced kernels are pure — no clocks, no host
    syncs, no env reads;
  * every batch is priced through `device_attribution.note_batch`
    under plane="merkle_proof", and the public entry points require an
    explicit ``consumer=`` (consumer-label lint).
"""

import time

import numpy as np

from lighthouse_tpu.common import device_attribution as attribution
from lighthouse_tpu.device_plane import GUARD, host_device_scope

# one jitted fold kernel per branch depth; jax retraces per lane bucket
# inside each entry (bounded by the pow2 padding)
_JITTED: dict = {}

MIN_LANE_BUCKET = 8

# FIPS 180-4 round constants / initial state
_SHA_K = (
    0x428A2F98, 0x71374491, 0xB5C0FBCF, 0xE9B5DBA5,
    0x3956C25B, 0x59F111F1, 0x923F82A4, 0xAB1C5ED5,
    0xD807AA98, 0x12835B01, 0x243185BE, 0x550C7DC3,
    0x72BE5D74, 0x80DEB1FE, 0x9BDC06A7, 0xC19BF174,
    0xE49B69C1, 0xEFBE4786, 0x0FC19DC6, 0x240CA1CC,
    0x2DE92C6F, 0x4A7484AA, 0x5CB0A9DC, 0x76F988DA,
    0x983E5152, 0xA831C66D, 0xB00327C8, 0xBF597FC7,
    0xC6E00BF3, 0xD5A79147, 0x06CA6351, 0x14292967,
    0x27B70A85, 0x2E1B2138, 0x4D2C6DFC, 0x53380D13,
    0x650A7354, 0x766A0ABB, 0x81C2C92E, 0x92722C85,
    0xA2BFE8A1, 0xA81A664B, 0xC24B8B70, 0xC76C51A3,
    0xD192E819, 0xD6990624, 0xF40E3585, 0x106AA070,
    0x19A4C116, 0x1E376C08, 0x2748774C, 0x34B0BCB5,
    0x391C0CB3, 0x4ED8AA4A, 0x5B9CCA4F, 0x682E6FF3,
    0x748F82EE, 0x78A5636F, 0x84C87814, 0x8CC70208,
    0x90BEFFFA, 0xA4506CEB, 0xBEF9A3F7, 0xC67178F2,
)
_SHA_IV = (
    0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
    0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19,
)


def _rotr(x, r: int):
    return (x >> r) | (x << (32 - r))


def _compress(jax, jnp, state, w16):
    """One SHA-256 compression: `state` (L, 8) uint32, `w16` (L, 16)
    uint32 message words. The 48 schedule extensions and the 64 rounds
    run as `fori_loop`s (the chain is inherently sequential; lanes are
    the parallel axis), keeping the traced graph — and the compile —
    small. All arithmetic wraps mod 2^32 in uint32: exact,
    byte-identical to the scalar reference."""
    kconst = jnp.asarray(_SHA_K, dtype=jnp.uint32)

    def sched_body(i, w):
        w15 = w[:, i - 15]
        w2 = w[:, i - 2]
        s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> 3)
        s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> 10)
        return w.at[:, i].set(w[:, i - 16] + s0 + w[:, i - 7] + s1)

    w = jnp.concatenate(
        [w16, jnp.zeros((w16.shape[0], 48), dtype=jnp.uint32)], axis=1
    )
    w = jax.lax.fori_loop(16, 64, sched_body, w)

    def round_body(i, vs):
        a, b, c, d, e, f, g, h = vs
        s1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
        ch = (e & f) ^ (~e & g)
        t1 = h + s1 + ch + kconst[i] + w[:, i]
        s0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
        maj = (a & b) ^ (a & c) ^ (b & c)
        t2 = s0 + maj
        return (t1 + t2, a, b, c, d + t1, e, f, g)

    vs = tuple(state[:, i] for i in range(8))
    vs = jax.lax.fori_loop(0, 64, round_body, vs)
    return jnp.stack(vs, axis=-1) + state


def _hash_pair(jax, jnp, left, right):
    """SHA-256 of a 64-byte (left || right) message, as words: two
    compressions — the message block, then the fixed padding block
    (0x80, zeros, bit length 512)."""
    block1 = jnp.concatenate([left, right], axis=-1)
    st = _compress(
        jax,
        jnp,
        jnp.broadcast_to(
            jnp.asarray(_SHA_IV, dtype=jnp.uint32), left.shape
        ),
        block1,
    )
    lanes = left.shape[0]
    pad = jnp.broadcast_to(
        jnp.asarray(
            (0x80000000,) + (0,) * 14 + (512,), dtype=jnp.uint32
        ),
        (lanes, 16),
    )
    return _compress(jax, jnp, st, pad)


def _fold_kernel(jax, jnp, depth: int):
    """Kernel folding (L, 8) leaves up `depth` levels of (L, depth, 8)
    siblings; `dirbits[:, d] == 1` means the running node is the RIGHT
    child at level d."""

    def run(leaves, siblings, dirbits):
        node = leaves
        for d in range(depth):
            sib = siblings[:, d, :]
            is_right = (dirbits[:, d : d + 1] != 0)
            left = jnp.where(is_right, sib, node)
            right = jnp.where(is_right, node, sib)
            node = _hash_pair(jax, jnp, left, right)
        return node

    return run


def _get_jitted(depth: int):
    fn = _JITTED.get(depth)
    if fn is None:
        import jax
        from jax import numpy as jnp

        _JITTED[depth] = jax.jit(_fold_kernel(jax, jnp, depth))
        fn = _JITTED[depth]
    return fn


# ------------------------------------------------------------- host side


def _words(chunks) -> np.ndarray:
    """list of 32-byte chunks -> (n, 8) uint32 big-endian words."""
    return np.frombuffer(
        b"".join(bytes(c) for c in chunks), dtype=">u4"
    ).reshape(-1, 8).astype(np.uint32)


def _chunks(words: np.ndarray) -> list:
    data = np.asarray(words, dtype=np.uint32).astype(">u4").tobytes()
    return [data[i : i + 32] for i in range(0, len(data), 32)]


def _lane_bucket(n: int) -> int:
    b = MIN_LANE_BUCKET
    while b < n:
        b <<= 1
    return b


def fold_branches_host(queries) -> list:
    """hashlib oracle: [(leaf, branch, gindex)] -> computed roots."""
    import hashlib

    out = []
    for leaf, branch, gindex in queries:
        node = bytes(leaf)
        g = int(gindex)
        for sibling in branch:
            if g & 1:
                node = hashlib.sha256(bytes(sibling) + node).digest()
            else:
                node = hashlib.sha256(node + bytes(sibling)).digest()
            g >>= 1
        out.append(node)
    return out


def batch_merkle_roots(queries, consumer=None) -> list:
    """Fold many (leaf, branch, gindex) queries to roots on device, in
    per-depth lane-bucketed dispatches. Returns the computed roots in
    query order — byte-identical to `fold_branches_host`."""
    queries = list(queries)
    if not queries:
        return []
    by_depth: dict = {}
    for pos, (leaf, branch, gindex) in enumerate(queries):
        if len(branch) != int(gindex).bit_length() - 1:
            raise ValueError(
                f"query {pos}: branch length {len(branch)} does not "
                f"match gindex {gindex} depth"
            )
        by_depth.setdefault(len(branch), []).append(
            (pos, bytes(leaf), branch, int(gindex))
        )
    out: list = [None] * len(queries)
    for depth, group in sorted(by_depth.items()):
        n = len(group)
        if depth == 0:
            for pos, leaf, _branch, _g in group:
                out[pos] = leaf
            continue
        bucket = _lane_bucket(n)
        leaves = np.zeros((bucket, 8), dtype=np.uint32)
        siblings = np.zeros((bucket, depth, 8), dtype=np.uint32)
        dirbits = np.zeros((bucket, depth), dtype=np.uint32)
        leaves[:n] = _words([leaf for _, leaf, _, _ in group])
        for i, (_pos, _leaf, branch, gindex) in enumerate(group):
            siblings[i] = _words(branch)
            for d in range(depth):
                dirbits[i, d] = (gindex >> d) & 1
        fn = _get_jitted(depth)

        # A fold yields root bytes, not a verdict — flip injection is a
        # no-op here (like the kzg MSM); stall/error/timeout still fail
        # over. The host tier is the committed hashlib oracle, so the
        # byte-identical contract holds on every tier.
        def device_attempt(plan):
            return _chunks(np.asarray(fn(leaves, siblings, dirbits))[:n])

        def xla_host_tier():
            with host_device_scope():
                return _chunks(
                    np.asarray(fn(leaves, siblings, dirbits))[:n]
                )

        def ref_tier():
            return fold_branches_host(
                [(leaf, branch, g) for _pos, leaf, branch, g in group]
            )

        t0 = time.perf_counter()
        chunks = GUARD.dispatch(
            "merkle_proof",
            f"d{depth}x{bucket}",
            device_attempt,
            fallbacks=[("xla-host", xla_host_tier), ("ref", ref_tier)],
        )
        wall = time.perf_counter() - t0
        attribution.note_batch(
            consumer,
            "merkle_proof",
            lanes=bucket,
            live=n,
            duration_s=wall,
        )
        for (pos, _leaf, _branch, _g), root in zip(group, chunks):
            out[pos] = root
    return out


def batch_verify_branches(queries, roots, consumer=None) -> list:
    """Per-query verdicts: device-computed root == expected root. The
    verdict flips on any corrupted sibling/leaf/direction — the
    conformance vectors pin both polarities."""
    computed = batch_merkle_roots(queries, consumer=consumer)
    return [c == bytes(r) for c, r in zip(computed, roots)]


def batch_extract_proofs(typ, states, requests, consumer=None):
    """Batched proof extraction over many (state, generalized-index)
    queries: host-side sibling-path gathers from each state's chunk
    tree (one TreeOracle per distinct state, leaf chunks served from
    the incremental tree-hash cache when attached), then ONE device
    dispatch per depth recomputing every root as a cross-check.

    `requests` is [(state_index, gindex)]; returns
    [(leaf, branch, computed_root)] in request order."""
    from lighthouse_tpu.ssz.gindex import (
        TreeOracle,
        branch_indices,
        state_field_chunks,
    )

    oracles = {}
    queries = []
    for state_index, gindex in requests:
        oracle = oracles.get(state_index)
        if oracle is None:
            state = states[state_index]
            oracle = TreeOracle(
                typ, state, chunks_override=state_field_chunks(state)
            )
            oracles[state_index] = oracle
        leaf = oracle.node(gindex)
        branch = [oracle.node(s) for s in branch_indices(gindex)]
        queries.append((leaf, branch, gindex))
    roots = batch_merkle_roots(queries, consumer=consumer)
    return [
        (leaf, branch, root)
        for (leaf, branch, _g), root in zip(queries, roots)
    ]
