"""Batched optimal ate pairing on BLS12-381, on slot bundles.

Same math as the validated scalar implementation (see ops history /
crypto/ref_pairing): inversion-free Jacobian twist Miller loop whose line
scalings live in Fp2 (annihilated by the final exponentiation), one
`lax.scan` over the 63 fixed bits of |x|, sparse line multiplication
(ops.programs.LINE_MUL — one 13-product stacked multiply), and the
(x-1)^2 (x+p)(x^2+p^2-1)+3 final-exponentiation addition chain.

`multi_pairing_is_one` = per-pair Miller -> tree product -> ONE shared
final exponentiation, the exact structure of the reference backend's batch
verify (crypto/bls/src/impls/blst.rs:36-119).
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import BLS_X, BLS_X_ABS
from lighthouse_tpu.ops import curve, fieldb as fb, fp2, tower
from lighthouse_tpu.ops.programs import LINE_MUL

NB = fb.NB

_X_BITS = np.array([int(b) for b in bin(BLS_X_ABS)[3:]], dtype=np.int32)


def _mul_by_line(f, line):
    """f (..., 12, NB) times the sparse line (..., 6, NB)."""
    return fp2.bilinear(f, line, LINE_MUL)


def _batch_shape(f):
    return f.shape[:-2]


# ---------------------------------------------------------------- the loop


def _dbl_step(t, px, py):
    """Tangent line at Jacobian twist point t evaluated at affine
    P=(px, py) (Fp bundles), plus 2t. Line = 3X^3 - 2Y^2
    - (3 X^2 Z^2 px) w^2 + (2 Y Z^3 py) w^3 (scaled by 2YZ^3 in Fp2)."""
    X, Y, Z = t
    F = curve.F2
    l1 = F.mul(
        jnp.stack([X, Y, Z], axis=-3), jnp.stack([X, Y, Z], axis=-3)
    )
    x2, y2, z2 = l1[..., 0, :, :], l1[..., 1, :, :], l1[..., 2, :, :]
    l2 = F.mul(
        jnp.stack([x2, z2, x2], axis=-3),
        jnp.stack([X, Z, z2], axis=-3),
    )
    x3c, z3c, x2z2 = (
        l2[..., 0, :, :],
        l2[..., 1, :, :],
        l2[..., 2, :, :],
    )
    yz3 = F.mul(Y, z3c)
    c0 = F.sub(F.scalar_small(x3c, 3), F.scalar_small(y2, 2))
    c2 = F.neg(
        fb.mul_lazy(
            F.scalar_small(x2z2, 3), jnp.broadcast_to(px, x2z2.shape)
        )
    )
    c3 = fb.mul_lazy(
        F.scalar_small(yz3, 2), jnp.broadcast_to(py, yz3.shape)
    )
    line = jnp.concatenate([c0, c2, c3], axis=-2)
    return curve.G2.double(t), line


def _add_step(t, q_affine, px, py):
    """Chord line through t and affine twist q evaluated at P, plus t+q.
    Valid when q != +-t (guaranteed: the running T is a proper multiple of
    q below the group order)."""
    X1, Y1, Z1 = t
    qx, qy = q_affine
    F = curve.F2
    z1s = F.sqr(Z1)
    l2 = F.mul(
        jnp.stack([z1s, qx], axis=-3), jnp.stack([Z1, z1s], axis=-3)
    )
    z1c, qxz = l2[..., 0, :, :], l2[..., 1, :, :]
    qyz = F.mul(qy, z1c)
    theta = F.sub(qyz, Y1)
    gamma = F.sub(qxz, X1)
    z1gam = F.mul(Z1, gamma)
    l3 = F.mul(
        jnp.stack([theta, qy], axis=-3),
        jnp.stack([qx, z1gam], axis=-3),
    )
    c0 = F.sub(l3[..., 0, :, :], l3[..., 1, :, :])
    c2 = F.neg(
        fb.mul_lazy(theta, jnp.broadcast_to(px, theta.shape))
    )
    c3 = fb.mul_lazy(z1gam, jnp.broadcast_to(py, z1gam.shape))
    line = jnp.concatenate([c0, c2, c3], axis=-2)
    one = jnp.broadcast_to(jnp.asarray(curve.F2.ONE), qx.shape)
    t_next = curve.G2.add(t, (qx, qy, one))
    return t_next, line


def miller_loop(p_g1_affine, q_g2_affine, valid_mask=None):
    """Batched Miller loop f_{x,Q}(P).

    p_g1_affine: (px, py) Fp bundles (..., 1, NB), Montgomery.
    q_g2_affine: (qx, qy) Fp2 bundles (..., 2, NB).
    valid_mask: optional bool batch; False pairs contribute f = 1.
    """
    px, py = p_g1_affine
    qx, qy = q_g2_affine
    one2 = jnp.broadcast_to(jnp.asarray(curve.F2.ONE), qx.shape)
    t0 = (qx, qy, one2)
    f0 = tower.fp12_broadcast_one(px.shape[:-2])
    bits = jnp.asarray(_X_BITS)

    def step(carry, bit):
        f, t = carry
        f = tower.fp12_sqr(f)
        t, line = _dbl_step(t, px, py)
        f = _mul_by_line(f, line)
        t_add, line_add = _add_step(t, (qx, qy), px, py)
        f_add = _mul_by_line(f, line_add)
        use = jnp.broadcast_to(bit == 1, _batch_shape(f))
        t = curve.G2.select(use, t_add, t)
        f = tower.fp12_select(use, f_add, f)
        return (f, t), None

    (f, _), _ = jax.lax.scan(step, (f0, t0), bits)
    if BLS_X < 0:
        f = tower.fp12_conj(f)
    if valid_mask is not None:
        one = tower.fp12_broadcast_one(px.shape[:-2])
        f = tower.fp12_select(valid_mask, f, one)
    return f


# ------------------------------------------------------- final exponentiation


def _pow_x_abs(f):
    nbits = BLS_X_ABS.bit_length()
    bits = jnp.asarray(
        np.array(
            [(BLS_X_ABS >> i) & 1 for i in range(nbits)], dtype=np.int32
        )
    )

    def step(carry, bit):
        result, base = carry
        mult = tower.fp12_mul(result, base)
        use = jnp.broadcast_to(bit == 1, _batch_shape(result))
        result = tower.fp12_select(use, mult, result)
        base = tower.fp12_sqr(base)
        return (result, base), None

    one = tower.fp12_broadcast_one(f.shape[:-2])
    (result, _), _ = jax.lax.scan(step, (one, f), bits)
    return result


def _pow_neg_x(f):
    return tower.fp12_conj(_pow_x_abs(f))


def final_exponentiation(f):
    """f^(3 (p^12-1)/r) — addition chain validated in ref_pairing."""
    f = tower.fp12_mul(tower.fp12_conj(f), tower.fp12_inv(f))
    f = tower.fp12_mul(tower.fp12_frobenius(tower.fp12_frobenius(f)), f)
    t0 = tower.fp12_mul(_pow_neg_x(f), tower.fp12_conj(f))
    t1 = tower.fp12_mul(_pow_neg_x(t0), tower.fp12_conj(t0))
    t2 = tower.fp12_mul(_pow_neg_x(t1), tower.fp12_frobenius(t1))
    t3 = tower.fp12_mul(
        _pow_neg_x(_pow_neg_x(t2)),
        tower.fp12_mul(
            tower.fp12_frobenius(tower.fp12_frobenius(t2)),
            tower.fp12_conj(t2),
        ),
    )
    f3 = tower.fp12_mul(tower.fp12_mul(f, f), f)
    return tower.fp12_mul(t3, f3)


# ------------------------------------------------------------- entry points


def pairing(p_g1_affine, q_g2_affine):
    return final_exponentiation(miller_loop(p_g1_affine, q_g2_affine))


def multi_pairing_is_one(p_g1_affine, q_g2_affine, valid_mask=None):
    """prod_i e(P_i, Q_i) == 1 over the leading pair axis, one shared
    final exponentiation."""
    f = miller_loop(p_g1_affine, q_g2_affine, valid_mask=valid_mask)
    prod = tower.fp12_product_axis(f, axis=0)
    return tower.fp12_is_one(final_exponentiation(prod))
