"""Batched optimal ate pairing on BLS12-381, on-device.

Mirrors the math of `lighthouse_tpu.crypto.ref_pairing` (the validated
ground truth) but re-derived for device execution:

- The Miller loop runs in **Jacobian twist coordinates with no field
  inversions**. The affine line through T (slope lam = 3x^2/2y resp.
  (y2-y1)/(x2-x1)) is scaled by the nonzero Fp2 factors 2*Y*Z^3 resp.
  Z1*gamma; such factors lie in a proper subfield of Fp12 and are
  annihilated by the final exponentiation, so the pairing value is
  unchanged (same argument as the w^3 scaling in ref_pairing).

      dbl line * 2YZ^3   = (3X^3 - 2Y^2) - (3 X^2 Z^2 px) w^2 + (2 Y Z^3 py) w^3
      add line * Z1*gam  = (th*x2 - y2*Z1*gam) - (th*px) w^2 + (Z1*gam*py) w^3
          with th = y2 Z1^3 - Y1, gam = x2 Z1^2 - X1

- The loop over the 63 fixed bits of |x| is a single `lax.scan`: every step
  doubles and (mask-)adds branchlessly, so the compiled graph is one step
  long. Pairs are batched along leading axes; infinity on either side is
  handled by forcing that pair's line to 1 (so it contributes nothing),
  matching ref_pairing's skip of infinity pairs.

- `multi_pairing_is_one` = per-pair Miller loops -> tree product ->
  ONE shared final exponentiation, the exact structure of the reference
  backend's batch verify (crypto/bls/src/impls/blst.rs:36-119, one
  multi-pairing for the whole signature-set batch).

Sparse Fp12 line multiplication (only the w^0, w^2, w^3 tower slots are
nonzero) is exploited in `_mul_by_line`.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import BLS_X, BLS_X_ABS
from lighthouse_tpu.ops import curve, fp, fp2, tower

# Bits of |x| after the leading one, MSB-first (static loop program).
_X_BITS = np.array(
    [int(b) for b in bin(BLS_X_ABS)[3:]], dtype=np.int32
)


# ------------------------------------------------------------- line algebra


def _line_elements(c0, c2, c3):
    """Assemble the sparse Fp12 line (w^0: Fp2, w^2: Fp2, w^3: Fp2).

    Tower slots: w^2 = v -> (part0, v^1); w^3 = w*v -> (part1, v^1).
    """
    return (c0, c2, c3)


def _mul_by_line(f, line):
    """f * (c0 + c2 w^2 + c3 w^3) exploiting sparsity.

    The line as a full Fp12 element is ((c0, c2, 0), (0, c3, 0)) over
    Fp6 = Fp2 + Fp2 v + Fp2 v^2, Fp12 = Fp6 + Fp6 w. We expand the
    Karatsuba fp12_mul with b0 = (c0, c2, 0), b1 = (0, c3, 0).
    """
    c0, c2, c3 = line
    b0 = (c0, c2, fp2_zero_like(c0))
    b1 = (fp2_zero_like(c0), c3, fp2_zero_like(c0))
    return tower.fp12_mul(f, (b0, b1))


def fp2_zero_like(a):
    return jax.tree_util.tree_map(jnp.zeros_like, a)


def _line_one_like(c0):
    one = fp2.broadcast_const(fp2.ONE_MONT, c0[0])
    zero = fp2_zero_like(c0)
    return (one, zero, zero)


# ---------------------------------------------------------------- the loop


def _dbl_step(t, px, py):
    """Tangent line at Jacobian twist point t, evaluated at affine P=(px,py)
    (Fp Montgomery limbs), and the doubled point. No inversions."""
    X, Y, Z = t
    x2 = fp2.sqr(X)
    x3 = fp2.mul(x2, X)
    y2 = fp2.sqr(Y)
    z2 = fp2.sqr(Z)
    z3 = fp2.mul(z2, Z)
    yz3 = fp2.mul(Y, z3)
    c0 = fp2.sub(fp2.scalar_small(x3, 3), fp2.scalar_small(y2, 2))
    c2 = fp2.neg(fp2.mul_fp(fp2.scalar_small(fp2.mul(x2, z2), 3), px))
    c3 = fp2.mul_fp(fp2.scalar_small(yz3, 2), py)
    t_next = curve.G2.double(t)
    return t_next, _line_elements(c0, c2, c3)


def _add_step(t, q_affine, px, py):
    """Chord line through t and the affine twist point q, evaluated at P,
    plus t + q. No inversions; q must not equal +-t (guaranteed in the
    Miller loop for points of odd prime order r since the running T is
    always a proper multiple of q in (1, r))."""
    X1, Y1, Z1 = t
    qx, qy = q_affine
    z1s = fp2.sqr(Z1)
    z1c = fp2.mul(z1s, Z1)
    theta = fp2.sub(fp2.mul(qy, z1c), Y1)
    gamma = fp2.sub(fp2.mul(qx, z1s), X1)
    z1gam = fp2.mul(Z1, gamma)
    c0 = fp2.sub(fp2.mul(theta, qx), fp2.mul(qy, z1gam))
    c2 = fp2.neg(fp2.mul_fp(theta, px))
    c3 = fp2.mul_fp(z1gam, py)
    q_jac = (qx, qy, fp2.broadcast_const(fp2.ONE_MONT, qx[0]))
    t_next = curve.G2.add(t, q_jac)
    return t_next, _line_elements(c0, c2, c3)


def miller_loop(p_g1_affine, q_g2_affine, valid_mask=None):
    """Batched Miller loop f_{x,Q}(P) over pairs of affine points.

    p_g1_affine: (px, py) Fp limb arrays (Montgomery), batched.
    q_g2_affine: (qx, qy) Fp2 tuples (Montgomery), batched.
    valid_mask:  optional bool batch; False pairs contribute f = 1
                 (the analog of ref_pairing skipping infinity pairs).

    Returns a batched Fp12 value (one per pair, before final exp).
    """
    px, py = p_g1_affine
    qx, qy = q_g2_affine
    t0 = (qx, qy, fp2.broadcast_const(fp2.ONE_MONT, qx[0]))
    f0 = tower.fp12_broadcast_one(px)

    bits = jnp.asarray(_X_BITS)

    def step(carry, bit):
        f, t = carry
        f = tower.fp12_sqr(f)
        t, line = _dbl_step(t, px, py)
        f = _mul_by_line(f, line)
        t_add, line_add = _add_step(t, (qx, qy), px, py)
        f_add = _mul_by_line(f, line_add)
        use_add = bit == 1
        t = curve.G2.select(
            jnp.broadcast_to(use_add, tower_batch_shape(f)), t_add, t
        )
        f = tower.fp12_select(
            jnp.broadcast_to(use_add, tower_batch_shape(f)), f_add, f
        )
        return (f, t), None

    (f, _), _ = jax.lax.scan(step, (f0, t0), bits)
    if BLS_X < 0:
        f = tower.fp12_conj(f)
    if valid_mask is not None:
        one = tower.fp12_broadcast_one(px)
        f = tower.fp12_select(valid_mask, f, one)
    return f


def tower_batch_shape(f):
    return jax.tree_util.tree_leaves(f)[0].shape[:-1]


# ------------------------------------------------------- final exponentiation


def _pow_x_abs(f):
    """f^|x| via one lax.scan over the fixed 64-bit parameter (LSB-first
    square-and-multiply with masked multiplies, as fp._pow_const)."""
    nbits = BLS_X_ABS.bit_length()
    bits = jnp.asarray(
        np.array([(BLS_X_ABS >> i) & 1 for i in range(nbits)], dtype=np.int32)
    )

    def step(carry, bit):
        result, base = carry
        mult = tower.fp12_mul(result, base)
        result = tower.fp12_select(
            jnp.broadcast_to(bit == 1, tower_batch_shape(result)),
            mult,
            result,
        )
        base = tower.fp12_sqr(base)
        return (result, base), None

    one = tower.fp12_broadcast_one(jax.tree_util.tree_leaves(f)[0])
    (result, _), _ = jax.lax.scan(step, (one, f), bits)
    return result


def _pow_neg_x(f):
    """f^x for the (negative) BLS parameter."""
    return tower.fp12_conj(_pow_x_abs(f))


def final_exponentiation(f):
    """f^(3*(p^12-1)/r) — same addition chain as ref_pairing (validated
    there against the integer exponent)."""
    f = tower.fp12_mul(tower.fp12_conj(f), tower.fp12_inv(f))
    f = tower.fp12_mul(
        tower.fp12_frobenius(tower.fp12_frobenius(f)), f
    )
    t0 = tower.fp12_mul(_pow_neg_x(f), tower.fp12_conj(f))
    t1 = tower.fp12_mul(_pow_neg_x(t0), tower.fp12_conj(t0))
    t2 = tower.fp12_mul(_pow_neg_x(t1), tower.fp12_frobenius(t1))
    t3 = tower.fp12_mul(
        _pow_neg_x(_pow_neg_x(t2)),
        tower.fp12_mul(
            tower.fp12_frobenius(tower.fp12_frobenius(t2)),
            tower.fp12_conj(t2),
        ),
    )
    f3 = tower.fp12_mul(tower.fp12_mul(f, f), f)
    return tower.fp12_mul(t3, f3)


# ------------------------------------------------------------- entry points


def pairing(p_g1_affine, q_g2_affine):
    """Full pairing e(P, Q), batched."""
    return final_exponentiation(miller_loop(p_g1_affine, q_g2_affine))


def multi_pairing_is_one(p_g1_affine, q_g2_affine, valid_mask=None):
    """prod_i e(P_i, Q_i) == 1 with one shared final exponentiation.

    The pair axis is the leading batch axis; returns a scalar bool (or a
    batch of bools if there are extra leading axes before the pair axis).
    """
    f = miller_loop(p_g1_affine, q_g2_affine, valid_mask=valid_mask)
    prod = tower.fp12_product_axis(f, axis=0)
    return tower.fp12_is_one(final_exponentiation(prod))
