"""Batched optimal ate pairing on BLS12-381, on slot bundles.

Same math as the validated scalar implementation (see ops history /
crypto/ref_pairing): inversion-free Jacobian twist Miller loop whose line
scalings live in Fp2 (annihilated by the final exponentiation), one
`lax.scan` over the 63 fixed bits of |x|, sparse line multiplication
(ops.programs.LINE_MUL — one 13-product stacked multiply), and the
(x-1)^2 (x+p)(x^2+p^2-1)+3 final-exponentiation addition chain.

`multi_pairing_is_one` = per-pair Miller -> tree product -> ONE shared
final exponentiation, the exact structure of the reference backend's batch
verify (crypto/bls/src/impls/blst.rs:36-119).
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import BLS_X, BLS_X_ABS, P
from lighthouse_tpu.crypto.constants import R as R_SUBGROUP
from lighthouse_tpu.ops import curve, fieldb as fb, fp2, tower
from lighthouse_tpu.ops.programs import LINE_MUL

NB = fb.NB

_X_BITS = np.array([int(b) for b in bin(BLS_X_ABS)[3:]], dtype=np.int32)


def _mul_by_line(f, line):
    """f (..., 12, NB) times the sparse line (..., 6, NB)."""
    return fp2.bilinear(f, line, LINE_MUL)


def _batch_shape(f):
    return f.shape[:-2]


# ---------------------------------------------------------------- the loop
#
# The doubling/addition steps fuse the line computation with the point
# update — they share nearly all intermediates — and batch every stage's
# independent Fp2 products into one stacked program call and every stage's
# linear recombination into one apply_combo. This keeps the scan body a few
# hundred equations instead of tens of thousands (the unified G2.add path).


def _mul2(pairs):
    """One stacked Fp2 multiply for a list of (a, b) bundle pairs."""
    A = jnp.stack([a for a, _ in pairs], axis=-3)
    B = jnp.stack([b for _, b in pairs], axis=-3)
    out = curve.F2.mul(A, B)
    return [out[..., i, :, :] for i in range(len(pairs))]


def _combo2(vals, coeffs):
    """One apply_combo over a list of Fp2 bundles; `coeffs` is an
    (n_out, n_in) integer matrix acting Fp2-componentwise."""
    x = jnp.concatenate(vals, axis=-2)
    # lint: allow(device-purity): coeffs is a static integer matrix
    m = np.kron(np.asarray(coeffs, dtype=np.int64), np.eye(2, dtype=np.int64))
    y = fb.apply_combo(x, m.astype(np.int32))
    return [y[..., 2 * i : 2 * i + 2, :] for i in range(coeffs.shape[0])]


def _line_scale(ca, cb, px, py):
    """(ca*px, cb*py) as one 4-slot raw multiply (Fp scalar acting
    componentwise on Fp2)."""
    lhs = jnp.concatenate([ca, cb], axis=-2)
    rhs = jnp.concatenate(
        [jnp.broadcast_to(px, ca.shape), jnp.broadcast_to(py, cb.shape)],
        axis=-2,
    )
    out = fb.mul_lazy(lhs, rhs)
    return out[..., 0:2, :], out[..., 2:4, :]


def _dbl_step(t, px, py):
    """Fused tangent-line + doubling. Line = 3X^3 - 2Y^2
    - (3 X^2 Z^2 px) w^2 + (2 Y Z^3 py) w^3 (scaled by 2YZ^3 in Fp2);
    point update is dbl-2001-b (a = X^2, b = Y^2, c = b^2,
    d = 2((X+b)^2 - a - c), e = 3a, f = e^2)."""
    X, Y, Z = t
    a, b, z2, yz = _mul2([(X, X), (Y, Y), (Z, Z), (Y, Z)])
    xb, e = _combo2(
        [X, a, b],
        np.array([[1, 0, 1], [0, 3, 0]]),
    )
    c, xb2, f, x3c, x2z2, yz3 = _mul2(
        [(b, b), (xb, xb), (e, e), (X, a), (a, z2), (yz, z2)]
    )
    # rows over [xb2, a, c, f, x3c, b, x2z2, yz3, yz]:
    #   d    = 2 xb2 - 2a - 2c
    #   x3   = f - 2d = f - 4 xb2 + 4a + 4c
    #   dmx  = d - x3 = 6 xb2 - 6a - 6c - f
    #   c0   = 3 x3c - 2b
    #   m3xz = -3 x2z2          (line w^2 coefficient, pre-px)
    #   c3p  = 2 yz3            (line w^3 coefficient, pre-py)
    #   z3   = 2 yz
    x3, dmx, c0, m3xz, c3p, z3 = _combo2(
        [xb2, a, c, f, x3c, b, x2z2, yz3, yz],
        np.array(
            [
                [-4, 4, 4, 1, 0, 0, 0, 0, 0],
                [6, -6, -6, -1, 0, 0, 0, 0, 0],
                [0, 0, 0, 0, 3, -2, 0, 0, 0],
                [0, 0, 0, 0, 0, 0, -3, 0, 0],
                [0, 0, 0, 0, 0, 0, 0, 2, 0],
                [0, 0, 0, 0, 0, 0, 0, 0, 2],
            ]
        ),
    )
    (edmx,) = _mul2([(e, dmx)])
    c2, c3 = _line_scale(m3xz, c3p, px, py)
    (y3,) = _combo2([edmx, c], np.array([[1, -8]]))
    line = jnp.concatenate([c0, c2, c3], axis=-2)
    return (x3, y3, z3), line


def _add_step(t, q_affine, px, py):
    """Fused chord-line + mixed addition (affine q, Z2 = 1). Valid when
    q != +-t and t is finite (guaranteed: the running T is a proper
    multiple of q below the group order). theta/gamma are the chord
    slope numerator/denominator; the point update is the classic
    X3 = theta^2 - gamma^3 - 2 X1 gamma^2 family with Z3 = Z1*gamma."""
    X1, Y1, Z1 = t
    qx, qy = q_affine
    (z1s,) = _mul2([(Z1, Z1)])
    u2, z1c = _mul2([(qx, z1s), (z1s, Z1)])
    (gamma,) = _combo2([u2, X1], np.array([[1, -1]]))
    qyz, hh, z1gam = _mul2([(qy, z1c), (gamma, gamma), (Z1, gamma)])
    (theta,) = _combo2([qyz, Y1], np.array([[1, -1]]))
    tt, hhh, v, tqx, qyz3 = _mul2(
        [(theta, theta), (gamma, hh), (X1, hh), (theta, qx), (qy, z1gam)]
    )
    # rows over [tt, hhh, v, tqx, qyz3, theta]:
    #   x3     = tt - hhh - 2v
    #   vmx    = v - x3 = -tt + hhh + 3v
    #   c0     = tqx - qyz3
    #   mtheta = -theta         (line w^2 coefficient, pre-px)
    x3, vmx, c0, mtheta = _combo2(
        [tt, hhh, v, tqx, qyz3, theta],
        np.array(
            [
                [1, -1, -2, 0, 0, 0],
                [-1, 1, 3, 0, 0, 0],
                [0, 0, 0, 1, -1, 0],
                [0, 0, 0, 0, 0, -1],
            ]
        ),
    )
    tvmx, y1hhh = _mul2([(theta, vmx), (Y1, hhh)])
    c2, c3 = _line_scale(mtheta, z1gam, px, py)
    (y3,) = _combo2([tvmx, y1hhh], np.array([[1, -1]]))
    line = jnp.concatenate([c0, c2, c3], axis=-2)
    return (x3, y3, z1gam), line


def miller_loop(p_g1_affine, q_g2_affine, valid_mask=None):
    """Batched Miller loop f_{x,Q}(P).

    p_g1_affine: (px, py) Fp bundles (..., 1, NB), Montgomery.
    q_g2_affine: (qx, qy) Fp2 bundles (..., 2, NB).
    valid_mask: optional bool batch; False pairs contribute f = 1.
    """
    px, py = p_g1_affine
    qx, qy = q_g2_affine
    one2 = jnp.broadcast_to(jnp.asarray(curve.F2.ONE), qx.shape)
    t0 = (qx, qy, one2)
    f0 = tower.fp12_broadcast_one(px.shape[:-2])
    bits = jnp.asarray(_X_BITS)

    def step(carry, bit):
        f, t = carry
        f = tower.fp12_sqr(f)
        t, line = _dbl_step(t, px, py)
        f = _mul_by_line(f, line)

        # `bit` is a SCALAR from the static exponent |x| (Hamming weight 6),
        # so this cond is a real branch: the add-step only runs on the 5
        # set bits after the leading one, not all 63 iterations.
        def do_add(op):
            f_, t_ = op
            t_next, line_add = _add_step(t_, (qx, qy), px, py)
            return _mul_by_line(f_, line_add), t_next

        f, t = jax.lax.cond(bit == 1, do_add, lambda op: op, (f, t))
        return (f, t), None

    (f, _), _ = jax.lax.scan(step, (f0, t0), bits)
    if BLS_X < 0:
        f = tower.fp12_conj(f)
    if valid_mask is not None:
        one = tower.fp12_broadcast_one(px.shape[:-2])
        f = tower.fp12_select(valid_mask, f, one)
    return f


# ------------------------------------------------------- final exponentiation


def _pow_x_abs(f):
    nbits = BLS_X_ABS.bit_length()
    bits = jnp.asarray(
        np.array(
            [(BLS_X_ABS >> i) & 1 for i in range(nbits)], dtype=np.int32
        )
    )

    def step(carry, bit):
        result, base = carry
        # scalar static-exponent bit -> real branch (|x| Hamming weight 6)
        result = jax.lax.cond(
            bit == 1,
            lambda rb: tower.fp12_mul(rb[0], rb[1]),
            lambda rb: rb[0],
            (result, base),
        )
        base = tower.fp12_sqr(base)
        return (result, base), None

    one = tower.fp12_broadcast_one(f.shape[:-2])
    (result, _), _ = jax.lax.scan(step, (one, f), bits)
    return result


def _pow_neg_x(f):
    return tower.fp12_conj(_pow_x_abs(f))


def final_exponentiation(f):
    """f^(3 (p^12-1)/r) — addition chain validated in ref_pairing.
    Double-frobenius sites use the cheap any-element p^2-Frobenius."""
    f = tower.fp12_mul(tower.fp12_conj(f), tower.fp12_inv(f))
    f = tower.fp12_mul(tower.fp12_frobenius2(f), f)
    t0 = tower.fp12_mul(_pow_neg_x(f), tower.fp12_conj(f))
    t1 = tower.fp12_mul(_pow_neg_x(t0), tower.fp12_conj(t0))
    t2 = tower.fp12_mul(_pow_neg_x(t1), tower.fp12_frobenius(t1))
    t3 = tower.fp12_mul(
        _pow_neg_x(_pow_neg_x(t2)),
        tower.fp12_mul(
            tower.fp12_frobenius2(t2),
            tower.fp12_conj(t2),
        ),
    )
    f3 = tower.fp12_mul(tower.fp12_mul(f, f), f)
    return tower.fp12_mul(t3, f3)


# ------------------------------------------------ final-exp equality test

# Definitional oracle: f^((p^12-1)/r) == 1 by one square-and-multiply scan
# over the full exponent. ~4300 sequential Fp12 ops, so it is far slower at
# RUNTIME than the addition chain (which exploits |x|-sparsity and
# unitarity) — but its graph is a single (sqr + cond mul) scan body. Used
# by tests to validate the chain against the spec exponent.
_FE_EXP = (P**12 - 1) // R_SUBGROUP
assert (P**12 - 1) % R_SUBGROUP == 0
_FE_BITS = np.array([int(b) for b in bin(_FE_EXP)[2:]], dtype=np.int32)


def final_exp_is_one_scan(f):
    """final_exponentiation(f) == 1, computed as f^((p^12-1)/r) == 1 by a
    bit scan (MSB-first, leading bit consumed by acc0 = f)."""
    bits = jnp.asarray(_FE_BITS[1:])

    def step(acc, bit):
        acc = tower.fp12_sqr(acc)
        acc = jax.lax.cond(
            bit == 1, lambda a: tower.fp12_mul(a, f), lambda a: a, acc
        )
        return acc, None

    acc, _ = jax.lax.scan(step, f, bits)
    return tower.fp12_is_one(acc)


def final_exp_is_one(f):
    """final_exponentiation(f) == 1 via the addition chain (fast path)."""
    return tower.fp12_is_one(final_exponentiation(f))


# ------------------------------------------------------------- entry points


def pairing(p_g1_affine, q_g2_affine):
    return final_exponentiation(miller_loop(p_g1_affine, q_g2_affine))


def multi_pairing_is_one(p_g1_affine, q_g2_affine, valid_mask=None):
    """prod_i e(P_i, Q_i) == 1 over the leading pair axis, one shared
    final exponentiation. The trace/* spans attribute JAX trace time to
    the two dominant graph stages for every caller (flat, grouped,
    sharded) — they fire once per (re)compile, not per dispatch."""
    from lighthouse_tpu.common.tracing import span

    with span("trace/miller_loop"):
        f = miller_loop(p_g1_affine, q_g2_affine, valid_mask=valid_mask)
    with span("trace/final_exp"):
        prod = tower.fp12_product_axis(f, axis=0)
        return final_exp_is_one(prod)
