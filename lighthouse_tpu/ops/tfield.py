"""Transposed ("batch-last") BLS12-381 field arithmetic for TPU kernels.

Layout: a bundle is an int32 array `(S_slots, NB, B)` — slots lead, the
12-bit limb axis is second-to-last (sublanes), and the BATCH axis B is last
(lanes). With B a multiple of 128 every elementwise op runs at full VPU
lane utilization, unlike the batch-leading layout in ops.fieldb whose
33-limb trailing axis wastes 3/4 of each vector register row.

Functions here are pure jnp and run in two modes:
  * directly under jit (XLA level), via ops.tpairing;
  * inside a Pallas TPU kernel (ops.pallas_pairing), where the whole
    Miller loop stays in VMEM.

The arithmetic, bounds, and relaxed-limb invariant are IDENTICAL to
ops.fieldb (see its module docstring for the full analysis): limbs stay in
[0, LIMB_RELAX], values < 2.2p, no exact carry resolution on the hot path.
Only the data movement differs:
  * the data x data convolution unrolls over the 33 limbs of `a`
    (static-slice accumulate) instead of an einsum against a one-hot
    tensor;
  * the two static convolutions of Montgomery REDC (by N' and by p)
    unroll over STATIC scalar limbs — scalar * tensor fused multiply-adds;
  * slot recombinations unroll per output row over the (sparse, small)
    static coefficients instead of an einsum.

Parity note: behind the reference's BLS boundary
(crypto/bls/src/impls/blst.rs), alternate layout of the same plane.
"""

import functools

import numpy as np

import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import LIMB_BITS, LIMB_MASK, NLIMBS
from lighthouse_tpu.ops import fieldb as fb

NB = fb.NB
LIMB_RELAX = fb.LIMB_RELAX

_NPRIME = [int(v) for v in fb.NPRIME_LIMBS]
_PLIMBS = [int(v) for v in fb.P_LIMBS32]
_COMP_2P = [int(v) for v in fb.COMP_2P]
_OFF = [int(v) for v in fb.OFF_CONST]
_SPREAD_SUB = [int(v) for v in fb.SPREAD_SUB]


# ----------------------------------------------------------- carry handling


def _partial_pass(x):
    """One value-preserving carry pass along the limb axis (-2)."""
    c = x >> LIMB_BITS
    d = x & LIMB_MASK
    pad = [(0, 0)] * x.ndim
    pad[-2] = (1, 0)
    return d + jnp.pad(c[..., :-1, :], pad)


def _relax(x, out_len, passes=3):
    """Limbs -> <= ~4096; truncation beyond out_len is deliberate mod-R /
    mod-2^396 arithmetic (same bound chains as fieldb._relax)."""
    in_len = x.shape[-2]
    if in_len < out_len:
        pad = [(0, 0)] * x.ndim
        pad[-2] = (0, out_len - in_len)
        x = jnp.pad(x, pad)
    elif in_len > out_len:
        x = x[..., :out_len, :]
    for _ in range(passes):
        x = _partial_pass(x)
    return x


import contextlib

# Inside a Pallas kernel, captured array constants are not allowed — the
# kernel passes them as inputs and installs them here for the duration of
# its trace (see ops.pallas_miller). Keys: "off", "spread_sub", "comp_2p",
# "one".
_CONST_OVERRIDES: dict = {}


_MISSING = object()


@contextlib.contextmanager
def const_overrides(**cols):
    """Reentrant: saves and restores any previously-installed value per
    key, so nested kernel traces cannot leak each other's tracers."""
    prev = {k: _CONST_OVERRIDES.get(k, _MISSING) for k in cols}
    _CONST_OVERRIDES.update(cols)
    try:
        yield
    finally:
        for k, v in prev.items():
            if v is _MISSING:
                _CONST_OVERRIDES.pop(k, None)
            else:
                _CONST_OVERRIDES[k] = v


def _const_col(limbs, name=None):
    """Static limb list -> (len, 1) column broadcastable over (..., L, B);
    an installed override (a traced in-kernel value) takes precedence."""
    if name is not None and name in _CONST_OVERRIDES:
        return _CONST_OVERRIDES[name]
    # lint: allow(device-purity): limbs is a static host constant list
    return jnp.asarray(np.array(limbs, dtype=np.int32)[:, None])


def one_col():
    """Montgomery 1 as a (NB, 1) column."""
    return _const_col(list(fb.ONE_MONT_B), "one")


def reduce_small(x):
    """fieldb.reduce_small in transposed layout: quotient estimate from the
    top two limbs, subtract q*2p via the 2^396-complement."""
    t2 = x[..., NB - 1, :] * (1 << LIMB_BITS) + x[..., NB - 2, :]
    q = t2 // 833
    return _relax(x + q[..., None, :] * _const_col(_COMP_2P, "comp_2p"), NB)


# ------------------------------------------------------------- multiplies


def _tpu_backend() -> bool:
    """True when this process computes on real TPU hardware (the MXU
    default only makes sense where there IS an MXU)."""
    import jax

    try:
        return jax.default_backend() in ("tpu", "axon")
    # lint: allow(except-swallow): no readable backend == not a TPU
    except Exception:
        return False


def use_mxu_redc() -> str:
    """Route the two STATIC convolutions of Montgomery REDC (by N' and
    by p) through MXU matmuls. LIGHTHOUSE_TPU_MXU_REDC selects the
    operand form: "1"/"i8" = int8 x int8 -> int32; "bf16" = bfloat16
    operands with f32 accumulation (exact: 7-bit digits give column
    sums <= 2^19 << 2^24, and bf16 matmul is the most-trodden Mosaic
    lowering); "0" = forced off (the legacy unrolled VPU chain, A/B via
    BENCH_IMPL=vredc). ""/unset resolves the DEFAULT device form: bf16
    on real TPU hardware (the Toeplitz matmuls replace ~57 of ~90 VPU
    FMA stages per Montgomery product), the VPU chain on the CPU mesh
    (XLA:CPU runs the FMA chain faster and has no MXU to feed). Unlike
    the failed data-conv int8 path (fieldb._conv_contract, measured
    slower 2026-07-31), the MXU here consumes RAW limb digits against
    precomputed Toeplitz digit matrices — no VPU-computed products
    feed it. Read at trace time — part of the backend jit cache keys
    (_impl_key); build fresh jitted functions after flipping it."""
    import os

    # lint: allow(device-purity): trace-time knob, keyed via _impl_key
    v = os.environ.get("LIGHTHOUSE_TPU_MXU_REDC", "")
    if v == "":
        return "bf16" if _tpu_backend() else ""
    if v == "0":
        return ""
    if v == "1":
        return "i8"
    if v in ("i8", "bf16"):
        return v
    # a typo must not silently measure the baseline under an MXU label
    raise ValueError(f"LIGHTHOUSE_TPU_MXU_REDC={v!r}: use i8, bf16, or 0")


def _toeplitz(vals, n_out: int, n_in: int) -> np.ndarray:
    """Conv-as-matmul matrix: out_k = sum_l x_l * vals[k - l], rows
    truncated at n_out (mod-R truncation for the N' matrix)."""
    m = np.zeros((n_out, n_in), np.int32)
    for l in range(n_in):
        for k in range(l, min(n_out, l + len(vals))):
            m[k, l] = vals[k - l]
    return m


# TP gets 64 output rows (63 real + one all-zero) so kernel refs slice at
# 8-aligned sublane offsets and no value-slicing is needed; the zero row
# contributes nothing downstream.
_TN_FULL = _toeplitz(_NPRIME, NLIMBS, NLIMBS)
_TP_FULL = _toeplitz(_PLIMBS, 64, NLIMBS)


def _digits8(m: np.ndarray):
    """12-bit-entry static matrix -> (lo7, hi5) int8 digit matrices."""
    return (m & 127).astype(np.int8), (m >> 7).astype(np.int8)


_TN_LO, _TN_HI = _digits8(_TN_FULL)
_TP_LO, _TP_HI = _digits8(_TP_FULL)


# Stack layout of redc_mats_array: [tn_lo | tn_hi | tp_lo | tp_hi] with
# row offsets derived from the matrix heights. Kernels size their
# BlockSpecs from REDC_MATS_SHAPE so a change here cannot silently
# misalign the in-kernel slices.
_REDC_OFFS = np.cumsum(
    [0, _TN_LO.shape[0], _TN_HI.shape[0], _TP_LO.shape[0], _TP_HI.shape[0]]
)
REDC_MATS_SHAPE = (int(_REDC_OFFS[-1]), NLIMBS)


def redc_mats_array():
    """(REDC_MATS_SHAPE) int8 stack — the single extra input a Pallas
    kernel threads when the MXU-REDC path is on (kernels cannot capture
    array constants). All slice offsets are 8-aligned sublane offsets."""
    return jnp.asarray(
        np.concatenate([_TN_LO, _TN_HI, _TP_LO, _TP_HI], axis=0)
    )


def redc_overrides(mats):
    """Split a REDC_MATS_SHAPE stack (ref-loaded in-kernel) into the
    const_overrides keys _static_conv_mxu reads."""
    o = _REDC_OFFS
    return {
        "tn_lo": mats[int(o[0]) : int(o[1])],
        "tn_hi": mats[int(o[1]) : int(o[2])],
        "tp_lo": mats[int(o[2]) : int(o[3])],
        "tp_hi": mats[int(o[3]) : int(o[4])],
    }


def _const_mat(arr_np, name):
    if name in _CONST_OVERRIDES:
        return _CONST_OVERRIDES[name]
    return jnp.asarray(arr_np)


def _static_conv_mxu(x, lo_np, hi_np, lo_name, hi_name, form: str):
    """Static convolution as four digit-matmuls on the MXU.

    x: (..., L, B) non-negative limbs < 2^13 (relaxed bound 4097).
    Exactness: x splits into lo7 (< 2^7) and hi (< 2^6) digits, the
    matrices into lo7/hi5; per-digit column sums <= 32*127*127 < 2^19
    (int32-exact, and also f32-exact since 2^19 << 2^24 for the bf16
    form) and the recombination sum(p_ab << 7(a+b)) <= 32*4097*4095
    < 2^30 — bit-identical to the unrolled shift-pad FMA chain
    (adversarially checked in tests/test_tfield.py)."""
    mlo = _const_mat(lo_np, lo_name)
    mhi = _const_mat(hi_np, hi_name)
    xlo = x & 127
    xhi = x >> 7
    if form == "bf16":
        dt, acc = jnp.bfloat16, jnp.float32
    else:
        dt, acc = jnp.int8, jnp.int32

    def dot(m, v):
        out = jnp.einsum(
            "kl,...lb->...kb",
            m.astype(dt),
            v.astype(dt),
            preferred_element_type=acc,
        )
        return out.astype(jnp.int32)

    p00 = dot(mlo, xlo)
    p01 = dot(mlo, xhi)
    p10 = dot(mhi, xlo)
    p11 = dot(mhi, xhi)
    return p00 + ((p01 + p10) << 7) + (p11 << 14)


def _shift_pad(x, lo: int, total: int):
    """Place x at limb offset `lo` within a length-`total` limb axis.
    Pad-and-sum composition (NO .at[] scatter updates: those lower to
    scatter-add with empty index constants, which Pallas kernels reject)."""
    pad = [(0, 0)] * x.ndim
    pad[-2] = (lo, total - lo - x.shape[-2])
    return jnp.pad(x, pad)


def mul_lazy(a, b):
    """Stacked Montgomery product: (..., S, NB, B) x (..., S, NB, B) ->
    (..., S, NB, B); inputs < 2.2p relaxed, output < 1.5p (fieldb bound
    chain). Data x data conv unrolls over a's limbs; REDC's two static
    convs unroll over scalar limbs of N' and p."""
    shape = jnp.broadcast_shapes(a.shape, b.shape)
    a = jnp.broadcast_to(a, shape)
    b = jnp.broadcast_to(b, shape)
    t = sum(
        _shift_pad(a[..., i : i + 1, :] * b, i, 2 * NB) for i in range(NB)
    )
    t = _relax(t, 2 * NB)

    t_low = t[..., :NLIMBS, :]
    form = use_mxu_redc()
    if form:
        # both static convs as digit MXU matmuls against Toeplitz digit
        # matrices (the _TN mod-R truncation is baked into the matrix)
        m = _relax(
            _static_conv_mxu(
                t_low, _TN_LO, _TN_HI, "tn_lo", "tn_hi", form
            ),
            NLIMBS,
        )
        mp = _static_conv_mxu(m, _TP_LO, _TP_HI, "tp_lo", "tp_hi", form)
    else:
        # shift t_low up by j limbs, truncated at NLIMBS (mod R)
        m = sum(
            _shift_pad(_NPRIME[j] * t_low[..., : NLIMBS - j, :], j, NLIMBS)
            for j in range(NLIMBS)
            if _NPRIME[j] != 0
        )
        m = _relax(m, NLIMBS)

        mp = sum(
            _shift_pad(_PLIMBS[j] * m, j, 2 * NLIMBS - 1)
            for j in range(NLIMBS)
            if _PLIMBS[j] != 0
        )
    full = _relax(t + _shift_pad(mp, 0, 2 * NB), 2 * NB)

    low_nonzero = jnp.any(full[..., :NLIMBS, :] != 0, axis=-2)
    out = full[..., NLIMBS : NLIMBS + NB, :]
    bump = low_nonzero[..., None, :].astype(jnp.int32)
    return out + _shift_pad(bump, 0, NB)


def sqr_lazy(a):
    return mul_lazy(a, a)


# --------------------------------------------------------------- combos


def apply_combo(x, matrix):
    """Slot recombination: (..., S_in, NB, B) -> (..., S_out, NB, B).
    Unrolled per output row over static small coefficients (rows L1 <= 36);
    double-reduced exactly like fieldb.apply_combo."""
    # lint: allow(device-purity): matrix is a static recombination table
    m = np.asarray(matrix, dtype=np.int64)
    assert np.abs(m).sum(axis=1).max() <= fb._OFF_K, "combo L1 too large"
    off = _const_col(_OFF, "off")
    rows = []
    for o in range(m.shape[0]):
        acc = None
        for s in range(m.shape[1]):
            c = int(m[o, s])
            if c == 0:
                continue
            term = x[..., s, :, :] if c == 1 else c * x[..., s, :, :]
            acc = term if acc is None else acc + term
        if acc is None:
            acc = jnp.zeros_like(x[..., 0, :, :])
        rows.append(acc + off)
    y = jnp.stack(rows, axis=-3)
    y = _relax(y, NB, passes=2)
    return reduce_small(reduce_small(y))


def add(a, b):
    return reduce_small(_partial_pass(a + b))


def sub(a, b):
    s = a - b + _const_col(_SPREAD_SUB, "spread_sub")
    return reduce_small(_relax(s, NB, passes=2))


def scalar_small(a, k: int):
    if k == 0:
        return jnp.zeros_like(a)
    assert k <= 12
    return reduce_small(_relax(a * k, NB, passes=2))


def select(cond, a, b):
    """cond: (..., B) broadcasting over (slots, limbs)."""
    return jnp.where(cond[..., None, None, :], a, b)


# --------------------------------------------------------- layout converts


def from_batchlead(x):
    """(..., S, NB) batch-leading (fieldb layout, batch axes in ...) ->
    (S, NB, B) with the single leading batch axis moved last."""
    return jnp.moveaxis(x, -3, -1)


def to_batchlead(x):
    """(S, NB, B) -> (B, S, NB)."""
    return jnp.moveaxis(x, -1, -3)
