"""Transposed-layout (batch-last) complete projective point ops.

The RCB complete-formula plane of ops.curve.ProjectiveGroup re-laid onto
ops.tfield bundles: a point is (X, Y, Z) with each coordinate (w, NB, B)
— w = 1 (G1/Fp) or 2 (G2/Fp2), batch on lanes. Reuses the EXACT combo
matrices built (and validated) by curve.PG1/PG2, so the two layouts
cannot drift. Runs under plain jit and inside Pallas kernels
(ops.pallas_ladder).
"""

import jax
import jax.numpy as jnp
import numpy as np

from lighthouse_tpu.ops import curve, tfield as tf
from lighthouse_tpu.ops.programs import FP2_MUL

NB = tf.NB


class TProjective:
    def __init__(self, pg):
        """pg: curve.PG1 or curve.PG2 (matrix provider)."""
        self.pg = pg
        self.w = pg.F.w

    # ------------------------------------------------------------ helpers

    def _mul(self, a, b):
        """Stacked coordinate multiply on (n, w, NB, B)."""
        if self.w == 1:
            return tf.mul_lazy(a, b)
        from lighthouse_tpu.ops.tpairing import bilinear

        return bilinear(a, b, FP2_MUL)

    def _stack_mul(self, avals, bvals):
        A = jnp.stack(avals)
        B = jnp.stack(bvals)
        out = self._mul(A, B)
        return [out[i] for i in range(len(avals))]

    def _combo(self, vals, matrix, n_out):
        w = self.w
        x = jnp.concatenate(vals, axis=-3)
        y = tf.apply_combo(x, matrix)
        return [y[..., w * i : w * (i + 1), :, :] for i in range(n_out)]

    def identity(self, batch: int):
        from lighthouse_tpu.ops.tpairing import _one_slot0

        zero = jnp.zeros((self.w, NB, batch), jnp.int32)
        return (zero, _one_slot0(self.w, batch), zero)

    def select(self, cond, a, b):
        return tuple(tf.select(cond, ca, cb) for ca, cb in zip(a, b))

    def from_affine(self, aff, valid):
        """(x, y) (w, NB, B) + (B,) mask -> projective; invalid lanes
        become the identity (0 : 1 : 0)."""
        x, y = aff
        B = x.shape[-1]
        ix, iy, iz = self.identity(B)
        from lighthouse_tpu.ops.tpairing import _one_slot0

        one = _one_slot0(self.w, B)
        return (
            tf.select(valid, x, ix),
            tf.select(valid, y, iy),
            tf.select(valid, one, iz),
        )

    def neg(self, pt):
        return (
            pt[0],
            tf.apply_combo(pt[1], -np.eye(self.w, dtype=np.int32)),
            pt[2],
        )

    # ---------------------------------------------------------- group ops

    def add(self, p, q):
        """RCB Algorithm 7 — same matrices as curve.ProjectiveGroup.add."""
        pg = self.pg
        a_ops = self._combo(list(p), pg._ADD_OPS, 6)
        b_ops = self._combo(list(q), pg._ADD_OPS, 6)
        m = self._stack_mul(a_ops, b_ops)
        t3, t4, t5, T0, Z3s, t1m = self._combo(m, pg._ADD_C1, 6)
        (y3c,) = self._combo([t5], pg._B3_ROW, 1)
        prods = self._stack_mul(
            [t4, t3, y3c, t1m, T0, Z3s],
            [y3c, t1m, T0, Z3s, t3, t4],
        )
        x3, y3, z3 = self._combo(prods, pg._ADD_C3, 3)
        return (x3, y3, z3)

    def double(self, pt):
        """RCB Algorithm 9 — same matrices as curve.ProjectiveGroup."""
        pg = self.pg
        X, Y, Z = pt
        m0, m1, m2, m3 = self._stack_mul([Y, Y, Z, X], [Y, Z, Z, Y])
        z8, t2v, y3s = self._combo([m0, m1, m2, m3], pg._DBL_C1, 3)
        (t0f,) = self._combo([m0, t2v], pg._DBL_C2, 1)
        prods = self._stack_mul([t2v, m1, t0f, t0f], [z8, z8, y3s, m3])
        x3, y3, z3 = self._combo(prods, pg._DBL_C3, 3)
        return (x3, y3, z3)

    def ladder_step(self, acc, addend, bit):
        """One double-add iteration: acc += addend when bit, addend
        doubles. `bit` is (B,) int32 (per-lane scalar bits)."""
        added = self.add(acc, addend)
        acc = self.select(bit == 1, added, acc)
        addend = self.double(addend)
        return acc, addend

    def mul_scalar_bits(self, pt, bits):
        """bits (nbits, B) int32 LSB-first -> per-lane scalar multiple."""
        B = pt[0].shape[-1]

        def step(carry, bit):
            acc, addend = carry
            acc, addend = self.ladder_step(acc, addend, bit)
            return (acc, addend), None

        (acc, _), _ = jax.lax.scan(step, (self.identity(B), pt), bits)
        return acc

    # ------------------------------------------ windowed (w=2) ladder

    def window2_table(self, pt):
        """{identity, P, 2P, 3P} for the MSB-first 2-bit window ladder.
        Complete formulas make the identity entry exact, so digit 0
        needs no conditional."""
        B = pt[0].shape[-1]
        p2 = self.double(pt)
        p3 = self.add(p2, pt)
        return (self.identity(B), pt, p2, p3)

    def window2_step(self, acc, table, digit):
        """One MSB-first 2-bit window: acc = 4*acc + table[digit].
        2 doubles + 1 complete add + selects, vs 2 doubles + 2 adds for
        two plain ladder steps — the PERF_NOTES '64 adds -> ~33' item."""
        acc = self.double(self.double(acc))
        t01 = self.select(digit == 1, table[1], table[0])
        t23 = self.select(digit == 3, table[3], table[2])
        cand = self.select(digit >= 2, t23, t01)
        return self.add(acc, cand)

    def mul_scalar_bits_w2(self, pt, bits):
        """Windowed-2 variant of mul_scalar_bits — identical result,
        ~25% fewer group ops. bits (nbits, B) int32 LSB-first; nbits is
        padded to even internally."""
        n_bits = bits.shape[0]
        if n_bits % 2:
            bits = jnp.concatenate(
                [bits, jnp.zeros((1,) + bits.shape[1:], bits.dtype)]
            )
        # LSB-first pairs -> MSB-first digit sequence
        digits = bits[0::2] + 2 * bits[1::2]
        digits = digits[::-1]
        table = self.window2_table(pt)
        B = pt[0].shape[-1]

        def step(acc, digit):
            return self.window2_step(acc, table, digit), None

        acc, _ = jax.lax.scan(step, self.identity(B), digits)
        return acc

    # --------------------------------- signed-digit window ladder pieces
    # (the transposed half of ops.window_ladder's unified plane — the
    # recode and dispatch live there; these are the layout-local steps
    # shared by the XLA-level ladder_t and the Pallas w4 kernel)

    def window_table(self, pt, c: int):
        """[identity, P, 2P, .., B·P] multiples (B = 2^(c-1)); even
        entries by doubling, odd by one add — complete formulas make
        the identity entry and identity input lanes exact."""
        B = pt[0].shape[-1]
        table = [self.identity(B), pt]
        for d in range(2, (1 << (c - 1)) + 1):
            table.append(
                self.double(table[d // 2])
                if d % 2 == 0
                else self.add(table[-1], pt)
            )
        return tuple(table)

    def window_step(self, acc, table, mag, neg, c: int):
        """acc <- [2^c] acc + sign·table[mag] — one signed-digit
        window: c doublings + ONE complete add + a select chain over
        the B+1 static table entries. mag (B,) int32, neg (B,) bool."""
        for _ in range(c):
            acc = self.double(acc)
        t = table[0]
        for d in range(1, len(table)):
            t = self.select(mag == d, table[d], t)
        t = self.select(neg, self.neg(t), t)
        return self.add(acc, t)

    def sum_lanes(self, pt, axis: int = -1):
        """Tree-fold the lane axis down to ONE point (1-lane bundles).
        Lane count must be a power of two (pad with identities first)."""
        x, y, z = pt
        n = x.shape[axis]
        assert n & (n - 1) == 0, "sum_lanes needs a power-of-two lane count"
        while n > 1:
            half = n // 2
            a = tuple(c[..., :half] for c in (x, y, z))
            b = tuple(c[..., half : 2 * half] for c in (x, y, z))
            x, y, z = self.add(a, b)
            n = half
        return (x, y, z)


TPG1 = TProjective(curve.PG1)
TPG2 = TProjective(curve.PG2)
