"""Device (JAX/XLA/Pallas) kernels for the lighthouse_tpu crypto data plane.

Layout convention: a base-field element is a little-endian vector of
`constants.NLIMBS` limbs of `constants.LIMB_BITS` bits held in int32 lanes,
shape (..., NLIMBS). Tower elements (Fp2/Fp6/Fp12) and curve points are
pytrees (tuples) of such arrays, mirroring the pure-Python reference in
`lighthouse_tpu.crypto` 1:1 so every kernel is testable against it.

All arithmetic is batched: every op broadcasts over leading axes, so the
same code serves one signature set or a 30k-signature slot batch.
"""
