"""Batched device multi-scalar multiplication (MSM) for BLS12-381 G1.

The KZG producer path (blob -> commitment, opening proofs) is one MSM
per blob plus one per proof: C = sum_i [s_i]P_i over up to 4096 points.
The naive form is N independent 255-bit double-add ladders — ~255N
doublings + ~128N adds. This module carries the two classic
restructurings into the projective-RCB lane discipline of
`ops.kzg_verify` / `ops.curve.PG1` (complete formulas: identity lanes,
duplicate points and folded collisions all flow through one branchless
code path):

* **fixed-base windowed** (`msm_fixed_base`): the trusted setup's G1
  points are static, so the host precomputes per-point digit multiples
  [d]P_i for |d| <= 2^(c-1) ONCE per setup (`TrustedSetup
  .g1_window_table`, cached) and each MSM reduces to W window steps of
  (gather digit multiple) + (log-depth tree fold over N lanes) + a
  Horner combine (c doublings + 1 add per window). Group-op count:
  W*N fold adds + ~255 doublings ~= 266k ops at N=4096/c=4, vs ~1.57M
  for the naive ladders — and the fold is log2(N)-deep instead of
  255-step-sequential per point.

* **variable-base Pippenger** (`msm_pippenger`): arbitrary point sets.
  Signed base-2^c digits put every window digit in [-B, B], B =
  2^(c-1); per window the B bucket sums are masked tree folds over the
  N lanes, the bucket-weighted sum T_w = sum_b b*S_b is the standard
  double running sum (2B adds), and windows combine by the same Horner
  scan. Op count: W*B*N masked fold adds — the win over the per-lane
  ladder is DEPTH (log2(N) + 2B + c per window vs 255 sequential
  add+double steps), which is what XLA scan latency and compile time
  scale with.

Scalar digit decomposition (`signed_digit_arrays`) happens on the host
(numpy, exact bigint). The signed-digit machinery itself now lives in
`ops.window_ladder` — the ONE windowed-ladder plane shared with the
per-lane RLC ladders of `ops.batch_verify` and the KZG lane ladders of
`ops.kzg_verify`; this module re-exports it specialized to the
255-bit subgroup-order width so the MSM graphs and the ladders cannot
drift. Both graphs return ONE projective PG1 point; callers convert
via `curve.PG1.to_affine`.

Host-side policy (which points, subgroup checks, setup caching) lives
in `lighthouse_tpu.kzg`; the pure-bigint Pippenger oracle these graphs
are verified against is `kzg.api._g1_lincomb`.
"""

import numpy as np

import jax
import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import R
from lighthouse_tpu.ops import curve, fieldb as fb
from lighthouse_tpu.ops import window_ladder as wl

NB = fb.NB

WINDOW_BITS = wl.WINDOW_BITS  # default window width c; B = 2^(c-1) magnitudes
SCALAR_BITS = R.bit_length()  # 255


def num_windows(c: int = WINDOW_BITS) -> int:
    """Window count for signed base-2^c digits of scalars < r — the
    shared `window_ladder.num_windows` at the subgroup-order width."""
    return wl.num_windows(SCALAR_BITS, c)


def signed_digits(s: int, c: int = WINDOW_BITS) -> list:
    """One scalar -> W signed base-2^c digits, LSB-first, each in
    [-(2^(c-1) - 1), 2^(c-1)]: sum_w d_w 2^(cw) == s mod r. The shared
    `window_ladder.signed_digits` at the subgroup-order width."""
    return wl.signed_digits(s % R, c, SCALAR_BITS)


def signed_digit_arrays(scalars, c: int = WINDOW_BITS):
    """Host: scalars -> (mags, negs): (W, N) int32 digit magnitudes in
    [0, 2^(c-1)] and (W, N) bool negation flags, window-major (the scan
    axis of both device graphs). Callers pass scalars already reduced
    mod r (the tpu backends do)."""
    return wl.signed_digit_arrays(
        [s % R for s in scalars], c, SCALAR_BITS
    )


def _identity_point():
    ident = jnp.asarray(curve.PG1._identity)  # (3, 1, NB)
    return (ident[0], ident[1], ident[2])


def _horner_step(acc, t, c: int):
    """acc <- [2^c] acc + t (the per-window combine, MSB-first)."""
    for _ in range(c):
        acc = curve.PG1.double(acc)
    return curve.PG1.add(acc, t)


def msm_fixed_base(table_x, table_y, table_valid, mags, negs, *, c=WINDOW_BITS):
    """Fixed-base windowed MSM over a precomputed digit-multiple table.

    table_x/table_y: (N, B+1, 1, NB) affine Montgomery bundles of
        [d]P_i for d = 0..B (d=0 rows are dummies, masked invalid).
    table_valid: (N, B+1) bool — False rows enter the fold as identity
        (d=0 and any infinity multiples).
    mags/negs: (W, N) digit magnitudes / negation flags from
        `signed_digit_arrays`.

    Returns one projective PG1 point, coords (1, NB).
    """
    n = table_x.shape[0]
    lane = jnp.arange(n)

    def body(acc, wd):
        mag, neg = wd
        x = table_x[lane, mag]  # (N, 1, NB) gather of [|d_i|]P_i
        y = table_y[lane, mag]
        v = table_valid[lane, mag]
        y = fb.select(neg, curve.F1.neg(y), y)
        pts = curve.PG1.from_affine((x, y), v)
        t = curve.PG1.sum_axis(pts, axis=0)
        return _horner_step(acc, t, c), None

    acc, _ = jax.lax.scan(
        body, _identity_point(), (mags, negs), reverse=True
    )
    return acc


def msm_pippenger(pts_x, pts_y, valid, mags, negs, *, c=WINDOW_BITS):
    """Variable-base Pippenger MSM: signed-digit windows + bucket
    aggregation by masked tree folds.

    pts_x/pts_y: (N, 1, NB) affine Montgomery bundles; valid: (N,) bool
    (False = infinity). mags/negs as in `signed_digit_arrays`.

    Returns one projective PG1 point, coords (1, NB).
    """
    b_max = 1 << (c - 1)
    pts = curve.PG1.from_affine((pts_x, pts_y), valid)
    buckets = jnp.arange(1, b_max + 1)  # (B,)

    def body(acc, wd):
        mag, neg = wd  # (N,)
        y_s = fb.select(neg, curve.F1.neg(pts[1]), pts[1])
        p_w = (pts[0], y_s, pts[2])
        lanes = tuple(
            jnp.broadcast_to(comp, (b_max,) + comp.shape) for comp in p_w
        )  # (B, N, 1, NB)
        sel = mag[None, :] == buckets[:, None]  # (B, N)
        s = curve.PG1.masked_sum_axis(lanes, sel, axis=1)  # (B,) points
        # T_w = sum_b b * S_b via the double running sum:
        #   run_k = sum_{b >= k} S_b accumulated top-down; T = sum_k run_k
        run = _identity_point()
        tot = _identity_point()
        for b in reversed(range(b_max)):
            run = curve.PG1.add(run, tuple(comp[b] for comp in s))
            tot = curve.PG1.add(tot, run)
        return _horner_step(acc, tot, c), None

    acc, _ = jax.lax.scan(
        body, _identity_point(), (mags, negs), reverse=True
    )
    return acc
