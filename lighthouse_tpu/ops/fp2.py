"""Batched BLS12-381 quadratic-extension (Fp2) arithmetic on device limbs.

Fp2 = Fp[u]/(u^2 + 1). An element is a 2-tuple `(c0, c1)` of `(..., NLIMBS)`
int32 limb arrays (see `lighthouse_tpu.ops.fp`), giving c0 + c1*u. Tuples are
JAX pytrees, so Fp2 values flow through jit/vmap/scan unchanged.

Multiplicative ops assume the Montgomery domain (as all device field values
on the hot path are); additive ops are domain-agnostic.

Parity note: fills the role of blst's fp2 arithmetic behind the reference
client's BLS boundary (reference crypto/bls/src/impls/blst.rs); validated
against `lighthouse_tpu.crypto.ref_fields` (fp2_*).
"""

import jax.numpy as jnp

from lighthouse_tpu.ops import fp

ZERO = (fp.ZERO, fp.ZERO)
ONE_MONT = (fp.ONE_MONT, fp.ZERO)


def pack(values):
    """Host: iterable of (c0, c1) int tuples -> Fp2 batch (canonical form)."""
    return (
        fp.pack([v[0] for v in values]),
        fp.pack([v[1] for v in values]),
    )


def to_ints(a):
    """Host: Fp2 batch -> list of (c0, c1) int tuples."""
    c0, c1 = a
    import numpy as np

    c0 = np.asarray(c0).reshape(-1, c0.shape[-1])
    c1 = np.asarray(c1).reshape(-1, c1.shape[-1])
    return [(fp.to_int(x), fp.to_int(y)) for x, y in zip(c0, c1)]


def to_mont(a):
    return (fp.to_mont(a[0]), fp.to_mont(a[1]))


def from_mont(a):
    return (fp.from_mont(a[0]), fp.from_mont(a[1]))


def add(a, b):
    return (fp.add(a[0], b[0]), fp.add(a[1], b[1]))


def sub(a, b):
    return (fp.sub(a[0], b[0]), fp.sub(a[1], b[1]))


def neg(a):
    return (fp.neg(a[0]), fp.neg(a[1]))


def conj(a):
    return (a[0], fp.neg(a[1]))


def scalar_small(a, k: int):
    return (fp.scalar_small(a[0], k), fp.scalar_small(a[1], k))


def mul(a, b):
    """Karatsuba: 3 base-field Montgomery products."""
    a0, a1 = a
    b0, b1 = b
    t0 = fp.mont_mul(a0, b0)
    t1 = fp.mont_mul(a1, b1)
    cross = fp.mont_mul(fp.add(a0, a1), fp.add(b0, b1))
    return (fp.sub(t0, t1), fp.sub(fp.sub(cross, t0), t1))


def sqr(a):
    """(a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u — 2 products."""
    a0, a1 = a
    c0 = fp.mont_mul(fp.add(a0, a1), fp.sub(a0, a1))
    t = fp.mont_mul(a0, a1)
    return (c0, fp.add(t, t))


def mul_fp(a, s):
    """Multiply Fp2 element by an Fp element (both Montgomery)."""
    return (fp.mont_mul(a[0], s), fp.mont_mul(a[1], s))


def mul_by_xi(a):
    """Multiply by xi = 1 + u: (c0 - c1) + (c0 + c1) u."""
    return (fp.sub(a[0], a[1]), fp.add(a[0], a[1]))


def inv(a):
    """1 / (a0 + a1 u) = (a0 - a1 u) / (a0^2 + a1^2). inv(0) = 0."""
    a0, a1 = a
    norm = fp.add(fp.mont_mul(a0, a0), fp.mont_mul(a1, a1))
    ninv = fp.inv(norm)
    return (fp.mont_mul(a0, ninv), fp.neg(fp.mont_mul(a1, ninv)))


def is_zero(a):
    return fp.is_zero(a[0]) & fp.is_zero(a[1])


def eq(a, b):
    return fp.eq(a[0], b[0]) & fp.eq(a[1], b[1])


def select(cond, a, b):
    """Branchless select; cond broadcasts over the limb axis."""
    return (fp.select(cond, a[0], b[0]), fp.select(cond, a[1], b[1]))


def broadcast_const(const_limbs, shape_like):
    """Broadcast a static (2, NLIMBS)-style tuple constant over batch dims of
    `shape_like` (an Fp limb array)."""
    batch = shape_like.shape[:-1]
    return tuple(
        jnp.broadcast_to(jnp.asarray(c), batch + (c.shape[-1],))
        for c in const_limbs
    )
