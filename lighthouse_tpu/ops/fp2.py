"""Batched BLS12-381 Fp2 arithmetic on slot bundles.

An Fp2 value is an int32 bundle `(..., 2, NB)` (see ops.fieldb): slot 0 =
c0, slot 1 = c1 of c0 + c1*u, u^2 = -1. Multiplication is the 3-product
Karatsuba bilinear program applied as ONE stacked Montgomery multiply.

Values are lazily reduced (< 2.2p); canonicalization happens in predicates
and at the host boundary. Validated against crypto/ref_fields.fp2_*.
"""

import numpy as np

import jax.numpy as jnp

from lighthouse_tpu.crypto.constants import P
from lighthouse_tpu.ops import fieldb as fb
from lighthouse_tpu.ops.programs import FP2_MUL

NB = fb.NB

ZERO = np.zeros((2, NB), dtype=np.int32)
ONE_MONT = np.stack([fb.ONE_MONT_B, fb.ZERO_B])

# combo matrices
_CONJ = np.array([[1, 0], [0, -1]], dtype=np.int32)
_MUL_BY_XI = np.array([[1, -1], [1, 1]], dtype=np.int32)
_NEG = -np.eye(2, dtype=np.int32)


def bilinear(x, y, prog):
    left = fb.apply_combo(x, prog.A)
    right = fb.apply_combo(y, prog.B)
    return fb.apply_combo(fb.mul_lazy(left, right), prog.C)


# ------------------------------------------------------------- host helpers


def pack(values) -> np.ndarray:
    """Host: iterable of (c0, c1) int tuples -> (N, 2, NB) bundle (plain
    domain, canonical)."""
    return np.stack([fb.pack_ints([v[0], v[1]]) for v in values])


def to_ints(a):
    """Host: (..., 2, NB) bundle -> list of (c0, c1) int tuples."""
    vals = fb.unpack_ints(a)
    return [(vals[i], vals[i + 1]) for i in range(0, len(vals), 2)]


def to_mont(a):
    return fb.to_mont(a)


def from_mont(a):
    return fb.from_mont(a)


# -------------------------------------------------------------- field ops


def add(a, b):
    return fb.add(a, b)


def sub(a, b):
    return fb.sub(a, b)


def neg(a):
    return fb.apply_combo(a, _NEG)


def conj(a):
    return fb.apply_combo(a, _CONJ)


def scalar_small(a, k: int):
    return fb.scalar_small(a, k)


def mul(a, b):
    return bilinear(a, b, FP2_MUL)


def sqr(a):
    return bilinear(a, a, FP2_MUL)


def mul_fp(a, s):
    """Fp2 bundle times an Fp bundle (..., 1, NB): per-slot product."""
    return fb.mul_lazy(a, jnp.broadcast_to(s, a.shape))


def mul_by_xi(a):
    return fb.apply_combo(a, _MUL_BY_XI)


def inv(a):
    """1/(c0 + c1 u) = (c0 - c1 u)/(c0^2 + c1^2); inv(0) == 0."""
    sq = fb.mul_lazy(a, a)  # (c0^2, c1^2)
    norm = fb.apply_combo(sq, np.array([[1, 1]], dtype=np.int32))
    ninv = fb.inv(norm)  # (..., 1, NB)
    scaled = fb.mul_lazy(a, jnp.broadcast_to(ninv, a.shape))
    return fb.apply_combo(scaled, _CONJ)


def is_zero(a):
    return fb.is_zero(a)


def eq(a, b):
    return fb.eq(a, b)


def select(cond, a, b):
    return fb.select(cond, a, b)


def broadcast_const(const_bundle, batch_shape):
    c = jnp.asarray(const_bundle)
    return jnp.broadcast_to(c, tuple(batch_shape) + c.shape)


def const_mont(c0: int, c1: int) -> np.ndarray:
    """Static (c0, c1) -> Montgomery-form bundle constant."""
    return np.stack(
        [
            fb._limbs((c0 << 384) % P, NB),
            fb._limbs((c1 << 384) % P, NB),
        ]
    )
