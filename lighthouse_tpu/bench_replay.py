"""Benchmark config #5 (BASELINE.md): epoch-transition replay — 32 slots
of blocks re-imported through the full state transition with BULK
signature verification streamed to the device.

Role of the reference's BlockReplayer + signature_verify_chain_segment
(consensus/state_processing/src/block_replayer.rs,
beacon_node/beacon_chain/src/block_verification.rs:509): a node catching
up replays block ranges, batch-verifying every signature in the segment
while the per-block state transition runs on the host. This config
measures that whole loop end to end: Python state transition +
per-block device signature batches, on a minimal-preset chain built by
the in-process harness.

The build phase (producing and signing the 32 blocks with the pure
reference crypto) is NOT in the measured window; only the replay is.
Reported: slots/sec over the replay, plus the verified-signature count.

Env knobs: BENCH_REPLAY_SLOTS (default 32), BENCH_REPLAY_VALIDATORS
(default 64 on TPU, 16 on CPU fallback).
"""

import os
import time


def measure(jax, platform):
    from lighthouse_tpu.harness import Harness
    from lighthouse_tpu.state_processing.per_block import (
        BlockSignatureStrategy,
    )
    from lighthouse_tpu.types.spec import minimal_spec

    on_tpu = platform in ("tpu", "axon")

    # ---- impl selection FIRST (cheap; a typo must fail before the
    # minutes-long segment build). The harness verifies through the bls
    # backend dispatch, steered by LIGHTHOUSE_TPU_IMPL. With BENCH_IMPL
    # unset the dispatch keeps its own auto-selection (Pallas on real
    # TPU) — pinning xla here would silently regress the default replay
    # measurement several-fold.
    impl = os.environ.get("BENCH_IMPL")
    if impl is not None:
        import sys

        from lighthouse_tpu.bench_impl import apply_impl_env

        apply_impl_env(impl, what="replay32")
        # The harness verifies through the bls backend dispatch, which
        # knows the xla|pallas program pair plus every form knob
        # apply_impl_env just set (ladder/REDC/squaring/tail — all part
        # of _impl_key now, so ptail IS dispatchable). txla (bench-only
        # transposed layout) exists only as a standalone bench program —
        # accepting it would measure the plain path under its label,
        # the exact mislabeling the exit-4 rule exists to prevent.
        if impl == "txla":
            print(
                f"replay32: BENCH_IMPL={impl} has no backend dispatch;"
                " use xla|mxu|pallas|ptail|predc|chain|vredc|mulsqr",
                file=sys.stderr,
            )
            sys.exit(4)
        if on_tpu:
            os.environ["LIGHTHOUSE_TPU_IMPL"] = (
                "xla" if impl in ("xla", "mxu") else "pallas"
            )
        impl_label = impl
    else:
        impl_label = "auto:pallas" if on_tpu else "auto:xla"

    # BENCH_NSETS (the watcher's generic size knob) maps to the slot
    # count; BENCH_REPLAY_SLOTS takes precedence when both are set.
    n_slots = int(
        os.environ.get("BENCH_REPLAY_SLOTS")
        or os.environ.get("BENCH_NSETS")
        or 32
    )
    default_v = 64 if on_tpu else 16
    n_validators = int(
        os.environ.get("BENCH_REPLAY_VALIDATORS") or default_v
    )
    if not on_tpu:
        n_slots = min(n_slots, 8)  # prove the path only

    spec = minimal_spec()

    # ---- build the segment (unmeasured): produce + import n_slots
    # blocks. The builder skips signature verification — it signed the
    # blocks itself one line earlier, and the measured replay verifies
    # every set anyway; re-verifying here through the pure-Python
    # pairing would burn minutes of the watcher's per-config deadline.
    builder = Harness(spec, n_validators, backend="ref")
    blocks = []
    start = builder.state.slot + 1
    for slot in range(start, start + n_slots):
        blocks.append(
            builder.advance_slot_with_block(
                slot, strategy=BlockSignatureStrategy.NO_VERIFICATION
            )
        )

    n_sigs = 0
    for b in blocks:
        # proposal + randao + one set per attestation (+ sync aggregate)
        n_sigs += 2 + len(b.message.body.attestations)
        if getattr(b.message.body, "sync_aggregate", None) is not None:
            n_sigs += 1

    def replay_once():
        replayer = Harness(spec, n_validators, backend="tpu")
        t0 = time.perf_counter()
        for b in blocks:
            replayer.import_block(
                b,
                strategy=BlockSignatureStrategy.VERIFY_BULK,
                consumer="bench",
            )
        return time.perf_counter() - t0

    # first pass compiles every (s_bucket, k_bucket) shape class — the
    # other configs separate compile via _compile_and_time; here the
    # warm-up IS a full unmeasured replay, and the second pass is timed
    warm_s = replay_once()
    wall = replay_once()

    return {
        "metric": "epoch_replay_slots_per_sec",
        "value": round(n_slots / wall, 3),
        "unit": "slots/sec",
        "vs_baseline": 0.0,  # no published reference number for this shape
        "platform": platform,
        "impl": impl_label,
        "n_sets": n_slots,  # the watcher's generic size field
        "n_slots": n_slots,
        "n_validators": n_validators,
        "n_signature_sets": n_sigs,
        "wall_s": round(wall, 3),
        "compile_s": round(warm_s, 1),  # warm-up pass incl. compiles
        "valid_for_headline": bool(on_tpu and n_slots >= 32),
    }
