"""CLI multiplexer: beacon node, validator client, accounts, dev tools.

Role of the reference's `lighthouse` binary (lighthouse/src/main.rs:34
subcommand multiplexer), account_manager, database_manager, and lcli (dev
Swiss-army tools: transition-blocks, skip-slots, new-testnet, ssz parsing).

    python -m lighthouse_tpu bn --network minimal --validators 32 --slots 16
    python -m lighthouse_tpu vc ...
    python -m lighthouse_tpu account new --password ... --out key.json
    python -m lighthouse_tpu lcli skip-slots --slots 4
    python -m lighthouse_tpu db inspect --path chain.sqlite
"""

import argparse
import json
import sys
import time


def _spec_for(name: str, altair_epoch=None):
    """Spec for --network: built-in network-config assets first (the
    eth2_network_config path — mainnet/minimal/gnosis config.yaml dirs
    under lighthouse_tpu/network_configs/), programmatic presets as the
    fallback."""
    from dataclasses import replace

    from lighthouse_tpu import network_config as nc
    from lighthouse_tpu.types.spec import mainnet_spec, minimal_spec

    try:
        spec = nc.builtin(name).spec
    except nc.NetworkConfigError:
        spec = minimal_spec() if name == "minimal" else mainnet_spec()
    if altair_epoch is not None:
        spec = replace(spec, ALTAIR_FORK_EPOCH=altair_epoch)
    return spec


def _apply_store_flags(chain, args) -> None:
    """Store flags shared by every bn boot path (applied before any
    migration can run; slots_per_restore_point is only read at
    migrate/load time)."""
    if args.slots_per_restore_point:
        chain.store.slots_per_restore_point = args.slots_per_restore_point


def _apply_trace_flags(args) -> None:
    """Size (or disable, with 0) the data-plane span tracer before any
    chain work runs."""
    from lighthouse_tpu.common import tracing

    capacity = getattr(args, "trace_buffer", tracing.DEFAULT_CAPACITY)
    tracing.configure(enabled=capacity > 0, capacity=max(capacity, 1))


def _apply_journal_flags(chain, args) -> None:
    """Size (or disable, with 0) the node's lifecycle event journal;
    point the process compile ledger at its persistent JSONL file."""
    from lighthouse_tpu.common import events_journal

    capacity = getattr(
        args, "journal_buffer", events_journal.DEFAULT_CAPACITY
    )
    chain.journal.configure(
        enabled=capacity > 0, capacity=max(capacity, 1)
    )
    ledger_path = getattr(args, "compile_ledger", None)
    if ledger_path:
        from lighthouse_tpu.common.compile_ledger import LEDGER

        LEDGER.configure(path=ledger_path)


def parse_admission_limits(spec_str):
    """``cls=concurrency:deadline,...`` -> {cls: (int, float)}; classes
    must exist in the admission vocabulary (typos are errors, not
    silently-ignored knobs)."""
    from lighthouse_tpu.http_api.admission import DEFAULT_LIMITS

    if not spec_str:
        return {}
    out = {}
    for part in spec_str.split(","):
        part = part.strip()
        if not part:
            continue
        cls_, _, limits = part.partition("=")
        conc, _, budget = limits.partition(":")
        if cls_ not in DEFAULT_LIMITS:
            raise ValueError(
                f"unknown admission class {cls_!r} "
                f"(one of {sorted(DEFAULT_LIMITS)})"
            )
        out[cls_] = (int(conc), float(budget or DEFAULT_LIMITS[cls_][1]))
    return out


def parse_bus_deadlines(spec_str):
    """``consumer=seconds,...`` -> {consumer: float}; consumers must be
    in the closed attribution vocabulary."""
    from lighthouse_tpu.common.device_attribution import CONSUMERS

    if not spec_str:
        return {}
    out = {}
    for part in spec_str.split(","):
        part = part.strip()
        if not part:
            continue
        consumer, _, seconds = part.partition("=")
        if consumer not in CONSUMERS:
            raise ValueError(
                f"unknown bus consumer {consumer!r} "
                f"(one of {sorted(CONSUMERS)})"
            )
        out[consumer] = float(seconds)
    return out


def _apply_bus_flags(chain, args) -> None:
    """Verification-bus knobs (max hold, bucket fill target, per-class
    deadline budgets) — the control surface for the ROADMAP self-tuning
    item, mirrored live at /lighthouse/health."""
    bus = getattr(chain, "verification_bus", None)
    if bus is None:
        return
    hold = getattr(args, "bus_max_hold_ms", None)
    if hold is not None and hold >= 0:
        bus.max_hold_ms = float(hold)
    fill = getattr(args, "bus_fill_target", 0)
    if fill:
        bus.fill_target = int(fill)
    deadlines = getattr(args, "bus_deadlines", None)
    if deadlines:
        bus.class_budgets.update(parse_bus_deadlines(deadlines))


def _apply_breaker_flags(chain, args) -> None:
    """Device-plane fault-domain knobs: circuit-breaker tuning, canary
    mode, and the optional boot-time known-answer self-test — applied
    to the process-global guarded executor (one accelerator, one
    breaker), mirrored live at /lighthouse/health under
    `device_plane`."""
    from lighthouse_tpu.device_plane import GUARD

    kwargs = {}
    threshold = getattr(args, "device_breaker_threshold", None)
    if threshold is not None:
        kwargs["threshold"] = int(threshold)
    cooldown_ms = getattr(args, "device_breaker_cooldown_ms", None)
    if cooldown_ms is not None:
        kwargs["cooldown_s"] = float(cooldown_ms) / 1000.0
    canary = getattr(args, "device_breaker_canary", None)
    if canary is not None:
        kwargs["canary"] = canary
    selftest = getattr(args, "device_breaker_selftest", "off") == "on"
    kwargs["selftest"] = selftest
    GUARD.configure(**kwargs)
    if selftest:
        GUARD.self_test(journal=getattr(chain, "journal", None))


def _apply_slot_fuse_flag(chain, args) -> None:
    """bn --slot-fuse: one-dispatch slot programs (default on)."""
    if chain is None:
        return
    fuse = getattr(args, "slot_fuse", None)
    if fuse is not None:
        chain.slot_fuse = fuse == "on"


def _apply_slot_budget_flags(chain, args) -> None:
    """Slot-budget profiler knobs: the enable switch and the recent-
    imports ring size behind GET /lighthouse/slot_budget."""
    recorder = getattr(chain, "slot_budget", None)
    if recorder is None:
        return
    enabled = getattr(args, "slot_budget", None)
    ring = getattr(args, "slot_budget_ring", None)
    recorder.configure(
        enabled=None if enabled is None else enabled == "on",
        ring=ring,
    )


def _apply_admission_flags(srv, args) -> None:
    """PR 10's hand-set admission constants become a flag: per-class
    concurrency + deadline overrides on the live controller."""
    limits = parse_admission_limits(
        getattr(args, "admission_limits", None)
    )
    if limits:
        srv.admission.limits.update(limits)


def _export_trace(args, chain=None) -> None:
    """Dump the buffered span trees (and journal events) as JSONL on
    shutdown when asked."""
    path = getattr(args, "trace_jsonl", None)
    if path:
        from lighthouse_tpu.common.tracing import TRACER

        n = TRACER.export_jsonl(path)
        print(f"wrote {n} span trees to {path}")
    jpath = getattr(args, "journal_jsonl", None)
    if jpath and chain is not None:
        n = chain.journal.export_jsonl(jpath)
        print(f"wrote {n} journal events to {jpath}")


def _serve_api(chain, args, banner: str) -> int:
    """Start the HTTP API, print the banner, serve for --serve-seconds,
    stop — shared by every bn boot path."""
    from lighthouse_tpu.http_api import BeaconApiServer

    _apply_store_flags(chain, args)
    _apply_journal_flags(chain, args)
    _apply_bus_flags(chain, args)
    _apply_breaker_flags(chain, args)
    _apply_slot_fuse_flag(chain, args)
    _apply_slot_budget_flags(chain, args)
    srv = BeaconApiServer(
        chain, host=args.http_address, port=args.http_port
    )
    _apply_admission_flags(srv, args)
    srv.start()
    print(f"{banner}; HTTP API on {args.http_address}:{srv.port}")
    try:
        if args.serve_seconds:
            time.sleep(args.serve_seconds)
    finally:
        srv.stop()
        _export_trace(args, chain)
    return 0


def cmd_bn(args):
    """Run a beacon node: interop genesis, optional self-proposing (dev
    chain), HTTP API, per-slot timer loop."""
    import os

    from lighthouse_tpu.harness import Harness
    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.http_api import BeaconApiServer
    from lighthouse_tpu.store import SqliteStore

    _apply_trace_flags(args)
    if args.purge_db and args.datadir:
        # fork_revert.rs:14-15 guidance: a node stuck on the wrong side
        # of a fork starts over. The SQLite WAL/SHM sidecars must go
        # too — a fresh db next to a stale -wal would REPLAY the purged
        # chain right back on open
        purged = False
        for path in (
            args.datadir,
            args.datadir + "-wal",
            args.datadir + "-shm",
        ):
            if os.path.exists(path):
                os.remove(path)
                purged = True
        if purged:
            print(f"purged {args.datadir}")
    kv = SqliteStore(args.datadir) if args.datadir else None
    if args.testnet_dir:
        # file-driven boot (--testnet-dir: config.yaml + genesis.ssz,
        # the eth2_network_config custom-directory path)
        from lighthouse_tpu import network_config as nc

        cfg = nc.load_dir(args.testnet_dir)
        genesis = cfg.genesis_state()
        if genesis is None:
            print(
                f"{args.testnet_dir}: no genesis.ssz "
                "(generate one with lcli new-testnet)",
                file=sys.stderr,
            )
            return 1
        chain = BeaconChain(
            genesis, cfg.spec, kv=kv, backend=args.bls_backend
        )
        return _serve_api(
            chain,
            args,
            f"booted network {cfg.name!r} from {args.testnet_dir} "
            f"(genesis_validators_root 0x"
            f"{bytes(genesis.genesis_validators_root).hex()[:12]}, "
            f"{len(cfg.boot_nodes or [])} boot nodes)",
        )
    spec = _spec_for(args.network)
    if (
        args.checkpoint_state
        or args.checkpoint_block
        or args.checkpoint_sync_url
    ):
        # weak-subjectivity boot (client/src/config.rs:31-34): trusted
        # finalized state + block, from SSZ files or fetched from a
        # trusted beacon node over the standard API
        from lighthouse_tpu.http_api.client import (
            ApiClientError,
            decode_checkpoint_pair,
            fetch_checkpoint,
        )

        if args.checkpoint_sync_url and (
            args.checkpoint_state or args.checkpoint_block
        ):
            print(
                "--checkpoint-sync-url and --checkpoint-state/"
                "--checkpoint-block are mutually exclusive",
                file=sys.stderr,
            )
            return 1
        try:
            if args.checkpoint_sync_url:
                state, block = fetch_checkpoint(
                    args.checkpoint_sync_url, spec
                )
            else:
                if not (args.checkpoint_state and args.checkpoint_block):
                    print(
                        "--checkpoint-state and --checkpoint-block are "
                        "required together",
                        file=sys.stderr,
                    )
                    return 1
                with open(args.checkpoint_state, "rb") as f:
                    raw_state = f.read()
                with open(args.checkpoint_block, "rb") as f:
                    raw_block = f.read()
                state, block = decode_checkpoint_pair(
                    raw_state, raw_block, spec
                )
        except ApiClientError as e:
            print(f"checkpoint sync failed: {e}", file=sys.stderr)
            return 1
        chain = BeaconChain.from_checkpoint(
            state, block, spec, kv=kv, backend=args.bls_backend
        )
        return _serve_api(
            chain,
            args,
            f"checkpoint boot at slot {state.slot} "
            f"(anchor 0x{chain.head_root.hex()[:12]})",
        )
    h = Harness(
        spec,
        args.validators,
        backend=args.bls_backend,
        genesis_time=int(time.time()) if args.slots == 0 else 0,
    )
    chain = BeaconChain(
        h.state.copy(), spec, kv=kv, backend=args.bls_backend
    )
    _apply_store_flags(chain, args)
    _apply_journal_flags(chain, args)
    _apply_bus_flags(chain, args)
    _apply_breaker_flags(chain, args)
    _apply_slot_fuse_flag(chain, args)
    _apply_slot_budget_flags(chain, args)
    srv = BeaconApiServer(
        chain, host=args.http_address, port=args.http_port
    )
    _apply_admission_flags(srv, args)
    srv.start()
    print(f"HTTP API on {args.http_address}:{srv.port}")
    try:
        if args.slots:
            for slot in range(1, args.slots + 1):
                block = h.advance_slot_with_block(slot)
                chain.process_block(block)
                chain.set_slot(slot)
                print(
                    f"slot {slot} head=0x{chain.head_root.hex()[:12]} "
                    f"justified={chain.head_state.current_justified_checkpoint.epoch} "
                    f"finalized={chain.finalized_checkpoint.epoch}"
                )
            print("dev chain complete")
            if args.serve_seconds:
                time.sleep(args.serve_seconds)
        else:
            while True:  # pragma: no cover
                time.sleep(spec.SECONDS_PER_SLOT)
    finally:
        srv.stop()
        _export_trace(args, chain)
    return 0


def build_http_vc(
    urls, keypairs, spec, slashing_db_path=None, use_builder=False
):
    """The `vc --beacon-node-url` wiring: one URL talks straight to a
    BeaconNodeHttpClient, several wrap in BeaconNodeFallback (health
    ranking + per-request failover) behind the same client surface.
    Returns a ready HttpValidatorClient."""
    from lighthouse_tpu.http_api.client import BeaconNodeHttpClient
    from lighthouse_tpu.validator_client.beacon_node_fallback import (
        BeaconNodeFallback,
        FallbackBeaconNodeClient,
    )
    from lighthouse_tpu.validator_client.http_vc import (
        HttpValidatorClient,
    )
    from lighthouse_tpu.validator_client.slashing_protection import (
        SlashingProtectionDB,
    )

    clients = [BeaconNodeHttpClient(u) for u in urls]
    if len(clients) == 1:
        client = clients[0]
    else:
        fallback = BeaconNodeFallback.from_clients(clients)
        fallback.update_health()
        client = FallbackBeaconNodeClient(fallback)
    return HttpValidatorClient(
        client,
        list(keypairs),
        spec,
        slashing_db=SlashingProtectionDB(slashing_db_path or ":memory:"),
        use_builder=use_builder,
    )


def _cmd_vc_http(args):
    """Run the HTTP-only duty loop against live beacon node(s): the VC
    reaches the BN exclusively over the REST API (validator_client/
    src/lib.rs production shape), following the BN's own genesis clock."""
    from lighthouse_tpu import bls

    spec = _spec_for(args.network)
    keypairs = bls.interop_keypairs(args.validators)
    vc = build_http_vc(
        args.beacon_node_url, keypairs, spec,
        slashing_db_path=args.slashing_db,
    )
    genesis_time = int(vc.client.get_genesis()["genesis_time"])
    sps = spec.SECONDS_PER_SLOT
    start_slot = max(1, (int(time.time()) - genesis_time) // sps + 1)
    for slot in range(start_slot, start_slot + args.slots):
        wait = genesis_time + slot * sps - time.time()
        if wait > 0:
            time.sleep(wait)
        vc.run_slot(slot)
    print(
        json.dumps(
            {
                "slots": args.slots,
                "beacon_nodes": list(args.beacon_node_url),
                "proposed": vc.metrics["blocks_proposed"],
                "attestations": vc.metrics["attestations_published"],
                "aggregates": vc.metrics["aggregates_published"],
                "publish_errors": vc.metrics["publish_errors"],
            }
        )
    )
    return 0


def cmd_vc(args):
    """Run validator duties: against live beacon node(s) over HTTP when
    --beacon-node-url is given (repeat the flag for a ranked fallback
    list), else against an in-process dev node for N slots."""
    from lighthouse_tpu.harness import Harness
    from lighthouse_tpu.beacon_chain import BeaconChain
    from lighthouse_tpu.validator_client import (
        SlashingProtectionDB,
        ValidatorClient,
    )

    if args.beacon_node_url:
        return _cmd_vc_http(args)
    spec = _spec_for(args.network)
    h = Harness(spec, args.validators, backend=args.bls_backend)
    chain = BeaconChain(h.state.copy(), spec, backend=args.bls_backend)
    db = SlashingProtectionDB(args.slashing_db or ":memory:")
    vc = ValidatorClient(
        chain, dict(enumerate(h.keypairs)), slashing_db=db
    )

    def producer(slot, proposer):
        blk = h.produce_block(slot, h.pending_attestations[:128])
        h.pending_attestations = h.pending_attestations[128:]
        return blk.message

    for slot in range(1, args.slots + 1):
        chain.set_slot(slot)
        signed = vc.propose(slot, producer)
        if signed is not None:
            chain.process_block(signed)
            h.import_block(signed)
        atts = vc.attest(slot)
        chain.process_unaggregated_attestations(atts)
        h.pending_attestations.extend(
            chain.naive_pool.aggregates_at_slot(slot)
        )
    print(
        json.dumps(
            {
                "slots": args.slots,
                "proposed": vc.metrics["blocks_proposed"],
                "attestations": vc.metrics["attestations_published"],
                "finalized_epoch": chain.finalized_checkpoint.epoch,
            }
        )
    )
    return 0


def cmd_account(args):
    from lighthouse_tpu import bls
    from lighthouse_tpu.accounts import (
        Keystore,
        derive_path,
        mnemonic_to_seed,
    )

    if args.account_cmd == "new":
        if args.mnemonic:
            seed = mnemonic_to_seed(args.mnemonic)
            sk_int = derive_path(seed, f"m/12381/3600/{args.index}/0")
            sk = bls.SecretKey(sk_int)
        else:
            sk = bls.SecretKey.random()
        pk = sk.public_key()
        ks = Keystore.encrypt(
            sk.to_bytes(),
            args.password,
            path=f"m/12381/3600/{args.index}/0",
            kdf=args.kdf,
            pubkey=pk.to_bytes(),
        )
        payload = ks.to_json()
        if args.out:
            with open(args.out, "w") as f:
                f.write(payload)
        else:
            print(payload)
        print(f"pubkey: 0x{pk.to_bytes().hex()}", file=sys.stderr)
        return 0
    if args.account_cmd == "import":
        with open(args.keystore) as f:
            ks = Keystore.from_json(f.read())
        secret = ks.decrypt(args.password)
        sk = bls.SecretKey.from_bytes(secret)
        print(f"imported 0x{sk.public_key().to_bytes().hex()}")
        return 0
    if args.account_cmd == "wallet-create":
        from lighthouse_tpu.accounts.wallet import Wallet

        w = Wallet.create(
            args.name, args.password, mnemonic=args.mnemonic,
            seed=bytes.fromhex(args.seed) if args.seed else None,
            kdf=args.kdf,
        )
        with open(args.out or f"{args.name}.wallet.json", "w") as f:
            f.write(w.to_json())
        print(json.dumps({"wallet": w.name, "nextaccount": w.nextaccount}))
        return 0
    if args.account_cmd == "wallet-next":
        from lighthouse_tpu.accounts.wallet import Wallet

        if not args.wallet:
            raise SystemExit("wallet-next requires --wallet <file>")
        with open(args.wallet) as f:
            w = Wallet.from_json(f.read())
        index, ks, _wd = w.next_validator(
            args.password, args.keystore_password or args.password
        )
        # keystore first, wallet (with the bumped counter) last — a
        # keystore write failure must not burn the account index
        out = args.out or f"validator_{index}.keystore.json"
        with open(out, "w") as f:
            f.write(ks.to_json())
        with open(args.wallet, "w") as f:
            f.write(w.to_json())
        print(
            json.dumps(
                {"index": index, "pubkey": "0x" + ks.pubkey_hex, "out": out}
            )
        )
        return 0
    raise SystemExit(f"unknown account command {args.account_cmd}")


def cmd_lcli(args):
    from lighthouse_tpu.harness import Harness
    from lighthouse_tpu.state_processing.per_slot import process_slots

    spec = _spec_for(args.network)
    if args.lcli_cmd == "skip-slots":
        h = Harness(spec, args.validators)
        state = process_slots(h.state, args.slots, spec)
        print(
            json.dumps(
                {
                    "slot": state.slot,
                    "state_root": "0x"
                    + type(state).hash_tree_root(state).hex(),
                }
            )
        )
        return 0
    if args.lcli_cmd == "transition-blocks":
        h = Harness(spec, args.validators)
        h.run_slots(args.slots)
        print(
            json.dumps(
                {
                    "slot": h.state.slot,
                    "state_root": "0x"
                    + type(h.state).hash_tree_root(h.state).hex(),
                    "finalized_epoch": h.finalized_epoch,
                }
            )
        )
        return 0
    if args.lcli_cmd == "new-testnet":
        from lighthouse_tpu import bls

        kps = bls.interop_keypairs(args.validators)
        from lighthouse_tpu.state_processing.genesis import (
            interop_genesis_state,
        )

        state = interop_genesis_state(
            [k.pk.to_bytes() for k in kps], args.genesis_time, spec
        )
        if args.testnet_dir:
            # full network directory (config.yaml + genesis.ssz) that
            # `bn --testnet-dir` boots from — new_testnet in lcli
            from lighthouse_tpu import network_config as nc

            nc.write_dir(args.testnet_dir, spec, genesis_state=state)
            print(
                json.dumps(
                    {
                        "testnet_dir": args.testnet_dir,
                        "genesis_validators_root": "0x"
                        + bytes(state.genesis_validators_root).hex(),
                    }
                )
            )
            return 0
        data = state.to_bytes()
        with open(args.out, "wb") as f:
            f.write(data)
        print(
            json.dumps(
                {
                    "genesis_validators_root": "0x"
                    + bytes(state.genesis_validators_root).hex(),
                    "bytes": len(data),
                }
            )
        )
        return 0
    raise SystemExit(f"unknown lcli command {args.lcli_cmd}")


def cmd_db(args):
    from lighthouse_tpu.store import SqliteStore

    kv = SqliteStore(args.path)
    if args.db_cmd == "inspect":
        from lighthouse_tpu.store.hot_cold import (
            COL_BLOCK,
            COL_COLD_STATE,
            COL_HOT_STATE,
        )
        from lighthouse_tpu.store.schema import get_schema_version

        print(
            json.dumps(
                {
                    "schema_version": get_schema_version(kv),
                    "blocks": len(kv.keys(COL_BLOCK)),
                    "hot_states": len(kv.keys(COL_HOT_STATE)),
                    "cold_states": len(kv.keys(COL_COLD_STATE)),
                }
            )
        )
        return 0
    if args.db_cmd == "version":
        from lighthouse_tpu.store.schema import (
            CURRENT_SCHEMA_VERSION,
            get_schema_version,
        )

        print(
            json.dumps(
                {
                    "schema_version": get_schema_version(kv),
                    "current": CURRENT_SCHEMA_VERSION,
                }
            )
        )
        return 0
    if args.db_cmd == "migrate":
        from lighthouse_tpu.store.schema import (
            CURRENT_SCHEMA_VERSION,
            migrate_schema,
        )

        target = (
            args.target if args.target is not None
            else CURRENT_SCHEMA_VERSION
        )
        final = migrate_schema(kv, target=target)
        print(json.dumps({"schema_version": final}))
        return 0
    raise SystemExit(f"unknown db command {args.db_cmd}")


def cmd_boot_node(args):
    """Standalone bootstrap-node entry point (`lighthouse boot_node`,
    boot_node/src). The registry here is in-process: simulated nodes join
    it directly (network.discovery.BootstrapRegistry is how the node-sim
    wires discovery); there is no wire listener yet."""
    from lighthouse_tpu.network.discovery import (
        BootstrapRegistry,
        PeerRecord,
    )

    registry = BootstrapRegistry()
    node_id = args.node_id or "boot"
    registry.register(PeerRecord(node_id=node_id))
    print(
        json.dumps(
            {
                "node_id": node_id,
                "role": "boot_node",
                "peers": len(registry.records),
            }
        )
    )
    if args.serve_seconds:
        time.sleep(args.serve_seconds)
    return 0


def build_parser():
    p = argparse.ArgumentParser(prog="lighthouse_tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    bn = sub.add_parser("bn", help="beacon node")
    bn.add_argument("--network", default="minimal")
    bn.add_argument("--validators", type=int, default=32)
    bn.add_argument("--slots", type=int, default=8)
    bn.add_argument("--http-port", type=int, default=0)
    bn.add_argument("--http-address", default="127.0.0.1")
    bn.add_argument("--datadir", default=None)
    bn.add_argument(
        "--purge-db",
        action="store_true",
        help="delete the datadir before boot (fork-revert recovery)",
    )
    bn.add_argument(
        "--slots-per-restore-point",
        type=int,
        default=0,
        help="freezer restore-point interval (0 = spec default)",
    )
    bn.add_argument("--bls-backend", default="ref")
    bn.add_argument("--serve-seconds", type=float, default=0)
    bn.add_argument(
        "--checkpoint-state",
        default=None,
        help="SSZ file with a trusted finalized state (checkpoint sync)",
    )
    bn.add_argument(
        "--checkpoint-block",
        default=None,
        help="SSZ file with the block matching --checkpoint-state",
    )
    bn.add_argument(
        "--testnet-dir",
        default=None,
        help="network directory (config.yaml + genesis.ssz) to boot from",
    )
    bn.add_argument(
        "--checkpoint-sync-url",
        default=None,
        help="trusted beacon node URL to fetch the finalized "
        "state/block from (weak-subjectivity boot)",
    )
    bn.add_argument(
        "--trace-buffer",
        type=int,
        default=256,
        help="span-tracer ring capacity in root spans, served at GET "
        "/lighthouse/spans (0 disables span-tree buffering; the "
        "*_stage_seconds histograms keep recording)",
    )
    bn.add_argument(
        "--trace-jsonl",
        default=None,
        help="write the buffered span trees to this JSONL file on "
        "shutdown (bench attribution input)",
    )
    bn.add_argument(
        "--journal-buffer",
        type=int,
        default=4096,
        help="lifecycle event-journal ring capacity, served at GET "
        "/lighthouse/events (0 disables the journal entirely; the "
        "underlying subsystem counters keep counting)",
    )
    bn.add_argument(
        "--journal-jsonl",
        default=None,
        help="write the buffered journal events to this JSONL file on "
        "shutdown (chaos-run forensics input)",
    )
    bn.add_argument(
        "--compile-ledger",
        default=None,
        help="append every COLD jit (re)compile event to this "
        "persistent JSONL ledger (warm dispatches stay in the "
        "in-memory ring served at GET /lighthouse/compiles; env "
        "LIGHTHOUSE_TPU_COMPILE_LEDGER is the flagless spelling)",
    )
    bn.add_argument(
        "--admission-limits",
        default=None,
        help="per-class HTTP admission overrides, "
        "'cls=concurrency:deadline_s,...' (classes: cheap_read, "
        "expensive_read, write) — the PR 10 hand-set constants as a "
        "control surface, mirrored at /lighthouse/health",
    )
    bn.add_argument(
        "--bus-max-hold-ms",
        type=float,
        default=None,
        help="verification bus: maximum milliseconds a submission may "
        "hold waiting for co-riders (default: 25 on the tpu backend, "
        "0 — attributed passthrough — on host backends)",
    )
    bn.add_argument(
        "--bus-fill-target",
        type=int,
        default=0,
        help="verification bus: pending live sets that close a batch "
        "(one pow2 lane bucket's worth; 0 keeps the default 64)",
    )
    bn.add_argument(
        "--bus-deadlines",
        default=None,
        help="verification bus per-class deadline budgets, "
        "'consumer=seconds,...' over the closed consumer vocabulary "
        "(gossip classes default to the slot clock's 1/3-slot window)",
    )
    bn.add_argument(
        "--device-breaker-threshold",
        type=int,
        default=None,
        help="device-plane circuit breaker: consecutive faults on a "
        "(plane, shape-bucket) that open it (default 3)",
    )
    bn.add_argument(
        "--device-breaker-cooldown-ms",
        type=float,
        default=None,
        help="device-plane circuit breaker: milliseconds an open "
        "breaker waits before admitting one half-open probe "
        "(default 30000)",
    )
    bn.add_argument(
        "--device-breaker-canary",
        choices=["auto", "on", "off"],
        default=None,
        help="canary sentinel checks on shared device batches: auto "
        "(tpu backend or armed fault injection — the default), on, "
        "or off",
    )
    bn.add_argument(
        "--slot-fuse",
        choices=["on", "off"],
        default=None,
        help="one-dispatch slot: chain tree-hash, signature fold and "
        "KZG settle of a blob import into a single guarded device "
        "dispatch (default on; off restores the serial "
        "three-dispatch path)",
    )
    bn.add_argument(
        "--slot-budget",
        choices=["on", "off"],
        default=None,
        help="slot-budget profiler: per-import critical-path recording "
        "behind GET /lighthouse/slot_budget (default on; off skips "
        "even the per-import begin/finish bookkeeping)",
    )
    bn.add_argument(
        "--slot-budget-ring",
        type=int,
        default=None,
        help="recent-import waterfalls kept for /lighthouse/slot_budget "
        "(default 128)",
    )
    bn.add_argument(
        "--device-breaker-selftest",
        choices=["on", "off"],
        default="off",
        help="run the per-plane known-answer self-test at boot; a "
        "failing plane starts quarantined on host tiers (default off)",
    )
    bn.set_defaults(fn=cmd_bn)

    vc = sub.add_parser("vc", help="validator client")
    vc.add_argument("--network", default="minimal")
    vc.add_argument("--validators", type=int, default=32)
    vc.add_argument("--slots", type=int, default=8)
    vc.add_argument("--slashing-db", default=None)
    vc.add_argument("--bls-backend", default="ref")
    vc.add_argument(
        "--beacon-node-url",
        action="append",
        default=None,
        help="beacon node REST URL; repeat for a ranked fallback list "
        "— the VC then talks HTTP only (HttpValidatorClient), never "
        "an in-process chain",
    )
    vc.set_defaults(fn=cmd_vc)

    acct = sub.add_parser("account", help="keys & keystores")
    acct.add_argument(
        "account_cmd",
        choices=["new", "import", "wallet-create", "wallet-next"],
    )
    acct.add_argument("--password", required=True)
    acct.add_argument("--kdf", default="pbkdf2")
    acct.add_argument("--mnemonic", default=None)
    acct.add_argument("--seed", default=None)
    acct.add_argument("--index", type=int, default=0)
    acct.add_argument("--name", default="wallet")
    acct.add_argument("--wallet", default=None)
    acct.add_argument("--keystore-password", default=None)
    acct.add_argument("--out", default=None)
    acct.add_argument("--keystore", default=None)
    acct.set_defaults(fn=cmd_account)

    lcli = sub.add_parser("lcli", help="dev tools")
    lcli.add_argument(
        "lcli_cmd",
        choices=["skip-slots", "transition-blocks", "new-testnet"],
    )
    lcli.add_argument("--network", default="minimal")
    lcli.add_argument("--validators", type=int, default=16)
    lcli.add_argument("--slots", type=int, default=8)
    lcli.add_argument("--genesis-time", type=int, default=0)
    lcli.add_argument("--out", default="genesis.ssz")
    lcli.add_argument(
        "--testnet-dir",
        default=None,
        help="write a full network dir (config.yaml + genesis.ssz)",
    )
    lcli.set_defaults(fn=cmd_lcli)

    db = sub.add_parser("db", help="database tools")
    db.add_argument("db_cmd", choices=["inspect", "version", "migrate"])
    db.add_argument("--path", required=True)
    db.add_argument("--target", type=int, default=None)
    db.set_defaults(fn=cmd_db)

    boot = sub.add_parser("boot_node", help="discovery bootstrap node")
    boot.add_argument("--node-id", default=None)
    boot.add_argument("--serve-seconds", type=float, default=0)
    boot.set_defaults(fn=cmd_boot_node)
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
