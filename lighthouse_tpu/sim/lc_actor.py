"""Light-client sim actor: one trusted root in, the honest head out.

The lc_serve scenario's consumer: bootstraps a `LightClientStore` from
ONE trusted finalized root read off the serving node's REST surface,
then tracks the chain exclusively through the light-client endpoints —
updates by range for period advancement, the finality/optimistic
documents every slot. Its sync-committee aggregate checks ride the
serving node's verification bus under the ``light_client`` consumer
label (the actor is in-process; a remote client would carry its own
BLS plane), so the attribution/bus invariants see the new traffic
class.

Evidence discipline: the actor's protocol PROGRESS is exported through
the registry families the store maintains
(``lighthouse_tpu_lc_client_proofs_total`` / ``_updates_total``); its
`summary()` is DRIVING context handed to the invariants — they compare
it against the node's own observability plane, never against node
internals.
"""

from lighthouse_tpu.common.logging import get_logger
from lighthouse_tpu.http_api.client import (
    ApiClientError,
    BeaconNodeHttpClient,
)
from lighthouse_tpu.light_client.store import (
    LightClientError,
    LightClientStore,
)
from lighthouse_tpu.types.containers import types_for

_LOG = get_logger("sim.lc_actor")


class LightClientActor:
    def __init__(self, base_url: str, spec, gvr: bytes, bus=None):
        self.client = BeaconNodeHttpClient(base_url)
        self.spec = spec
        self.t = types_for(spec)
        self.gvr = bytes(gvr)
        self.bus = bus
        self.store = None
        self.requests = 0
        self.errors = 0
        self.trusted_root = None

    # ------------------------------------------------------------ plumbing

    def _verify(self, sets) -> bool:
        if self.bus is not None:
            return self.bus.submit(sets, consumer="light_client")
        from lighthouse_tpu import bls

        return bls.verify_signature_sets(
            sets, consumer="light_client"
        )

    def _get(self, fn, *args):
        self.requests += 1
        try:
            return fn(self.t, *args)
        except ApiClientError as e:
            self.errors += 1
            _LOG.debug("lc actor request failed: %s", e)
            return None

    # ------------------------------------------------------------- driving

    def _try_bootstrap(self):
        """Bootstrap once the provider has finalized: the finalized
        block root read off the REST surface is the ONE trusted input;
        everything after is proven."""
        try:
            cps = self.client.get_finality_checkpoints("head")
        except ApiClientError:
            return
        if int(cps["finalized"]["epoch"]) < 1:
            return
        try:
            root = self.client.get_block_root("finalized")
        except ApiClientError:
            return
        bootstrap = self._get(self.client.get_lc_bootstrap, root)
        if bootstrap is None:
            return
        store = LightClientStore(
            self.spec,
            self.t,
            self.gvr,
            root,
            verify=self._verify,
        )
        try:
            store.process_bootstrap(bootstrap)
        except LightClientError as e:
            _LOG.warning("lc bootstrap rejected: %s", e)
            self.errors += 1
            return
        self.store = store
        self.trusted_root = root

    def poll(self):
        """One polling round: bootstrap if needed, then advance through
        range updates + the finality/optimistic documents."""
        if self.store is None:
            self._try_bootstrap()
            if self.store is None:
                return
        store = self.store
        updates = self._get(
            self.client.get_lc_updates, store.current_period, 4
        )
        for update in updates or ():
            try:
                store.process_update(update)
            except LightClientError as e:
                _LOG.debug("lc update rejected: %s", e)
        fu = self._get(self.client.get_lc_finality_update)
        if fu is not None:
            try:
                store.process_finality_update(fu)
            except LightClientError as e:
                _LOG.debug("lc finality update rejected: %s", e)
        ou = self._get(self.client.get_lc_optimistic_update)
        if ou is not None:
            try:
                store.process_optimistic_update(ou)
            except LightClientError as e:
                _LOG.debug("lc optimistic update rejected: %s", e)

    def summary(self) -> dict:
        doc = {
            "bootstrapped": self.store is not None,
            "trusted_root": (
                "0x" + self.trusted_root.hex()
                if self.trusted_root
                else None
            ),
            "requests": self.requests,
            "errors": self.errors,
        }
        if self.store is not None:
            doc.update(self.store.summary())
        return doc
