"""Seeded network conditioner: the adversarial-delivery edge of the sim.

Sits on `SocketNet`'s outbound path (gossip frames and RPC calls) and
decides, per directed peer pair, whether a message is delivered, dropped,
duplicated, delayed, or reordered — plus hard partition masks the
scenario timeline schedules (split-brain partitions, eclipse of one
node, offline windows).

Determinism contract: every gossip decision is a pure function of
``(seed, src, dst, message_id)`` and every RPC decision a pure function
of ``(seed, src, dst, method, per-pair call index)``. Gossip keys on the
MESSAGE ID rather than a call counter on purpose — forwarding order
between threads can differ run to run (whichever reader thread delivers
first forwards first), but the same message on the same pair always
draws the same fate, so the DELIVERY OUTCOME of the whole flood is
replayable from the seed alone. RPC calls are issued sequentially from
the orchestrator-driven sync path, so a per-pair counter is already
deterministic there.

Delay/reorder carry no wall clock: a held frame is released after N
later frames pass on the pair (``hold`` in the plan), and the simulator
force-flushes holds at every slot barrier.

Link-shape DISTRIBUTIONS ride on top of the fate draw (PR 10): every
delivered frame on a pair pays the policy's base ``latency_holds``, a
seeded uniform jitter draw in ``[0, latency_jitter_holds]`` (same rng,
same purity contract), and — under ``bandwidth_bytes_per_hold`` — one
extra hold per that many payload bytes, so serialization delay is a
pure function of message size. All in hold units: wall-clock-free,
byte-identically replayable.
"""

import random
import threading
from dataclasses import dataclass, field

from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.network.rpc import RpcError

_ACTIONS = REGISTRY.counter_vec(
    "lighthouse_tpu_sim_conditioner_actions_total",
    "network-conditioner decisions on outbound gossip frames "
    "(deliver|drop|duplicate|delay|reorder|dist_hold|partition_block)",
    ("action",),
)
_RPC_FAULTS = REGISTRY.counter_vec(
    "lighthouse_tpu_sim_rpc_faults_total",
    "network-conditioner decisions on outbound RPC calls "
    "(partition_block|stall)",
    ("kind",),
)

# `status` is exempt from seeded stalls (partition masks still apply):
# the sync manager's status cache refreshes on a wall-clock TTL, so the
# NUMBER of status calls varies run to run — letting them consume seeded
# fault draws would leak wall-clock timing into the replay.
RPC_STALL_EXEMPT = frozenset({"status"})


@dataclass
class GossipPlan:
    """What to do with one outbound gossip frame: send `copies` of it
    (0 = drop, 2 = duplicate), each after `hold` later frames have
    passed on the pair (0 = immediately)."""

    copies: int = 1
    hold: int = 0


@dataclass
class PairPolicy:
    """Per-directed-pair fault rates (probabilities per message/call)
    plus LINK-SHAPE distributions: every delivered frame on the pair
    pays `latency_holds` base holds, a seeded uniform jitter draw in
    [0, latency_jitter_holds], and — when `bandwidth_bytes_per_hold`
    is set — one extra hold per that many payload bytes (serialization
    delay as a pure function of message size). Holds are frame-count
    based like the delay/reorder plans, so the distributions stay
    wall-clock-free and replay byte-identically from the seed."""

    drop_rate: float = 0.0
    duplicate_rate: float = 0.0
    delay_rate: float = 0.0
    reorder_rate: float = 0.0
    rpc_stall_rate: float = 0.0
    latency_holds: int = 0
    latency_jitter_holds: int = 0
    bandwidth_bytes_per_hold: int = 0

    _RATE_KEYS = (
        "drop_rate", "duplicate_rate", "delay_rate",
        "reorder_rate", "rpc_stall_rate",
    )
    _INT_KEYS = (
        "latency_holds", "latency_jitter_holds",
        "bandwidth_bytes_per_hold",
    )

    @classmethod
    def from_dict(cls, doc: dict) -> "PairPolicy":
        kwargs = {
            k: float(doc[k]) for k in cls._RATE_KEYS if k in doc
        }
        kwargs.update(
            {k: int(doc[k]) for k in cls._INT_KEYS if k in doc}
        )
        return cls(**kwargs)


@dataclass
class NetworkConditioner:
    seed: int = 0
    default: PairPolicy = field(default_factory=PairPolicy)
    # (src, dst) -> PairPolicy overrides
    pairs: dict = field(default_factory=dict)

    def __post_init__(self):
        self._lock = threading.Lock()
        # partition state: list of frozensets; nodes absent from every
        # group form one implicit extra group
        self._groups: list = []
        self._isolated: set = set()
        self._offline: set = set()
        self._rpc_counts: dict = {}

    # -------------------------------------------------------- masks

    def set_partition(self, groups):
        """Schedule a partition: traffic crosses only WITHIN a group."""
        with self._lock:
            self._groups = [frozenset(g) for g in groups]

    def clear_partition(self):
        with self._lock:
            self._groups = []

    def isolate(self, node_id: str):
        """Eclipse `node_id`: block every pair that touches it."""
        with self._lock:
            self._isolated.add(node_id)

    def release(self, node_id: str):
        with self._lock:
            self._isolated.discard(node_id)

    def set_offline(self, node_id: str, offline: bool):
        """An offline node is unreachable in BOTH directions (its
        sockets are also closed by the orchestrator; the mask keeps
        stragglers deterministic)."""
        with self._lock:
            if offline:
                self._offline.add(node_id)
            else:
                self._offline.discard(node_id)

    def blocked(self, src: str, dst: str) -> bool:
        with self._lock:
            if src in self._offline or dst in self._offline:
                return True
            if src in self._isolated or dst in self._isolated:
                return True
            if self._groups:
                g_src = next(
                    (g for g in self._groups if src in g), None
                )
                g_dst = next(
                    (g for g in self._groups if dst in g), None
                )
                # absent nodes share the implicit remainder group (None)
                if g_src is not g_dst:
                    return True
        return False

    # ------------------------------------------------------ decisions

    def _policy(self, src: str, dst: str) -> PairPolicy:
        return self.pairs.get((src, dst), self.default)

    def plan_gossip(
        self, src: str, dst: str, mid: bytes, size: int = 0
    ) -> GossipPlan:
        """The fate of one outbound frame. `size` (payload bytes) feeds
        the pair's bandwidth model; every decision — fate draw, delay
        length, latency jitter — comes from ONE rng seeded on
        (seed, pair, message-id), so the whole plan is a pure function
        of those plus the message size."""
        if self.blocked(src, dst):
            _ACTIONS.labels("partition_block").inc()
            return GossipPlan(copies=0)
        pol = self._policy(src, dst)
        rng = random.Random(f"{self.seed}:g:{src}>{dst}:{mid.hex()}")
        r = rng.random()
        plan = None
        edge = pol.drop_rate
        if r < edge:
            _ACTIONS.labels("drop").inc()
            return GossipPlan(copies=0)
        edge += pol.duplicate_rate
        if plan is None and r < edge:
            _ACTIONS.labels("duplicate").inc()
            plan = GossipPlan(copies=2)
        edge += pol.delay_rate
        if plan is None and r < edge:
            _ACTIONS.labels("delay").inc()
            plan = GossipPlan(copies=1, hold=rng.randrange(2, 4))
        edge += pol.reorder_rate
        if plan is None and r < edge:
            _ACTIONS.labels("reorder").inc()
            plan = GossipPlan(copies=1, hold=1)
        if plan is None:
            _ACTIONS.labels("deliver").inc()
            plan = GossipPlan()
        # link-shape distributions ride on top of the fate: base
        # latency, seeded per-message jitter, and a size-proportional
        # serialization delay — all in hold units (wall-clock-free)
        extra = pol.latency_holds
        if pol.latency_jitter_holds > 0:
            extra += rng.randrange(0, pol.latency_jitter_holds + 1)
        if pol.bandwidth_bytes_per_hold > 0 and size > 0:
            extra += size // pol.bandwidth_bytes_per_hold
        if extra > 0:
            _ACTIONS.labels("dist_hold").inc()
            plan.hold += extra
        return plan

    def check_rpc(self, src: str, dst: str, method: str):
        """Raise the fault (if any) for this outbound RPC call. Raises
        RpcError(2, ...) — the wire timeout shape — for partition
        blocks and seeded stalls; returns None to let the call through."""
        if self.blocked(src, dst):
            _RPC_FAULTS.labels("partition_block").inc()
            raise RpcError(2, f"sim: {src}->{dst} partitioned")
        pol = self._policy(src, dst)
        if pol.rpc_stall_rate <= 0 or method in RPC_STALL_EXEMPT:
            return
        with self._lock:
            key = (src, dst, method)
            n = self._rpc_counts.get(key, 0)
            self._rpc_counts[key] = n + 1
        rng = random.Random(f"{self.seed}:r:{src}>{dst}:{method}:{n}")
        if rng.random() < pol.rpc_stall_rate:
            _RPC_FAULTS.labels("stall").inc()
            raise RpcError(2, f"sim: injected stall on {method}")
