"""DAS sampling actor: one per simulated node in column mode.

Role of the reference's PeerDAS sampling loop (`DataAvailability
Sampling` in the fulu design docs): for every column-carrying block a
node hears about, probe a few deterministic column indices against its
peers' serving surfaces and decide — from samples alone, never from
the proposer's word — whether the data behind the block is actually
retrievable. A block whose sampled columns stay unserved after the
sampling deadline is flagged withheld.

The actor is DRIVING machinery (the orchestrator feeds it roots and
polls it each slot), but its evidence runs through the same planes the
invariants read: samples are issued against peers' REST
``/lighthouse/da/columns/{root}?indices=…`` endpoints, every returned
cell re-verifies through the node's verification bus under the
``da_cells`` consumer label (trust-but-verify: a lying serving peer is
a wrong verdict, not a satisfied sample), and every verdict lands in
the node's journal as a ``das_sample`` event plus the ``da_*`` metric
families. Sample-index choice is a pure function of (seed, node,
root), so a replay issues the identical probes.
"""

import hashlib
import json
import urllib.error
import urllib.request

from lighthouse_tpu.common.logging import get_logger
from lighthouse_tpu.common.metrics import REGISTRY

_LOG = get_logger("sim.das")

_SAMPLES = REGISTRY.counter_vec(
    "lighthouse_tpu_da_samples_total",
    "DAS sampler probes by outcome (issued|satisfied|unsatisfied|"
    "verify_failed)",
    ("outcome",),
)
_FLAGS = REGISTRY.counter(
    "lighthouse_tpu_da_withholding_flags_total",
    "column-carrying blocks a DAS sampler flagged as withheld "
    "(sampling deadline passed with unserved sampled columns)",
)

# polls (slots) a sample may stay unserved before the block is flagged
FLAG_AFTER_POLLS = 2


class DasSampler:
    """Samples column availability for one node against its peers."""

    def __init__(
        self,
        name: str,
        spec,
        journal,
        bus,
        peer_urls,
        samples_per_slot: int,
        seed: int = 0,
        backend: str = "ref",
    ):
        """`peer_urls` is a callable returning the base URLs of the
        node's currently-online peers (the orchestrator's view — a
        sampler never probes a socket it knows is down)."""
        from lighthouse_tpu.da.domain import geometry_for_spec

        self.name = name
        self.geo = geometry_for_spec(spec)
        self.journal = journal
        self.bus = bus
        self.peer_urls = peer_urls
        self.samples_per_slot = int(samples_per_slot)
        self.seed = int(seed)
        self.backend = backend
        # root hex -> sample state
        self.pending: dict = {}
        self.flagged: list = []
        self.counts = {
            "issued": 0, "satisfied": 0, "verify_failed": 0,
        }

    # ------------------------------------------------------------ intake

    def _indices_for(self, root_hex: str) -> list:
        """Deterministic distinct column indices for (seed, node, root):
        a seeded hash-chain walk over the column space, so a replayed
        run probes the identical columns."""
        want = min(self.samples_per_slot, self.geo.num_cells)
        out: list = []
        ctr = 0
        while len(out) < want:
            digest = hashlib.sha256(
                f"{self.seed}:{self.name}:{root_hex}:{ctr}".encode()
            ).digest()
            idx = int.from_bytes(digest[:8], "big") % self.geo.num_cells
            if idx not in out:
                out.append(idx)
            ctr += 1
        return out

    def observe_block(self, root_hex: str, slot: int):
        """The orchestrator heard a column-carrying block enter the
        network: issue this node's samples against it."""
        if root_hex in self.pending or self.samples_per_slot <= 0:
            return
        indices = self._indices_for(root_hex)
        self.pending[root_hex] = {
            "slot": slot,
            "indices": indices,
            "satisfied": set(),
            "polls": 0,
        }
        self.counts["issued"] += len(indices)
        _SAMPLES.labels("issued").inc(len(indices))
        self.journal.emit(
            "das_sample",
            root=bytes.fromhex(root_hex[2:]),
            slot=slot,
            outcome="issued",
            n=len(indices),
            indices=",".join(str(i) for i in indices),
        )

    # ------------------------------------------------------------- probes

    def _fetch_column(self, url: str, root_hex: str, index: int):
        req = f"{url}/lighthouse/da/columns/{root_hex}?indices={index}"
        try:
            with urllib.request.urlopen(req, timeout=10) as r:
                docs = json.loads(r.read())["data"]
        except (urllib.error.URLError, OSError, ValueError) as e:
            _LOG.debug("%s sample fetch failed: %s", self.name, e)
            return None
        return docs[0] if docs else None

    def _verify_sidecar(self, doc: dict, slot: int) -> bool:
        """Re-verify a served column's cell proofs through the bus under
        the da_cells consumer — a sample is satisfied only by data that
        PROVES against the block's commitments."""
        index = int(doc["index"])
        items = [
            (
                bytes.fromhex(c[2:]),
                index,
                bytes.fromhex(cell[2:]),
                bytes.fromhex(p[2:]),
            )
            for c, cell, p in zip(
                doc["kzg_commitments"], doc["column"], doc["kzg_proofs"],
                strict=True,
            )
        ]
        return self.bus.submit_cells(
            items,
            self.geo,
            backend=self.backend,
            journal=self.journal,
            slot=slot,
        )

    def poll(self, slot: int):
        """One sampling round: probe every unsatisfied index of every
        pending block against the online peers; flag blocks whose
        samples outlived the deadline."""
        for root_hex, st in sorted(self.pending.items()):
            missing = [
                i for i in st["indices"] if i not in st["satisfied"]
            ]
            if not missing:
                continue
            urls = list(self.peer_urls())
            for index in missing:
                for url in urls:
                    doc = self._fetch_column(url, root_hex, index)
                    if doc is None:
                        continue
                    if self._verify_sidecar(doc, slot):
                        st["satisfied"].add(index)
                        self.counts["satisfied"] += 1
                        _SAMPLES.labels("satisfied").inc()
                        self.journal.emit(
                            "das_sample",
                            root=bytes.fromhex(root_hex[2:]),
                            slot=slot,
                            outcome="satisfied",
                            index=index,
                        )
                    else:
                        # served data that fails its own proof: the
                        # das_no_wrong_verdicts invariant holds this
                        # counter to zero on honest runs
                        self.counts["verify_failed"] += 1
                        _SAMPLES.labels("verify_failed").inc()
                        self.journal.emit(
                            "das_sample",
                            root=bytes.fromhex(root_hex[2:]),
                            slot=slot,
                            outcome="verify_failed",
                            index=index,
                        )
                    break
            st["polls"] += 1
            still = [
                i for i in st["indices"] if i not in st["satisfied"]
            ]
            if still and st["polls"] >= FLAG_AFTER_POLLS:
                if root_hex not in self.flagged:
                    self.flagged.append(root_hex)
                    _SAMPLES.labels("unsatisfied").inc(len(still))
                    _FLAGS.inc()
                    self.journal.emit(
                        "das_sample",
                        root=bytes.fromhex(root_hex[2:]),
                        slot=slot,
                        outcome="withheld_flagged",
                        missing=len(still),
                        indices=",".join(str(i) for i in still),
                    )

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        """The health-plane view (/lighthouse/health doc.da.sampling)."""
        outstanding = sum(
            1
            for st in self.pending.values()
            if len(st["satisfied"]) < len(st["indices"])
        )
        return {
            "blocks_sampled": len(self.pending),
            "samples_issued": self.counts["issued"],
            "samples_satisfied": self.counts["satisfied"],
            "verify_failed": self.counts["verify_failed"],
            "outstanding_blocks": outstanding,
            "withheld_flagged": list(self.flagged),
        }
