"""Declarative scenario spec for the network simulator.

A scenario is one JSON document: topology (nodes, validator split),
spec overrides, conditioner fault rates, blob schedule, and a fault
TIMELINE — slot-indexed windows of partitions, eclipses, offline nodes,
spam floods, RPC floods, and kv crashes — plus the invariant list the
run must satisfy. `scripts/sim.py --list` validates every committed
file in `lighthouse_tpu/sim/scenarios/` against this spec (wired into
tier-1), so the library cannot rot.

The schema is deliberately closed: unknown keys, unknown fault kinds,
and out-of-range windows are validation ERRORS, not warnings — a typo'd
fault that silently never fires would make a chaos run test nothing.
"""

import json
import os
from dataclasses import dataclass, field

FAULT_KINDS = (
    "partition",   # groups: [[node-index, ...], ...]
    "eclipse",     # node: index — all pairs touching it blocked
    "offline",     # node: index — down at at_slot, restarts at until_slot
    "spam_flood",  # node: name/index — junk blob-sidecar gossip, rate/slot
    "rpc_flood",   # node: name/index — req/resp burst per slot at rate
    "kv_crash",    # node: index — torn-WAL crash at at_slot, reboot+resync
    "att_flood",   # node: ACTOR name/index — junk attestation gossip,
                   # rate/slot (drives the processor shed plane)
    "rest_flood",  # node: TARGET name/index — concurrent REST read
                   # bursts against that node's HTTP API, rate threads
    # device-plane fault injection (device_plane/faults.FaultInjector,
    # armed/disarmed on the window edges; `plane` picks the target,
    # default "bls"). Every guarded dispatch in the window faults —
    # injection is deterministic, so replay is byte-identical.
    "device_stall",         # dispatches hang -> watchdog + failover
    "device_error",         # dispatches raise -> breaker + failover
    "device_flip",          # device LIES -> canary catches, quarantine
    "device_slow_compile",  # injected compile delay (bounded)
    "das_withhold",  # node: PROPOSER index — while the window is
                     # active, its column-carrying proposals publish
                     # the block but serve only `rate` of the columns
                     # (rate < half withholds the data; samplers must
                     # flag it). Requires das.column_mode.
)

# guarded device planes a device_* fault may target (executor dispatch
# plane labels)
DEVICE_PLANES = (
    "bls", "kzg", "merkle_proof", "msm", "sharded",
    "rs_extend", "da_cells",
)

SCENARIO_KINDS = ("multi_node", "vc_http", "lc_serve")

INVARIANT_NAMES = (
    "honest_convergence",
    "exactly_once_imports",
    "da_completeness",
    "bounded_scores",
    "no_honest_quarantine",
    "eclipse_rejoin",
    "spam_priced",
    "faults_fired",
    "attribution_complete",
    "budget_complete",
    "bus_no_starvation",
    "finalized",
    "sheds_bounded",
    "overload_reported",
    "overload_recovery",
    "lc_tracks_finality",
    "lc_proofs_verify",
    "lc_served_bounded",
    "device_faults_caught",
    "device_no_wrong_verdicts",
    "device_breaker_balanced",
    "das_convergence",
    "das_withheld_flagged",
    "das_no_wrong_verdicts",
)

_CONDITIONER_RATE_KEYS = {
    "drop_rate", "duplicate_rate", "delay_rate", "reorder_rate",
    "rpc_stall_rate",
}
# link-shape distribution knobs: non-negative integers in hold units
# (see sim/conditioner.PairPolicy)
_CONDITIONER_INT_KEYS = {
    "latency_holds", "latency_jitter_holds", "bandwidth_bytes_per_hold",
}
_CONDITIONER_KEYS = _CONDITIONER_RATE_KEYS | _CONDITIONER_INT_KEYS

_TOP_KEYS = {
    "name", "kind", "seed", "nodes", "validators", "slots", "backend",
    "spec", "blob_slots", "conditioner", "faults", "invariants",
    "journal_capacity", "adversaries", "description",
    "processor_bounds", "das",
}

# the data-availability-sampling block: column_mode flips every node's
# DA gate from blob sidecars to column sidecars; samples_per_slot is
# how many distinct column indices each node's sampler probes per
# column-carrying block
_DAS_KEYS = {"column_mode", "samples_per_slot"}

_FAULT_KEYS = {
    "kind", "at_slot", "until_slot", "node", "groups", "rate", "plane",
}


class ScenarioError(Exception):
    pass


@dataclass
class FaultSpec:
    kind: str
    at_slot: int
    until_slot: int | None = None
    node: object = None       # node index (int) or adversary name (str)
    groups: list | None = None
    rate: int = 4
    plane: str = "bls"        # device_* faults: guarded plane to hit

    def active(self, slot: int) -> bool:
        if slot < self.at_slot:
            return False
        return self.until_slot is None or slot < self.until_slot


@dataclass
class Scenario:
    name: str
    seed: int = 0
    kind: str = "multi_node"
    nodes: int = 5
    validators: int = 40
    slots: int = 16
    backend: str = "fake"
    spec_overrides: dict = field(default_factory=dict)
    blob_slots: list = field(default_factory=list)
    conditioner: dict = field(default_factory=dict)
    faults: list = field(default_factory=list)
    invariants: list = field(default_factory=list)
    journal_capacity: int = 16384
    # per-run beacon-processor queue-bound overrides (kind -> bound):
    # overload scenarios shrink a queue so a seeded flood crosses the
    # shedding policy's high-water mark within one slot
    processor_bounds: dict = field(default_factory=dict)
    # extra validator-less nodes available as fault actors (spammers)
    adversaries: list = field(default_factory=list)
    description: str = ""
    # DAS config: {"column_mode": bool, "samples_per_slot": int}
    das: dict = field(default_factory=dict)

    @property
    def honest_names(self) -> list:
        return [f"node{i}" for i in range(self.nodes)]

    def node_name(self, ref) -> str:
        """Resolve a fault's `node` reference: an int indexes the honest
        nodes, a string names an adversary."""
        if isinstance(ref, int):
            return f"node{ref}"
        return str(ref)


def _err(name, msg):
    raise ScenarioError(f"scenario {name!r}: {msg}")


def validate(doc: dict) -> Scenario:
    """Parse + validate one scenario document; raises ScenarioError
    with a precise message on any schema violation."""
    if not isinstance(doc, dict):
        raise ScenarioError("scenario document must be a JSON object")
    name = doc.get("name")
    if not isinstance(name, str) or not name:
        raise ScenarioError("scenario needs a non-empty 'name'")
    unknown = set(doc) - _TOP_KEYS
    if unknown:
        _err(name, f"unknown keys {sorted(unknown)}")
    kind = doc.get("kind", "multi_node")
    if kind not in SCENARIO_KINDS:
        _err(name, f"unknown kind {kind!r} (one of {SCENARIO_KINDS})")
    for key, typ in (
        ("seed", int), ("nodes", int), ("validators", int),
        ("slots", int), ("journal_capacity", int),
    ):
        if key in doc and not isinstance(doc[key], int):
            _err(name, f"{key!r} must be an integer")
    slots = doc.get("slots", 16)
    nodes = doc.get("nodes", 5)
    if slots < 1:
        _err(name, "'slots' must be >= 1")
    if kind == "multi_node" and not 2 <= nodes <= 16:
        _err(name, "'nodes' must be in [2, 16]")
    cond = doc.get("conditioner", {})
    if not isinstance(cond, dict):
        _err(name, "'conditioner' must be an object")
    bad = set(cond) - _CONDITIONER_KEYS
    if bad:
        _err(name, f"unknown conditioner keys {sorted(bad)}")
    for k, v in cond.items():
        if k in _CONDITIONER_INT_KEYS:
            if not isinstance(v, int) or v < 0:
                _err(
                    name,
                    f"conditioner {k!r} must be a non-negative integer",
                )
        elif not isinstance(v, (int, float)) or not 0 <= v <= 1:
            _err(name, f"conditioner {k!r} must be a rate in [0, 1]")
    blob_slots = doc.get("blob_slots", [])
    if not all(
        isinstance(s, int) and 1 <= s <= slots for s in blob_slots
    ):
        _err(name, "'blob_slots' must be slot numbers within the run")
    adversaries = doc.get("adversaries", [])
    if not all(isinstance(a, str) and a for a in adversaries):
        _err(name, "'adversaries' must be a list of names")

    das = doc.get("das", {})
    if not isinstance(das, dict):
        _err(name, "'das' must be an object")
    bad = set(das) - _DAS_KEYS
    if bad:
        _err(name, f"unknown das keys {sorted(bad)}")
    if "column_mode" in das and not isinstance(das["column_mode"], bool):
        _err(name, "das 'column_mode' must be a boolean")
    sps = das.get("samples_per_slot", 0)
    if not isinstance(sps, int) or sps < 0:
        _err(name, "das 'samples_per_slot' must be a non-negative int")
    if sps and not das.get("column_mode"):
        _err(name, "das sampling requires 'column_mode': true")

    faults = []
    for i, f in enumerate(doc.get("faults", [])):
        if not isinstance(f, dict):
            _err(name, f"fault #{i} must be an object")
        bad = set(f) - _FAULT_KEYS
        if bad:
            _err(name, f"fault #{i}: unknown keys {sorted(bad)}")
        fkind = f.get("kind")
        if fkind not in FAULT_KINDS:
            _err(
                name,
                f"fault #{i}: unknown kind {fkind!r} "
                f"(one of {FAULT_KINDS})",
            )
        at = f.get("at_slot")
        if not isinstance(at, int) or not 1 <= at <= slots:
            _err(name, f"fault #{i}: 'at_slot' must be in [1, {slots}]")
        until = f.get("until_slot")
        if until is not None and (
            not isinstance(until, int) or until <= at or until > slots + 1
        ):
            _err(
                name,
                f"fault #{i}: 'until_slot' must be in "
                f"({at}, {slots + 1}]",
            )
        node_ref = f.get("node")
        if fkind == "partition":
            groups = f.get("groups")
            if (
                not isinstance(groups, list)
                or len(groups) < 2
                or not all(
                    isinstance(g, list)
                    and g
                    and all(
                        isinstance(n, int) and 0 <= n < nodes for n in g
                    )
                    for g in groups
                )
            ):
                _err(
                    name,
                    f"fault #{i}: partition needs >= 2 'groups' of "
                    "node indices",
                )
            if until is None:
                _err(name, f"fault #{i}: partition needs 'until_slot'")
        else:
            if node_ref is None:
                _err(name, f"fault #{i}: {fkind} needs 'node'")
            if isinstance(node_ref, int):
                if not 0 <= node_ref < nodes:
                    _err(
                        name,
                        f"fault #{i}: node index {node_ref} out of "
                        f"range [0, {nodes})",
                    )
            elif node_ref not in adversaries:
                _err(
                    name,
                    f"fault #{i}: node {node_ref!r} is not a declared "
                    "adversary",
                )
            if fkind in ("eclipse", "offline") and until is None:
                _err(name, f"fault #{i}: {fkind} needs 'until_slot'")
        if fkind.startswith("device_"):
            # the injector is deterministic and window-scoped: every
            # guarded dispatch in the window faults, so 'rate' has no
            # meaning here — reject it rather than let it silently
            # test nothing (the closed-schema rule)
            if "rate" in f:
                _err(
                    name,
                    f"fault #{i}: {fkind} takes no 'rate' (injection "
                    "is deterministic over the window)",
                )
            if until is None:
                _err(name, f"fault #{i}: {fkind} needs 'until_slot'")
            plane = f.get("plane", "bls")
            if plane not in DEVICE_PLANES:
                _err(
                    name,
                    f"fault #{i}: unknown plane {plane!r} "
                    f"(one of {DEVICE_PLANES})",
                )
        elif "plane" in f:
            _err(
                name,
                f"fault #{i}: 'plane' only applies to device_* faults",
            )
        if fkind == "das_withhold":
            if not das.get("column_mode"):
                _err(
                    name,
                    f"fault #{i}: das_withhold requires das "
                    "'column_mode': true",
                )
            if until is None:
                _err(name, f"fault #{i}: das_withhold needs 'until_slot'")
        rate = f.get("rate", 4)
        # das_withhold's rate is the number of columns SERVED — zero
        # (publish the block, serve nothing) is a legitimate adversary
        rate_floor = 0 if fkind == "das_withhold" else 1
        if not isinstance(rate, int) or rate < rate_floor:
            _err(
                name,
                f"fault #{i}: 'rate' must be an integer >= {rate_floor}",
            )
        faults.append(
            FaultSpec(
                kind=fkind, at_slot=at, until_slot=until,
                node=node_ref, groups=f.get("groups"), rate=rate,
                plane=f.get("plane", "bls"),
            )
        )

    invariants = doc.get("invariants", [])
    for inv in invariants:
        if inv not in INVARIANT_NAMES:
            _err(
                name,
                f"unknown invariant {inv!r} (one of {INVARIANT_NAMES})",
            )
    if any(i.startswith("das_") for i in invariants) and not das.get(
        "column_mode"
    ):
        _err(name, "das_* invariants require das 'column_mode': true")
    if "sheds_bounded" in invariants:
        # the invariant cross-checks per-node-LIFE shed counters (reset
        # on reboot, skipped while offline) against the process-global
        # registry delta, and its flood bound assumes at-most-once
        # delivery per node — scenarios breaking either assumption
        # would report false violations, so the schema refuses them
        incompatible = sorted(
            {f.kind for f in faults if f.kind in ("kv_crash", "offline")}
        )
        if incompatible:
            _err(
                name,
                f"'sheds_bounded' cannot hold across node reboots/"
                f"offline windows (faults: {incompatible})",
            )
        if cond.get("duplicate_rate", 0) > 0:
            _err(
                name,
                "'sheds_bounded' assumes at-most-once delivery per "
                "node; set duplicate_rate to 0",
            )

    spec_overrides = doc.get("spec", {})
    if not isinstance(spec_overrides, dict) or not all(
        isinstance(k, str) for k in spec_overrides
    ):
        _err(name, "'spec' must map override names to values")

    processor_bounds = doc.get("processor_bounds", {})
    if not isinstance(processor_bounds, dict):
        _err(name, "'processor_bounds' must map work kinds to bounds")
    if processor_bounds:
        from lighthouse_tpu.network.beacon_processor import PRIORITIES

        for k, v in processor_bounds.items():
            if k not in PRIORITIES:
                _err(
                    name,
                    f"processor_bounds: unknown work kind {k!r} "
                    f"(one of {sorted(PRIORITIES)})",
                )
            if not isinstance(v, int) or v < 1:
                _err(
                    name,
                    f"processor_bounds[{k!r}] must be a positive "
                    "integer",
                )

    return Scenario(
        name=name,
        kind=kind,
        seed=doc.get("seed", 0),
        nodes=nodes,
        validators=doc.get("validators", 40),
        slots=slots,
        backend=doc.get("backend", "fake"),
        spec_overrides=spec_overrides,
        blob_slots=sorted(blob_slots),
        conditioner=dict(cond),
        faults=faults,
        invariants=list(invariants),
        journal_capacity=doc.get("journal_capacity", 16384),
        adversaries=list(adversaries),
        description=doc.get("description", ""),
        processor_bounds=dict(processor_bounds),
        das=dict(das),
    )


def load_scenario(path: str) -> Scenario:
    with open(path) as f:
        try:
            doc = json.load(f)
        except ValueError as e:
            raise ScenarioError(f"{path}: invalid JSON: {e}") from e
    return validate(doc)


def scenario_library() -> str:
    """The committed scenario directory."""
    return os.path.join(os.path.dirname(__file__), "scenarios")


def list_scenarios(directory: str | None = None) -> list:
    """[(path, Scenario)] for every *.json in the library, validated.
    Raises ScenarioError on the first file that fails to parse."""
    directory = directory or scenario_library()
    out = []
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".json"):
            continue
        path = os.path.join(directory, fname)
        out.append((path, load_scenario(path)))
    return out


def find_scenario(name_or_path: str) -> Scenario:
    """Resolve a CLI argument: a path to a JSON file, or the name of a
    committed library scenario."""
    if os.path.exists(name_or_path):
        return load_scenario(name_or_path)
    path = os.path.join(scenario_library(), name_or_path + ".json")
    if os.path.exists(path):
        return load_scenario(path)
    known = [s.name for _, s in list_scenarios()]
    raise ScenarioError(
        f"no scenario {name_or_path!r} (library: {known})"
    )
