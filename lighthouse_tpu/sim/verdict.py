"""Canonical journal export + the JSONL verdict artifact.

Replay contract: re-running a scenario with the same seed must produce
the IDENTICAL forensic record. Raw journal exports carry wall-clock
timestamps, durations, and ring sequence numbers — artifacts of thread
scheduling, not of protocol behavior — so the comparison surface is a
CANONICAL PROJECTION:

  * only the forensic event kinds (imports, DA lifecycle, sync
    outcomes, scoring, sim faults) — queue-plane events like
    `processor_enqueue` carry depth/batch-size attrs that legitimately
    vary with thread interleaving inside one lockstep step;
  * volatile fields stripped (`t`, `seq`, `duration_s`);
  * events sorted by their full canonical JSON encoding, per node-life.

Two runs of the same seed produce byte-identical canonical JSONL (the
tier-1 seed-determinism gate); a diff in this projection is a REAL
behavioral divergence, never scheduler noise.
"""

import json

# the forensic projection: kinds whose occurrence/content is a protocol
# claim (an import happened, a sidecar verified, a peer paid) rather
# than a scheduling observation (queue depth at enqueue time)
CANONICAL_KINDS = (
    "block_import",
    "block_release",
    "sidecar",
    "da_settle",
    # column-sidecar lifecycle (DA sampling plane): arrival, verify,
    # and reconstruction verdicts are protocol claims — cell_batch
    # (bus coalescing economics) stays OUT like signature_batch
    "column_sidecar",
    "sync_batch",
    "sync_request",
    "peer_downscore",
    "peer_quarantine",
    "sim_fault",
    # shed-window transitions are protocol claims (the overload run's
    # whole point): the lockstep barriers make open/close counts a pure
    # function of the seeded flood volume, so they replay byte-identically
    "shed_window",
    # light-client update production is a pure function of the import
    # stream (period, participation, attested/finalized slots) — a
    # protocol claim that must replay byte-identically. lc_served stays
    # OUT: request/TTL timing attribution, not protocol behavior.
    "lc_update_produced",
    # slot_budget stays OUT (like signature_batch): its content is
    # per-import wall/stage/dispatch timing, which varies run to run
    # even under lockstep — budget_complete reads the raw journal and
    # pairs it 1:1 with the canonical block_import stream instead.
    # device_fault stays OUT (like signature_batch): fault/failover
    # events attach to device BATCHES, whose formation timing varies
    # with thread interleaving inside one lockstep step. The device
    # invariants read the raw journal instead; the window edges
    # (device_fault_armed/disarmed) ride the canonical sim_fault kind.
)

VOLATILE_FIELDS = ("t", "seq", "duration_s")


def canonical_events(docs: list) -> list:
    """Project raw journal docs (Journal.query() shape) onto the
    canonical forensic record: filtered, stripped, sorted."""
    out = []
    for doc in docs:
        if doc.get("kind") not in CANONICAL_KINDS:
            continue
        slim = {
            k: v for k, v in doc.items() if k not in VOLATILE_FIELDS
        }
        out.append(slim)
    return sorted(
        out, key=lambda d: json.dumps(d, sort_keys=True)
    )


def canonical_jsonl(docs: list) -> str:
    lines = [
        json.dumps(d, sort_keys=True) for d in canonical_events(docs)
    ]
    return "\n".join(lines) + ("\n" if lines else "")


def node_journals(sim) -> dict:
    """name -> canonical JSONL covering every LIFE of the node (crash /
    offline archives first, then the live journal)."""
    out = {}
    for sn in sim.nodes:
        docs = []
        for archive in sn.journal_archives:
            docs.extend(archive)
        if sn.node is not None:
            docs.extend(sn.node.chain.journal.query())
        out[sn.name] = canonical_jsonl(docs)
    return out


def build_report(sim, ctx, violations: list) -> dict:
    """The run's verdict document (`scripts/sim.py` writes it as JSONL
    alongside the per-node canonical journals)."""
    from lighthouse_tpu.common.metrics import snapshot_diff

    sc = sim.scenario
    heads = {}
    for sn in sim.nodes:
        if not sn.online:
            heads[sn.name] = None
            continue
        h = ctx.health(sn.name)["head"]
        heads[sn.name] = {
            "slot": h["slot"],
            "root": h["root"],
            "finalized_epoch": h["finalized_epoch"],
        }
    diff = snapshot_diff(ctx.snapshot_before, ctx.snapshot_after)
    sim_series = {
        k: v
        for k, v in sorted(diff.items())
        if k.startswith("lighthouse_tpu_sim_")
        or k.startswith("lighthouse_tpu_sync_")
        or k.startswith("lighthouse_tpu_rpc_")
        or k.startswith("lighthouse_tpu_da_")
    }
    return {
        "scenario": sc.name,
        "kind": sc.kind,
        "seed": sc.seed,
        "slots": sc.slots,
        "nodes": [sn.name for sn in sim.nodes],
        "ok": not violations,
        "violations": list(violations),
        "invariants": list(sc.invariants),
        "heads": heads,
        "blob_blocks": dict(ctx.blob_blocks),
        "registry_diff": sim_series,
        "journals": node_journals(sim),
    }


def write_report(report: dict, out_dir: str) -> list:
    """Write verdict.jsonl (one line per invariant verdict + a summary
    line) and per-node canonical journals; returns written paths."""
    import os

    os.makedirs(out_dir, exist_ok=True)
    paths = []
    verdict_path = os.path.join(out_dir, "verdict.jsonl")
    with open(verdict_path, "w") as f:
        for inv in report["invariants"]:
            f.write(json.dumps({
                "scenario": report["scenario"],
                "seed": report["seed"],
                "invariant": inv,
                "ok": not any(
                    v.startswith(f"[{inv}]")
                    for v in report["violations"]
                ),
                "violations": [
                    v for v in report["violations"]
                    if v.startswith(f"[{inv}]")
                ],
            }, sort_keys=True) + "\n")
        summary = {
            k: v for k, v in report.items() if k != "journals"
        }
        f.write(json.dumps(summary, sort_keys=True) + "\n")
    paths.append(verdict_path)
    for name, jsonl in sorted(report["journals"].items()):
        p = os.path.join(out_dir, f"journal_{name}.jsonl")
        with open(p, "w") as f:
            f.write(jsonl)
        paths.append(p)
    return paths
