"""Invariant checker library: chaos claims proven from the
observability plane ONLY.

Every check in this module reads exactly three surfaces:

  * ``GET /lighthouse/events``  — per-object forensic journal queries,
  * ``GET /lighthouse/health``  — per-node head/finality/peers/DA view,
  * ``Registry.snapshot()`` diffs — process-wide counter deltas,

never node internals (the `test_chaos_forensics_via_observability_plane`
pattern, PR 6). A violation is a human-readable string; a clean run
returns []. The orchestrator records node-life metadata (anchors,
restart slots, eclipse windows) as DRIVING context — checks use it only
to decide what a node should be held accountable for, while the
evidence itself always comes from the three surfaces above.
"""

import json
import re
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

from lighthouse_tpu.network.gossip import BAN_THRESHOLD


@dataclass
class SimContext:
    scenario: object
    nodes: dict                      # name -> SimNode
    snapshot_before: dict
    snapshot_after: dict
    blob_blocks: dict                # "0x…" root -> n blobs
    eclipse_windows: dict            # name -> (at_slot, until_slot)
    # column-mode runs: "0x…" root -> {slot, n_blobs, served, columns,
    # withheld, available} for every column-carrying block (the das_*
    # checks' driving context; evidence still comes from the planes)
    das_blocks: dict = field(default_factory=dict)
    # name -> pre-flood median probe latency (seconds), recorded by the
    # orchestrator BEFORE any overload fault fires — the budget the
    # post-flood recovery check holds the node to
    probe_budget: dict = field(default_factory=dict)
    # light-client actor summary (lc_serve scenarios): DRIVING context
    # naming what the actor believes — the lc_* checks compare it
    # against the node's observability plane
    lc_client: dict | None = None
    _health_cache: dict = field(default_factory=dict)

    # --------------------------------------------- plane accessors

    def _get(self, name: str, path: str) -> dict:
        url = self.nodes[name].base_url() + path
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read())

    def health(self, name: str) -> dict:
        if name not in self._health_cache:
            self._health_cache[name] = self._get(
                name, "/lighthouse/health"
            )["data"]
        return self._health_cache[name]

    def events(self, name: str, **query) -> list:
        qs = urllib.parse.urlencode(
            {k: v for k, v in query.items() if v is not None}
        )
        return self._get(name, f"/lighthouse/events?{qs}")["data"]

    def diff(self, series: str) -> float:
        return self.snapshot_after.get(series, 0) - (
            self.snapshot_before.get(series, 0)
        )

    def diff_family(self, prefix: str) -> float:
        total = 0.0
        for key, after in self.snapshot_after.items():
            if key.startswith(prefix):
                total += after - self.snapshot_before.get(key, 0)
        return total

    # --------------------------------------------- node classification

    def honest_online(self) -> list:
        return [
            name
            for name, sn in sorted(self.nodes.items())
            if sn.index is not None and sn.online
        ]

    def adversaries(self) -> list:
        return [
            name
            for name, sn in sorted(self.nodes.items())
            if sn.index is None
        ]


# ------------------------------------------------------------ invariants


def honest_convergence(ctx: SimContext) -> list:
    """Every honest online node ends on the same head, close to the
    final slot."""
    out = []
    heads = {}
    for name in ctx.honest_online():
        h = ctx.health(name)["head"]
        heads[name] = (h["root"], h["slot"])
        if h["slot"] < ctx.scenario.slots - 2:
            out.append(
                f"{name}: head slot {h['slot']} lags the run end "
                f"({ctx.scenario.slots})"
            )
    roots = {r for r, _ in heads.values()}
    if len(roots) > 1:
        out.append(f"honest heads diverge: {heads}")
    return out


def exactly_once_imports(ctx: SimContext) -> list:
    """No node-life imports the same block twice (gossip, sync, and
    DA-release paths share one journaled terminal)."""
    out = []
    for name in ctx.honest_online():
        seen = {}
        for ev in ctx.events(
            name, kind="block_import", outcome="imported"
        ):
            root = ev.get("root")
            seen[root] = seen.get(root, 0) + 1
        dups = {r: n for r, n in seen.items() if n > 1}
        if dups:
            out.append(f"{name}: blocks imported more than once: {dups}")
    return out


def da_completeness(ctx: SimContext) -> list:
    """Every blob-carrying block a node imported through the DA gate
    shows each of its sidecars individually verified. Blocks below a
    checkpoint anchor were backfilled (blocks-only, the blob-retention
    contract) and are exempt for that node — the block's slot is read
    from whichever node's journal records its import, so a restarted
    node with NO record of a pre-anchor block is exempt too."""
    out = []
    # root -> slot, learned from any honest node's import record
    block_slots = {}
    for name in ctx.honest_online():
        for root_hex in ctx.blob_blocks:
            if root_hex in block_slots:
                continue
            for ev in ctx.events(
                name, root=root_hex, kind="block_import",
                outcome="imported",
            ):
                if ev.get("slot") is not None:
                    block_slots[root_hex] = ev["slot"]
                    break
    for name in ctx.honest_online():
        sn = ctx.nodes[name]
        for root_hex, n in sorted(ctx.blob_blocks.items()):
            blk_slot = block_slots.get(root_hex)
            if blk_slot is not None and blk_slot <= sn.anchor_slot:
                continue  # backfilled history: no DA required
            imports = ctx.events(
                name, root=root_hex, kind="block_import",
                outcome="imported",
            )
            if not imports:
                out.append(f"{name}: blob block {root_hex} not imported")
                continue
            verified = ctx.events(
                name, root=root_hex, kind="sidecar", outcome="verified"
            )
            indices = {e["attrs"]["index"] for e in verified}
            if len(indices) < n:
                out.append(
                    f"{name}: blob block {root_hex} has "
                    f"{len(indices)}/{n} sidecars verified"
                )
        da = ctx.health(name)["da"]
        if da["held_blocks"]:
            out.append(
                f"{name}: {da['held_blocks']} blocks still DA-held at "
                "run end"
            )
    return out


def bounded_scores(ctx: SimContext) -> list:
    """Peer scores stay bounded and ORDERED: honest peers never fall to
    the ban threshold; no node ranks an adversary above its honest
    peers; and every adversary was actually PRICED by at least one node
    it abused — scored strictly below that node's honest floor, or
    banned outright (absent from the peer table while honest peers
    remain). A node the adversary never abused (e.g. one that
    reconnected after the flood window) may legitimately hold it at a
    fresh zero."""
    out = []
    adversaries = set(ctx.adversaries())
    honest = set(ctx.honest_online())
    priced = {a: False for a in adversaries}
    for name in ctx.honest_online():
        peers = ctx.health(name)["peers"]
        scores = (peers.get("scores") or {}).get("by_peer") or {}
        honest_scores = {
            p: s for p, s in scores.items() if p in honest
        }
        for p, s in honest_scores.items():
            if s <= BAN_THRESHOLD:
                out.append(
                    f"{name}: honest peer {p} at ban threshold ({s})"
                )
        if not honest_scores:
            continue
        floor = min(honest_scores.values())
        for adv in adversaries:
            if adv not in scores:
                # adversary banned/disconnected while honest peers
                # remain: the strongest form of pricing
                priced[adv] = True
                continue
            if scores[adv] > floor:
                out.append(
                    f"{name}: adversary {adv} score {scores[adv]} "
                    f"above honest floor {floor}"
                )
            if scores[adv] < floor:
                priced[adv] = True
    for adv, ok in sorted(priced.items()):
        if not ok:
            out.append(
                f"adversary {adv} was never priced below any node's "
                "honest floor"
            )
    return out


def no_honest_quarantine(ctx: SimContext) -> list:
    out = []
    honest = set(ctx.honest_online())
    for name in ctx.honest_online():
        quarantined = set(
            ctx.health(name)["peers"].get("quarantined", [])
        )
        bad = quarantined & honest
        if bad:
            out.append(f"{name}: quarantined honest peers {sorted(bad)}")
    return out


def eclipse_rejoin(ctx: SimContext) -> list:
    """An eclipsed node must show, in its OWN journal, imports covering
    the eclipse window that happened only after the lift event."""
    out = []
    for name, (at, until) in sorted(ctx.eclipse_windows.items()):
        lifts = ctx.events(
            name, kind="sim_fault", outcome="eclipse_lifted"
        )
        if not lifts:
            out.append(f"{name}: no eclipse_lifted event journaled")
            continue
        lift_seq = lifts[0]["seq"]
        caught_up = [
            ev
            for ev in ctx.events(
                name, kind="block_import", outcome="imported"
            )
            if ev["seq"] > lift_seq and at <= ev.get("slot", -1) < until
        ]
        if not caught_up:
            out.append(
                f"{name}: no post-lift imports covering the eclipse "
                f"window [{at}, {until})"
            )
        head = ctx.health(name)["head"]
        honest_heads = {
            ctx.health(n)["head"]["root"]
            for n in ctx.honest_online()
            if n != name
        }
        if honest_heads and head["root"] not in honest_heads:
            out.append(f"{name}: head did not rejoin the honest chain")
    return out


def spam_priced(ctx: SimContext) -> list:
    """The spam flood was absorbed by the pricing surfaces: the DA
    candidate cache stayed within its caps and the RPC token buckets
    actually rate-limited the flood."""
    out = []
    spam = ctx.diff_family("lighthouse_tpu_sim_spam_messages_total")
    if spam <= 0:
        out.append("spam flood scheduled but no spam was emitted")
    for name in ctx.honest_online():
        da = ctx.health(name)["da"]
        if da["pending_entries"] > 512:
            out.append(
                f"{name}: DA pending entries {da['pending_entries']} "
                "exceed the cache cap"
            )
    if any(f.kind == "rpc_flood" for f in ctx.scenario.faults):
        limited = ctx.diff_family(
            'lighthouse_tpu_rpc_requests_total{method="status",'
            'outcome="rate_limited"}'
        )
        if limited <= 0:
            out.append(
                "rpc flood ran but no request was rate-limited "
                "(token buckets never priced it)"
            )
    return out


def sheds_bounded(ctx: SimContext) -> list:
    """The overload was shed, counted, and BOUNDED: processor shed
    counters grew during the flood, never exceeded what the flood
    actually emitted (each flood message can be shed at most once per
    node), and the per-node health counts agree exactly with the
    registry delta (the PR 6 cross-check pattern).

    Preconditions the SCHEMA enforces (scenario.validate): no
    kv_crash/offline faults (a reboot zeroes the per-node-life counters
    the registry delta is compared against) and duplicate_rate == 0
    (the flood bound assumes at-most-once delivery per node)."""
    out = []
    shed = ctx.diff_family("lighthouse_tpu_processor_shed_total")
    if shed <= 0:
        out.append("no processor work was shed during the run")
    flood = ctx.diff(
        "lighthouse_tpu_sim_spam_messages_total"
        '{kind="gossip_attestation_flood"}'
    )
    n_nodes = len(
        [sn for sn in ctx.nodes.values() if sn.online]
    )
    if flood > 0 and shed > flood * n_nodes:
        out.append(
            f"shed count {shed} exceeds the flood volume bound "
            f"{flood * n_nodes} ({flood} messages x {n_nodes} nodes)"
        )
    health_total = 0
    for name, sn in sorted(ctx.nodes.items()):
        if not sn.online:
            continue
        proc = ctx.health(name).get("overload", {}).get("processor", {})
        health_total += sum(proc.get("shed_total", {}).values())
    if health_total != int(shed):
        out.append(
            f"health shed totals ({health_total}) disagree with the "
            f"registry delta ({int(shed)})"
        )
    return out


def overload_reported(ctx: SimContext) -> list:
    """The overload episode is visible on the observability plane:
    every shedding node journals balanced shed_window opened/closed
    pairs and ends the run with no window open, health carries the
    overload section, NO forensic journal events were lost, and the
    hot-read cache absorbed the REST read flood."""
    out = []
    for name in ctx.honest_online():
        health = ctx.health(name)
        ov = health.get("overload")
        if not ov:
            out.append(f"{name}: health has no overload section")
            continue
        proc = ov.get("processor", {})
        opened = ctx.events(name, kind="shed_window", outcome="opened")
        closed = ctx.events(name, kind="shed_window", outcome="closed")
        if proc.get("shed_total"):
            if not opened:
                out.append(
                    f"{name}: work was shed but no shed_window event "
                    "was journaled"
                )
            if len(opened) != len(closed):
                out.append(
                    f"{name}: unbalanced shed windows "
                    f"({len(opened)} opened / {len(closed)} closed)"
                )
            if proc.get("active"):
                out.append(
                    f"{name}: shed window still open at run end: "
                    f"{proc['active']}"
                )
        if health["journal"]["dropped"]:
            out.append(
                f"{name}: forensic journal lost "
                f"{health['journal']['dropped']} events under load"
            )
    if any(f.kind == "rest_flood" for f in ctx.scenario.faults):
        hits = ctx.diff(
            "lighthouse_tpu_http_cache_events_total"
            '{cache="state_reads",event="hit"}'
        )
        if hits <= 0:
            out.append(
                "rest flood ran but the hot-read cache never hit — "
                "every read paid a store/state resolve"
            )
        exp = ctx.diff(
            "lighthouse_tpu_http_class_seconds_count"
            '{cls="expensive_read"}'
        )
        if exp <= 0:
            out.append(
                "rest flood ran but the expensive_read class saw no "
                "traffic — the admission classifier missed the flood"
            )
        # NOTE: wire-level concurrency sheds (503s) are timing-
        # dependent at sim scale (sub-ms handlers barely overlap even
        # under a barrier-released burst); the deterministic proof of
        # the 503/429 + Retry-After contract lives in
        # tests/test_serving_plane.py with a controlled slow handler.
    return out


def overload_recovery(ctx: SimContext) -> list:
    """After the flood lifts, the node serves within budget again: a
    fresh probe of health + a hot read on every honest node succeeds,
    with the slowest probe under a small multiple of the pre-flood
    budget the orchestrator recorded."""
    out = []
    for name in ctx.honest_online():
        budget = max(10.0 * ctx.probe_budget.get(name, 0.0), 1.0)
        times = []
        try:
            for _ in range(6):
                t0 = time.perf_counter()
                ctx._get(name, "/lighthouse/health")
                times.append(time.perf_counter() - t0)
                t0 = time.perf_counter()
                ctx._get(
                    name,
                    "/eth/v1/beacon/states/finalized/"
                    "finality_checkpoints",
                )
                times.append(time.perf_counter() - t0)
        except Exception as e:
            out.append(f"{name}: post-flood probe failed: {e}")
            continue
        worst = max(times)
        if worst > budget:
            out.append(
                f"{name}: post-flood worst probe {worst:.3f}s above "
                f"the pre-flood budget {budget:.3f}s"
            )
    return out


def faults_fired(ctx: SimContext) -> list:
    """A chaos run that injected nothing tests nothing: at least one
    non-deliver conditioner action (or partition block) must have
    fired."""
    injected = 0.0
    for action in (
        "drop", "duplicate", "delay", "reorder", "dist_hold",
        "partition_block",
    ):
        injected += ctx.diff(
            "lighthouse_tpu_sim_conditioner_actions_total"
            f'{{action="{action}"}}'
        )
    injected += ctx.diff_family("lighthouse_tpu_sim_rpc_faults_total")
    if injected <= 0:
        return ["no conditioner fault fired during the run"]
    return []


def attribution_complete(ctx: SimContext) -> list:
    """Device-plane attribution survives chaos: every journaled
    `signature_batch` event carries a consumer label, the registry's
    per-consumer set totals (`lighthouse_tpu_device_sets_total`)
    EXACTLY equal the journals' summed `n_sets`, and nothing entered
    the plane unattributed. Journal evidence covers every node LIFE of
    every FULL node — adversaries included: a spammer is still a full
    node verifying the gossip it receives, and its device batches land
    in ITS journal — via the live /lighthouse/events plus the
    crash/offline archives the orchestrator captured at shutdown (the
    same journal surface, read at archive time). Scenarios using this
    invariant must end with their nodes ONLINE (an end-offline node's
    post-archive events would be unreadable and report as a false
    mismatch)."""
    out = []
    totals: dict = {}
    unlabeled = 0
    n_events = 0
    for name, sn in sorted(ctx.nodes.items()):
        docs = list()
        for archive in getattr(sn, "journal_archives", ()):
            docs.extend(archive)
        if sn.online:
            dropped = ctx.health(name)["journal"]["dropped"]
            if dropped:
                out.append(
                    f"{name}: journal evicted {dropped} events — "
                    "attribution equality cannot be asserted (size "
                    "journal_capacity to the run)"
                )
            docs.extend(ctx.events(name, kind="signature_batch"))
        for ev in docs:
            if ev.get("kind") != "signature_batch":
                continue
            n_events += 1
            attrs = ev.get("attrs") or {}
            consumer = attrs.get("consumer")
            n_sets = attrs.get("n_sets")
            if not consumer or consumer == "unattributed" or (
                n_sets is None
            ):
                unlabeled += 1
                continue
            totals[consumer] = totals.get(consumer, 0) + int(n_sets)
    if unlabeled:
        out.append(
            f"{unlabeled} signature_batch events lack a consumer label"
        )
    if not n_events:
        out.append(
            "no signature_batch events journaled — the device plane "
            "went dark (or lost its journal threading)"
        )
    for consumer, journal_total in sorted(totals.items()):
        reg = ctx.diff(
            "lighthouse_tpu_device_sets_total"
            f'{{consumer="{consumer}"}}'
        )
        if int(reg) != journal_total:
            out.append(
                f"consumer {consumer!r}: registry counted {int(reg)} "
                f"sets but the journals carry {journal_total}"
            )
    # the equality must be TWO-sided: a consumer whose call sites lost
    # their journal threading entirely would vanish from `totals` and
    # escape the loop above — walk the registry's per-consumer series
    # and require journal evidence for every one that moved
    series_re = re.compile(
        r'lighthouse_tpu_device_sets_total\{consumer="([^"]+)"\}$'
    )
    for key in set(ctx.snapshot_after) | set(ctx.snapshot_before):
        m = series_re.match(key)
        if m is None:
            continue
        consumer = m.group(1)
        if consumer == "unattributed" or consumer in totals:
            continue
        delta = ctx.diff(key)
        if delta > 0:
            out.append(
                f"consumer {consumer!r}: registry counted {int(delta)} "
                "sets but no journal carries a single batch for it — "
                "journal threading lost"
            )
    unattr = ctx.diff(
        'lighthouse_tpu_device_sets_total{consumer="unattributed"}'
    )
    if unattr > 0:
        out.append(
            f"{int(unattr)} sets entered the device plane unattributed"
        )
    return out


def budget_complete(ctx: SimContext) -> list:
    """The slot-budget profiler's accounting closes: every
    `block_import` journal event has exactly one `slot_budget` partner
    with the same (root, outcome) — both directions, archives plus live
    events per node LIFE like `attribution_complete` — and every
    slot_budget event's arithmetic is self-consistent: stage union plus
    unattributed time equals wall (the recorder's defining identity),
    overlap and unattributed are non-negative, the fusable gap fits in
    the wall, the dispatch-label ledger matches the serial-dispatch
    count, and the per-stage durations sum to sum_stages. Then the
    registry must agree with the journals EXACTLY: the fusable-gap
    histogram counted one observation per slot_budget event, the
    serial-dispatch histogram's sum equals the journals' summed
    serial_dispatches, and the stage family counted one observation per
    journaled stage. Scenarios using this invariant must end with their
    nodes ONLINE (attribution_complete's archive caveat)."""
    out = []
    n_events = 0
    serial_total = 0
    stage_obs_total = 0
    # rounded-to-6dp fields: identity slack is rounding, not tolerance
    eps = 1e-3
    for name, sn in sorted(ctx.nodes.items()):
        docs = []
        for archive in getattr(sn, "journal_archives", ()):
            docs.extend(archive)
        if sn.online:
            dropped = ctx.health(name)["journal"]["dropped"]
            if dropped:
                out.append(
                    f"{name}: journal evicted {dropped} events — "
                    "budget pairing cannot be asserted (size "
                    "journal_capacity to the run)"
                )
            docs.extend(ctx.events(name, kind="block_import"))
            docs.extend(ctx.events(name, kind="slot_budget"))
        imports: dict = {}
        budgets: dict = {}
        for ev in docs:
            key = (ev.get("root"), ev.get("outcome"))
            if ev.get("kind") == "block_import":
                imports[key] = imports.get(key, 0) + 1
                continue
            if ev.get("kind") != "slot_budget":
                continue
            budgets[key] = budgets.get(key, 0) + 1
            n_events += 1
            a = ev.get("attrs") or {}
            wall = a.get("wall_s")
            union = a.get("union_s")
            unattr = a.get("unattributed_s")
            if None in (wall, union, unattr):
                out.append(
                    f"{name}: slot_budget event for {key} lacks the "
                    "accounting fields"
                )
                continue
            if abs(union + unattr - wall) > eps:
                out.append(
                    f"{name}: {key}: union {union} + unattributed "
                    f"{unattr} != wall {wall}"
                )
            if a.get("overlap_s", 0) < 0 or unattr < 0:
                out.append(
                    f"{name}: {key}: negative overlap/unattributed"
                )
            if a.get("fusable_gap_s", 0) > wall + eps:
                out.append(
                    f"{name}: {key}: fusable gap "
                    f"{a.get('fusable_gap_s')} exceeds wall {wall}"
                )
            serial = int(a.get("serial_dispatches", 0))
            labels = a.get("dispatch_labels") or []
            if len(labels) != serial:
                out.append(
                    f"{name}: {key}: {len(labels)} dispatch labels "
                    f"vs serial_dispatches={serial}"
                )
            stages = a.get("stages") or {}
            if abs(
                sum(stages.values()) - a.get("sum_stages_s", 0)
            ) > eps:
                out.append(
                    f"{name}: {key}: stage durations do not sum to "
                    "sum_stages_s"
                )
            serial_total += serial
            stage_obs_total += int(a.get("n_stages", len(stages)))
        for key in set(imports) | set(budgets):
            if imports.get(key, 0) != budgets.get(key, 0):
                out.append(
                    f"{name}: {key}: {imports.get(key, 0)} "
                    f"block_import events vs {budgets.get(key, 0)} "
                    "slot_budget events — the profiler lost (or "
                    "invented) an import"
                )
    if not n_events:
        out.append(
            "no slot_budget events journaled — the profiler went dark"
        )
    reg_count = ctx.diff("lighthouse_tpu_slot_fusable_gap_seconds_count")
    if int(reg_count) != n_events:
        out.append(
            f"registry observed {int(reg_count)} imports but the "
            f"journals carry {n_events} slot_budget events"
        )
    reg_serial = ctx.diff("lighthouse_tpu_slot_serial_dispatches_sum")
    if int(round(reg_serial)) != serial_total:
        out.append(
            f"registry summed {int(round(reg_serial))} serial "
            f"dispatches but the journals carry {serial_total}"
        )
    reg_stages = 0.0
    for key in set(ctx.snapshot_after) | set(ctx.snapshot_before):
        if key.startswith("lighthouse_tpu_slot_stage_seconds_count{"):
            reg_stages += ctx.diff(key)
    if int(round(reg_stages)) != stage_obs_total:
        out.append(
            f"registry counted {int(round(reg_stages))} stage "
            f"observations but the journals carry {stage_obs_total}"
        )
    return out


def bus_no_starvation(ctx: SimContext) -> list:
    """The verification bus never starves a submission: every node's
    bus reports submitted == completed with an empty queue at run end,
    and every bus-journaled `signature_batch` event's submit-to-verdict
    wait stayed within its deadline budget plus the batch wall (with a
    scheduling-slack floor — the bound is about starvation, not
    scheduler jitter). A submission that timed out of the queue must
    have been small-batch flushed (a verdict event exists), never
    silently dropped."""
    out = []
    for name in ctx.honest_online():
        health = ctx.health(name)
        bus = health.get("overload", {}).get("verification_bus")
        if bus is None:
            out.append(
                f"{name}: health carries no verification_bus section"
            )
            continue
        if bus.get("pending"):
            out.append(
                f"{name}: {bus['pending']} submissions still queued "
                "at run end"
            )
        if bus.get("submitted") != bus.get("completed"):
            out.append(
                f"{name}: bus submitted {bus.get('submitted')} but "
                f"completed {bus.get('completed')} — a submission "
                "never reached a verdict"
            )
        for ev in ctx.events(name, kind="signature_batch"):
            attrs = ev.get("attrs") or {}
            if "bus_batch" not in attrs:
                continue
            wait = attrs.get("wait_s")
            budget = attrs.get("budget_s")
            if wait is None or budget is None:
                out.append(
                    f"{name}: bus signature_batch event lacks "
                    "wait_s/budget_s"
                )
                continue
            wall = attrs.get("wall_s") or 0.0
            if wait > budget + max(1.0, 4 * wall):
                out.append(
                    f"{name}: submission waited {wait:.3f}s against a "
                    f"{budget:.3f}s deadline + {wall:.3f}s batch wall"
                )
    return out


def lc_tracks_finality(ctx: SimContext) -> list:
    """The light-client actor — bootstrapped from ONE trusted root —
    ends the run on the serving node's own finalized head, with its
    optimistic head within the attestation lag of the final slot. The
    node's side of the comparison comes from the REST surface
    (/eth/v1/beacon/blocks/finalized/root + /lighthouse/health), the
    client's from the actor summary the orchestrator recorded."""
    lc = ctx.lc_client
    if lc is None:
        return ["scenario ran no light-client actor"]
    out = []
    if not lc.get("bootstrapped"):
        return ["light client never bootstrapped"]
    name = ctx.honest_online()[0]
    fin_epoch = ctx.health(name)["head"]["finalized_epoch"]
    if fin_epoch < 1:
        out.append(f"{name}: chain never finalized ({fin_epoch})")
        return out
    node_fin = ctx._get(
        name, "/eth/v1/beacon/blocks/finalized/root"
    )["data"]["root"]
    lc_fin = (lc.get("finalized") or {}).get("root")
    if lc_fin != node_fin:
        out.append(
            f"lc finalized head {lc_fin} != node finalized {node_fin}"
        )
    head_slot = ctx.health(name)["head"]["slot"]
    opt_slot = (lc.get("optimistic") or {}).get("slot", -1)
    if opt_slot < head_slot - 2:
        out.append(
            f"lc optimistic head slot {opt_slot} lags the node head "
            f"{head_slot} beyond the attestation lag"
        )
    return out


def lc_proofs_verify(ctx: SimContext) -> list:
    """Every branch the client verified passed, at least one did, and
    the serving node journaled update production — registry + journal
    evidence only."""
    out = []
    ok = ctx.diff(
        'lighthouse_tpu_lc_client_proofs_total{outcome="ok"}'
    )
    fail = ctx.diff(
        'lighthouse_tpu_lc_client_proofs_total{outcome="fail"}'
    )
    if ok <= 0:
        out.append("light client verified no branch at all")
    if fail > 0:
        out.append(f"{int(fail)} light-client branch proofs FAILED")
    rejected = ctx.diff(
        'lighthouse_tpu_lc_client_updates_total{outcome="rejected"}'
    )
    if rejected > 0:
        out.append(
            f"{int(rejected)} light-client updates were rejected"
        )
    for name in ctx.honest_online():
        if not ctx.events(name, kind="lc_update_produced"):
            out.append(
                f"{name}: no lc_update_produced events journaled"
            )
    return out


def lc_served_bounded(ctx: SimContext) -> list:
    """The serving plane actually streamed light-client bytes, and the
    total stayed within a per-request envelope (no handler ever
    amplified a read into a state-sized response)."""
    lc = ctx.lc_client
    if lc is None:
        return ["scenario ran no light-client actor"]
    out = []
    served = ctx.diff_family("lighthouse_tpu_lc_served_bytes_total")
    if served <= 0:
        out.append("no light-client bytes were served")
    requests = max(int(lc.get("requests", 0)), 1)
    # generous per-request envelope: an updates-by-range response is a
    # handful of ~2 KB documents; a beacon state is megabytes
    budget = requests * 64 * 1024
    if served > budget:
        out.append(
            f"{int(served)} lc bytes served exceeds the "
            f"{budget}-byte envelope for {requests} requests"
        )
    return out


# injected device-fault kind -> the guard-taxonomy kind its journal
# evidence must carry. flip is caught AS a canary violation (that is
# the canary contract); slow_compile only delays, the guard absorbs it
# without a fault event, so it needs no evidence here.
_DEVICE_FAULT_EVIDENCE = {
    "stall": "stall",
    "error": "error",
    "flip": "canary",
}


def _device_fault_events(ctx: SimContext) -> list:
    out = []
    for name in ctx.honest_online():
        for ev in ctx.events(name, kind="device_fault"):
            out.append((name, ev))
    return out


def device_faults_caught(ctx: SimContext) -> list:
    """Every armed device-fault kind left journal evidence that the
    guard CAUGHT it (a `device_fault` event of the expected taxonomy
    kind on the targeted plane) and that callers were answered by host
    failover — never left hanging on a faulted device."""
    out = []
    specs = [
        f for f in ctx.scenario.faults if f.kind.startswith("device_")
    ]
    if not specs:
        return ["scenario armed no device_* faults"]
    events = _device_fault_events(ctx)
    faults = [
        (n, e) for n, e in events if e.get("outcome") == "fault"
    ]
    failovers = [
        (n, e) for n, e in events if e.get("outcome") == "failover"
    ]
    for f in specs:
        kind = f.kind[len("device_"):]
        expected = _DEVICE_FAULT_EVIDENCE.get(kind)
        if expected is None:
            continue
        hits = [
            (n, e)
            for n, e in faults
            if (e.get("attrs") or {}).get("fault") == expected
            and (e.get("attrs") or {}).get("plane") == f.plane
        ]
        if not hits:
            out.append(
                f"no journaled {expected!r} fault on plane "
                f"{f.plane!r} — the {f.kind} injection was never caught"
            )
    if not failovers:
        out.append(
            "faults were injected but no failover was journaled — "
            "callers' verdicts are unaccounted for"
        )
    if ctx.diff_family("lighthouse_tpu_device_faults_total") <= 0:
        out.append("registry counted no device fault")
    if ctx.diff_family("lighthouse_tpu_device_failovers_total") <= 0:
        out.append("registry counted no device failover")
    return out


def device_no_wrong_verdicts(ctx: SimContext) -> list:
    """A lying device must never reach a caller: under flip injection
    every flipped verdict is caught by the canary pair (journaled as a
    `canary` fault) and re-verified on host, so NO node journals a
    non-ok signature_batch verdict anywhere in the run — honest sim
    traffic is all-valid, so any failed batch IS a wrong verdict."""
    out = []
    for name in ctx.honest_online():
        bad = [
            ev
            for ev in ctx.events(name, kind="signature_batch")
            if ev.get("outcome") != "ok"
        ]
        if bad:
            out.append(
                f"{name}: {len(bad)} signature_batch verdicts were "
                f"not ok (first: {bad[0].get('outcome')!r}) — a "
                "flipped verdict escaped the canary"
            )
    if any(f.kind == "device_flip" for f in ctx.scenario.faults):
        canary = [
            (n, e)
            for n, e in _device_fault_events(ctx)
            if e.get("outcome") == "fault"
            and (e.get("attrs") or {}).get("fault") == "canary"
        ]
        if not canary:
            out.append(
                "flip injection armed but the canary never fired"
            )
    return out


def device_breaker_balanced(ctx: SimContext) -> list:
    """The breaker cycled AND healed: at least one open and one close
    transition journaled (exact counts are not required to match —
    zero-cooldown half-open probes legitimately re-trip several times
    per recovery), and every plane-wide QUARANTINE key shows closed in
    health at run end. Shape-bucket keys MAY end open: a bucket whose
    batch shape never recurs after its fault window has no probe
    opportunity, and an open bucket key costs nothing but a skip to
    failover when (if ever) that shape returns — that is the breaker's
    keying design, not a stuck plane."""
    out = []
    events = _device_fault_events(ctx)
    opens = sum(
        1 for _n, e in events if e.get("outcome") == "breaker_open"
    )
    closes = sum(
        1 for _n, e in events if e.get("outcome") == "breaker_closed"
    )
    if opens < 1:
        out.append("breaker never opened under injected faults")
    if closes < 1:
        out.append(
            "breaker never closed again after the fault windows"
        )
    for name in ctx.honest_online():
        dp = ctx.health(name).get("overload", {}).get("device_plane")
        if not dp:
            out.append(f"{name}: health has no device_plane section")
            continue
        state = (dp.get("breaker") or {}).get("state") or {}
        stuck = {
            k: v
            for k, v in state.items()
            if k.endswith("/*") and v != "closed"
        }
        if stuck:
            out.append(
                f"{name}: plane quarantine not healed at run end: "
                f"{stuck}"
            )
    return out


def das_convergence(ctx: SimContext) -> list:
    """Column-mode availability is decided by the DATA, never the
    proposer's word: every column-carrying block whose served columns
    reached the 50% reconstruction threshold was imported by every
    honest node — with at least threshold-many distinct column indices
    individually verified in that node's journal — and every block
    published below the threshold was imported by NO node (its root is
    nobody's head, and the chain kept growing past it on the parent)."""
    if not ctx.das_blocks:
        return ["scenario produced no column-carrying blocks"]
    out = []
    for root_hex, meta in sorted(ctx.das_blocks.items()):
        threshold = meta["columns"] // 2
        for name in ctx.honest_online():
            sn = ctx.nodes[name]
            if meta["slot"] <= sn.anchor_slot:
                continue  # backfilled history: no DA required
            imports = ctx.events(
                name, root=root_hex, kind="block_import",
                outcome="imported",
            )
            if meta["available"]:
                if not imports:
                    out.append(
                        f"{name}: available column block {root_hex} "
                        "not imported"
                    )
                    continue
                verified = ctx.events(
                    name, root=root_hex, kind="column_sidecar",
                    outcome="verified",
                )
                indices = {e["attrs"]["index"] for e in verified}
                if len(indices) < threshold:
                    out.append(
                        f"{name}: column block {root_hex} imported "
                        f"with only {len(indices)}/{threshold} "
                        "verified columns"
                    )
            elif imports:
                out.append(
                    f"{name}: imported WITHHELD block {root_hex} "
                    f"({meta['served']}/{meta['columns']} columns "
                    "served — below the reconstruction threshold)"
                )
        if not meta["available"]:
            for name in ctx.honest_online():
                if ctx.health(name)["head"]["root"] == root_hex:
                    out.append(
                        f"{name}: head sits on the withheld block "
                        f"{root_hex}"
                    )
    return out


def das_withheld_flagged(ctx: SimContext) -> list:
    """Every below-threshold (withheld) block was flagged by EVERY
    honest node's sampler — a das_sample/withheld_flagged journal event
    per node per root — and the registry's flag counter agrees. A
    scheduled das_withhold fault that never actually withheld a block
    tested nothing and is itself a violation."""
    out = []
    withheld = {
        r: m
        for r, m in sorted(ctx.das_blocks.items())
        if m["withheld"] and not m["available"]
    }
    if any(f.kind == "das_withhold" for f in ctx.scenario.faults) and (
        not withheld
    ):
        out.append(
            "das_withhold was scheduled but no block was ever "
            "withheld below the threshold"
        )
    expected_flags = 0
    for root_hex in withheld:
        for name in ctx.honest_online():
            flags = ctx.events(
                name, root=root_hex, kind="das_sample",
                outcome="withheld_flagged",
            )
            if not flags:
                out.append(
                    f"{name}: withheld block {root_hex} was never "
                    "flagged by its sampler"
                )
            expected_flags += len(flags)
    reg = ctx.diff("lighthouse_tpu_da_withholding_flags_total")
    if withheld and int(reg) < len(withheld):
        out.append(
            f"registry counted {int(reg)} withholding flags for "
            f"{len(withheld)} withheld blocks"
        )
    return out


def das_no_wrong_verdicts(ctx: SimContext) -> list:
    """The cell-proof plane never lied: every bus-journaled cell_batch
    verdict is ok (honest sim traffic is all-valid), at least one cell
    batch actually rode the bus, and no sampler saw served data fail
    its own proof (a das_sample/verify_failed event would mean a
    serving peer handed out cells that do not verify — a wrong verdict
    on one side or the other)."""
    out = []
    n_batches = 0
    for name in ctx.honest_online():
        bad = [
            ev
            for ev in ctx.events(name, kind="cell_batch")
            if ev.get("outcome") != "ok"
        ]
        n_batches += len(ctx.events(name, kind="cell_batch"))
        if bad:
            out.append(
                f"{name}: {len(bad)} cell_batch verdicts were not ok "
                f"(first: {bad[0].get('outcome')!r})"
            )
        failed = ctx.events(
            name, kind="das_sample", outcome="verify_failed"
        )
        if failed:
            out.append(
                f"{name}: {len(failed)} sampled columns failed "
                "verification — served data did not prove"
            )
    if not n_batches:
        out.append(
            "no cell_batch events journaled — cell proofs never rode "
            "the verification bus"
        )
    wrong = ctx.diff(
        'lighthouse_tpu_da_samples_total{outcome="verify_failed"}'
    )
    if wrong > 0:
        out.append(
            f"registry counted {int(wrong)} verify-failed samples"
        )
    return out


def finalized(ctx: SimContext) -> list:
    out = []
    for name in ctx.honest_online():
        fin = ctx.health(name)["head"]["finalized_epoch"]
        if fin < 1:
            out.append(f"{name}: finalized epoch {fin} < 1")
    return out


CHECKS = {
    "honest_convergence": honest_convergence,
    "exactly_once_imports": exactly_once_imports,
    "da_completeness": da_completeness,
    "bounded_scores": bounded_scores,
    "no_honest_quarantine": no_honest_quarantine,
    "eclipse_rejoin": eclipse_rejoin,
    "spam_priced": spam_priced,
    "faults_fired": faults_fired,
    "attribution_complete": attribution_complete,
    "budget_complete": budget_complete,
    "bus_no_starvation": bus_no_starvation,
    "finalized": finalized,
    "sheds_bounded": sheds_bounded,
    "overload_reported": overload_reported,
    "overload_recovery": overload_recovery,
    "lc_tracks_finality": lc_tracks_finality,
    "lc_proofs_verify": lc_proofs_verify,
    "lc_served_bounded": lc_served_bounded,
    "device_faults_caught": device_faults_caught,
    "device_no_wrong_verdicts": device_no_wrong_verdicts,
    "device_breaker_balanced": device_breaker_balanced,
    "das_convergence": das_convergence,
    "das_withheld_flagged": das_withheld_flagged,
    "das_no_wrong_verdicts": das_no_wrong_verdicts,
}


def check_all(ctx: SimContext, names) -> list:
    violations = []
    for name in names:
        for msg in CHECKS[name](ctx):
            violations.append(f"[{name}] {msg}")
    return violations
