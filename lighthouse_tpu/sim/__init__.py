"""Deterministic multi-node network simulator.

The reference ships a whole deterministic-simulation plane
(testing/antithesis/, testing/simulator/) because consensus correctness
only shows up at network scale: aggregate-signature protocols must hold
under adversarial delivery ("One For All", PAPERS.md). This package is
that plane for lighthouse_tpu:

  * `conditioner`  — seeded per-directed-peer-pair drop/delay/reorder/
    duplicate plus schedulable partition masks, layered into
    `network/socket_net.py`'s outbound edge;
  * `scenario`     — the declarative scenario spec (nodes, validator
    split, fault timeline) with a committed JSON library under
    `scenarios/`;
  * `orchestrator` — boots 5-10 in-process BeaconNodes over real TCP
    sockets on a deterministic slot clock and executes the timeline;
  * `invariants`   — honest-head convergence, exactly-once imports, DA
    completeness, bounded/ordered peer scores, no-quarantine-of-honest
    — asserted ONLY through `GET /lighthouse/events`,
    `GET /lighthouse/health`, and registry snapshot diffs;
  * `verdict`      — canonical (replay-comparable) journal export and
    the JSONL verdict artifact `scripts/sim.py` writes.

Every run replays from one seed: re-running a scenario produces a
byte-identical canonical journal (the seed-determinism gate in
tests/test_sim.py).
"""

from lighthouse_tpu.sim.conditioner import NetworkConditioner
from lighthouse_tpu.sim.scenario import (
    Scenario,
    ScenarioError,
    load_scenario,
    scenario_library,
)
from lighthouse_tpu.sim.orchestrator import Simulation

__all__ = [
    "NetworkConditioner",
    "Scenario",
    "ScenarioError",
    "Simulation",
    "load_scenario",
    "scenario_library",
]
