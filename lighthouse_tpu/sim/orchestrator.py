"""Simulation orchestrator: N in-process beacon nodes over real TCP
sockets on a deterministic slot clock, executing a scenario's fault
timeline.

Role of the reference's `testing/simulator` (n beacon nodes + validator
clients in one process over real libp2p) crossed with its Antithesis
deterministic-simulation campaigns: every node is a full `BeaconNode`
(chain, DA checker, sync manager, beacon processor, HTTP API) attached
to a `SocketNet` whose outbound edge runs through one shared seeded
`NetworkConditioner`. The orchestrator drives the slot clock in
LOCKSTEP — publish, settle (socket quiescence + conditioner hold
flush), drain — so the only nondeterminism left is thread interleaving
WITHIN a step, which the canonical journal projection (verdict.py)
normalizes away.

Validator split: validator v belongs to node ``v % nodes``. Each node
proposes on ITS OWN head when it owns the proposer (so partitions
genuinely fork the chain), attests with its own validators on its own
head, and self-aggregates its naive-pool aggregates into its op pool
(the in-process stand-in for the aggregate gossip plane).

Driving uses chain/node methods freely — it is the test rig. ASSERTIONS
never do: invariants.py reads only /lighthouse/events,
/lighthouse/health, and registry snapshot diffs.
"""

import json
import os
import time
import urllib.request

from lighthouse_tpu import bls, kzg, ssz
from lighthouse_tpu.common.logging import get_logger
from lighthouse_tpu.common.metrics import REGISTRY
from lighthouse_tpu.node import BeaconNode
from lighthouse_tpu.sim.conditioner import (
    NetworkConditioner,
    PairPolicy,
)
from lighthouse_tpu.state_processing.genesis import interop_genesis_state
from lighthouse_tpu.types.helpers import (
    compute_domain,
    compute_signing_root,
)
from lighthouse_tpu.types.spec import minimal_spec

_LOG = get_logger("sim")

_SPAM_TOTAL = REGISTRY.counter_vec(
    "lighthouse_tpu_sim_spam_messages_total",
    "adversarial messages emitted by simulator fault actors "
    "(gossip_sidecar|gossip_sidecar_invalid|rpc_burst|"
    "gossip_attestation_flood|rest_read)",
    ("kind",),
)
_SLOTS_TOTAL = REGISTRY.counter(
    "lighthouse_tpu_sim_slots_total",
    "simulated slots driven across all scenario runs",
)
_RUNS_TOTAL = REGISTRY.counter_vec(
    "lighthouse_tpu_sim_runs_total",
    "scenario runs, by outcome (ok|violations)",
    ("outcome",),
)

SETTLE_POLL_S = 0.015
SETTLE_STABLE_POLLS = 4
SETTLE_TIMEOUT_S = 8.0
CONNECT_TIMEOUT_S = 5.0


def _deterministic_blob(spec, seed: int) -> bytes:
    """A canonical blob (every field element < the BLS modulus)."""
    return b"".join(
        ((seed * 31 + i + 1) % 1009).to_bytes(32, "big")
        for i in range(spec.FIELD_ELEMENTS_PER_BLOB)
    )


class SimNode:
    """One simulated participant: a full BeaconNode + its transport."""

    def __init__(self, name: str, index: int | None):
        self.name = name
        self.index = index  # None for validator-less adversaries
        self.node = None
        self.net = None
        self.api = None
        self.online = True
        self.anchor_slot = 0        # > 0 after a checkpoint restart
        self.restart_slots: list = []
        self.produced_slots: list = []
        self.kv_path = None
        # journals of previous node lives (archived at crash/offline)
        self.journal_archives: list = []

    @property
    def chain(self):
        return self.node.chain

    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.api.port}"

    def archive_journal(self):
        if self.node is not None:
            self.journal_archives.append(
                self.node.chain.journal.query()
            )


class Simulation:
    def __init__(self, scenario, workdir: str | None = None):
        self.scenario = scenario
        self.workdir = workdir
        self.spec = minimal_spec(**scenario.spec_overrides)
        self.keypairs = bls.interop_keypairs(scenario.validators)
        self.genesis = interop_genesis_state(
            [kp.pk.to_bytes() for kp in self.keypairs], 0, self.spec
        )
        self.gvr = bytes(self.genesis.genesis_validators_root)
        self.conditioner = NetworkConditioner(
            seed=scenario.seed,
            default=PairPolicy.from_dict(scenario.conditioner),
        )
        self.nodes: list[SimNode] = []
        self.blob_blocks: dict = {}   # root hex -> n_blobs
        # column-mode runs: root hex -> {slot, n_blobs, served,
        # columns, withheld, available} for every column-carrying
        # block (DRIVING context for the das_* invariants)
        self.das_blocks: dict = {}
        self.eclipse_windows: dict = {}  # name -> (at, until)
        self.probe_budget: dict = {}  # name -> pre-flood probe median
        self._slot = 0
        # the device plane is process-global (one accelerator, one
        # breaker, one injector) — start every run from a clean slate
        # so back-to-back sims and replay runs see identical dynamics
        from lighthouse_tpu.device_plane import GUARD, INJECTOR

        GUARD.reset()
        INJECTOR.reset()

    # ------------------------------------------------------------- build

    def _boot_node(self, sn: SimNode, genesis_state, anchor_block=None,
                   kv=None):
        das = self.scenario.das
        sn.node = BeaconNode(
            sn.name,
            genesis_state,
            self.spec,
            backend=self.scenario.backend,
            kv=kv,
            anchor_block=anchor_block,
            column_mode=bool(das.get("column_mode")),
        )
        sn.node.chain.journal.configure(
            capacity=self.scenario.journal_capacity
        )
        if self.scenario.processor_bounds:
            # overload scenarios shrink queue bounds so a seeded flood
            # crosses the shed thresholds within one slot (the shedder
            # holds the SAME dict, so its hysteresis follows)
            sn.node.processor.bounds.update(
                self.scenario.processor_bounds
            )
        # deterministic sync: no real backoff sleeps, scenario-seeded
        # jitter, and the scenario seed keying every retry schedule
        sn.node.sync._sleep = lambda s: None
        sn.node.sync._rng_seed = self.scenario.seed
        sn.net = sn.node.attach_socket_net(
            conditioner=self.conditioner, mesh_enabled=False
        )
        self._subscribe_all_subnets(sn)
        sn.api = sn.node.start_http_api()
        sn.online = True
        if (
            sn.index is not None
            and das.get("column_mode")
            and das.get("samples_per_slot")
        ):
            # one DAS sampler per honest node, probing its ONLINE peers
            # (rebooted nodes get a fresh sampler with fresh counters,
            # like every other per-node-life surface); attaching it to
            # the node makes its stats ride /lighthouse/health
            from lighthouse_tpu.sim.das_sampler import DasSampler

            sn.node.das_sampler = DasSampler(
                sn.name,
                self.spec,
                sn.node.chain.journal,
                sn.node.chain.verification_bus,
                peer_urls=lambda me=sn: [
                    o.base_url()
                    for o in self._honest_online()
                    if o is not me
                ],
                samples_per_slot=das["samples_per_slot"],
                seed=self.scenario.seed,
                backend=self.scenario.backend,
            )

    def _subscribe_all_subnets(self, sn: SimNode):
        """Full-custody attestation subnets: the sim floods singles on
        their committee subnets and every node follows all of them (the
        deterministic stand-in for duty-driven subscriptions)."""
        from lighthouse_tpu.network.gossip import topic
        from lighthouse_tpu.network.subnet_service import (
            subnet_topic_name,
        )

        for i in range(self.spec.ATTESTATION_SUBNET_COUNT):
            sn.net.subscribe(
                sn.name, topic(sn.node.fork_digest, subnet_topic_name(i))
            )

    def _kv_for(self, index: int):
        """A durable store for nodes a kv_crash fault targets (native
        WAL kv when buildable, sqlite otherwise)."""
        if self.workdir is None:
            return None, None
        path = os.path.join(self.workdir, f"node{index}.kv")
        from lighthouse_tpu.native import kvstore

        if kvstore.available():
            return kvstore.NativeKVStore(path), path
        from lighthouse_tpu.store import SqliteStore

        return SqliteStore(path), path

    def _build(self):
        sc = self.scenario
        crash_targets = {
            sc.node_name(f.node)
            for f in sc.faults
            if f.kind == "kv_crash"
        }
        for i in range(sc.nodes):
            sn = SimNode(f"node{i}", i)
            kv = None
            if sn.name in crash_targets:
                kv, sn.kv_path = self._kv_for(i)
            self._boot_node(sn, self.genesis.copy(), kv=kv)
            self.nodes.append(sn)
        for name in sc.adversaries:
            sn = SimNode(name, None)
            self._boot_node(sn, self.genesis.copy())
            self.nodes.append(sn)
        # full mesh, dialed in fixed order; every dial is confirmed
        # (both sync views updated) before the next one so the peer
        # tables — and everything iterating them — are replay-stable
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1:]:
                a.net.connect(self.net_host(b), b.net.tcp_port)
                self._await_peers(a, b)

    @staticmethod
    def net_host(sn: SimNode) -> str:
        return sn.net.host

    def _await_peers(self, a: SimNode, b: SimNode):
        deadline = time.monotonic() + CONNECT_TIMEOUT_S
        while time.monotonic() < deadline:
            if (
                b.name in a.node.sync.peers
                and a.name in b.node.sync.peers
            ):
                return
            time.sleep(0.005)
        raise RuntimeError(
            f"sim: {a.name}<->{b.name} connection not confirmed"
        )

    def _connect_to_online(self, sn: SimNode):
        for other in self.nodes:
            if other is sn or not other.online:
                continue
            sn.net.connect(self.net_host(other), other.net.tcp_port)
            self._await_peers(sn, other)

    # ----------------------------------------------------------- helpers

    def _online(self):
        return [sn for sn in self.nodes if sn.online]

    def _probe_latency(self, sn: SimNode, count: int = 8) -> float:
        """Median wall latency of a health read against `sn`."""
        times = []
        url = sn.base_url() + "/lighthouse/health"
        for _ in range(count):
            t0 = time.perf_counter()
            with urllib.request.urlopen(url, timeout=10):
                pass
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    def _honest_online(self):
        return [
            sn for sn in self._online() if sn.index is not None
        ]

    def _owner(self, validator_index: int) -> str:
        return f"node{validator_index % self.scenario.nodes}"

    def _sign(self, kp, domain_type: bytes, epoch: int, root: bytes):
        domain = compute_domain(
            domain_type, self.spec.fork_version_at_epoch(epoch), self.gvr
        )
        return kp.sk.sign(compute_signing_root(root, domain)).to_bytes()

    def _emit_all(self, slot: int, outcome: str, **attrs):
        """Land one sim_fault event in every ONLINE node's journal, so
        each forensic record is self-describing about the fault
        timeline it lived through."""
        for sn in self._online():
            sn.chain.journal.emit(
                "sim_fault", slot=slot, outcome=outcome, **attrs
            )

    # ----------------------------------------------------- settle / drain

    def _settle(self):
        """Barrier: flush conditioner holds and wait until every online
        node's work queues have been stable for a few polls — i.e. the
        sockets have gone quiet for this step."""
        stable = 0
        last = None
        deadline = time.monotonic() + SETTLE_TIMEOUT_S
        while stable < SETTLE_STABLE_POLLS:
            flushed = 0
            for sn in self._online():
                flushed += sn.net.flush_conditioned() or 0
            cur = tuple(
                (
                    sn.name,
                    tuple(sorted(
                        sn.node.processor.queue_depths().items()
                    )),
                    sn.node.processor.metrics["processed"],
                    sn.node.processor.metrics["dropped"],
                    # shed counts are part of quiescence: a flood whose
                    # frames are still in flight keeps shedding without
                    # moving queue depths, and the barrier must wait
                    # for the LAST frame to land before the drain
                    sn.node.processor.metrics.get("shed", 0),
                )
                for sn in self._online()
            )
            if flushed == 0 and cur == last:
                stable += 1
            else:
                stable = 0
            last = cur
            if time.monotonic() > deadline:
                _LOG.warning("sim settle barrier timed out")
                return
            time.sleep(SETTLE_POLL_S)

    def _drain(self, sn: SimNode):
        """Drain a node's processor, tolerating handler errors (they are
        journaled as handler_error; the queue keeps moving)."""
        guard = 0
        while guard < 10_000:
            guard += 1
            try:
                if sn.node.processor.process_pending() == 0:
                    return
            except Exception as e:
                _LOG.debug("%s drain handler error: %s", sn.name, e)

    def _drain_all(self):
        for sn in self._online():
            self._drain(sn)

    # -------------------------------------------------------- block plane

    def _proposer_at(self, sn: SimNode, slot: int):
        epoch = self.spec.slot_to_epoch(slot)
        proposers = sn.chain.proposers_for_epoch(epoch)
        return proposers[slot - self.spec.epoch_start_slot(epoch)]

    def _propose(self, sn: SimNode, slot: int):
        sc = self.scenario
        epoch = self.spec.slot_to_epoch(slot)
        proposer = self._proposer_at(sn, slot)
        kp = self.keypairs[proposer]
        reveal = self._sign(
            kp,
            self.spec.DOMAIN_RANDAO,
            epoch,
            ssz.uint64.hash_tree_root(epoch),
        )
        blobs = []
        comms = []
        if slot in sc.blob_slots:
            blobs = [
                _deterministic_blob(self.spec, slot * 16 + i)
                for i in range(2)
            ]
            comms = [
                kzg.blob_to_kzg_commitment(b, consumer="kzg")
                for b in blobs
            ]
        try:
            block = sn.chain.produce_block_unsigned(
                slot, reveal, blob_kzg_commitments=comms
            )
        except Exception as e:
            _LOG.warning("%s production at %d failed: %s", sn.name, slot, e)
            return
        fork = self.spec.fork_name_at_epoch(epoch)
        block_cls = type(block)
        sig = self._sign(
            kp,
            self.spec.DOMAIN_BEACON_PROPOSER,
            epoch,
            block_cls.hash_tree_root(block),
        )
        signed = sn.chain.t.signed_block_classes[fork](
            message=block, signature=sig
        )
        column_mode = bool(sc.das.get("column_mode"))
        sidecars = []
        if blobs:
            from lighthouse_tpu.harness import Harness

            shim = _TypesShim(sn.chain.t, self.spec)
            if column_mode:
                sidecars = Harness.make_data_column_sidecars(
                    shim, signed, blobs
                )
            else:
                sidecars = Harness.make_blob_sidecars(shim, signed, blobs)
        root = type(block).hash_tree_root(block)
        withhold = (
            self._das_withhold_for(sn.name, slot)
            if column_mode and blobs
            else None
        )
        if withhold is not None:
            # data-withholding proposer: publish the block but serve
            # only the first `rate` columns — and do NOT self-import
            # (the adversary knows its own block is unavailable; its
            # head stays on the parent like every honest sampler's)
            served = sidecars[: withhold.rate]
            self._note_das_block(
                root, slot, blobs,
                served=len(served), total=len(sidecars), withheld=True,
            )
            self._emit_all(
                slot, "das_withhold",
                node=sn.name, served=len(served), columns=len(sidecars),
            )
            sn.produced_slots.append(slot)
            sn.node.publish_block(signed)
            for scd in served:
                sn.node.publish_data_column_sidecar(scd)
            return
        if blobs:
            # own sidecars first so the producer's own import settles
            for scd in sidecars:
                try:
                    if column_mode:
                        sn.chain.process_data_column_sidecar(scd)
                    else:
                        sn.chain.process_blob_sidecar(scd)
                except Exception as e:
                    _LOG.debug("own sidecar skipped: %s", e)
        try:
            sn.chain.process_block(signed)
        except Exception as e:
            _LOG.warning("%s own block at %d failed: %s", sn.name, slot, e)
            return
        sn.produced_slots.append(slot)
        if blobs:
            # tracked only once the block actually entered the network
            # — a failed own-import must not leave a phantom entry the
            # da_completeness invariant would hold every node to
            if column_mode:
                self._note_das_block(
                    root, slot, blobs,
                    served=len(sidecars), total=len(sidecars),
                    withheld=False,
                )
            else:
                self.blob_blocks["0x" + root.hex()] = len(blobs)
        sn.node.publish_block(signed)
        for scd in sidecars:
            if column_mode:
                sn.node.publish_data_column_sidecar(scd)
            else:
                sn.node.publish_blob_sidecar(scd)

    def _das_withhold_for(self, name: str, slot: int):
        """The active das_withhold fault targeting this proposer node at
        `slot`, if any."""
        for f in self.scenario.faults:
            if (
                f.kind == "das_withhold"
                and f.active(slot)
                and self.scenario.node_name(f.node) == name
            ):
                return f
        return None

    def _note_das_block(self, root, slot, blobs, served, total,
                        withheld):
        """Record a column-carrying block for the das_* invariants and
        hand its root to every honest sampler."""
        root_hex = "0x" + root.hex()
        self.das_blocks[root_hex] = {
            "slot": slot,
            "n_blobs": len(blobs),
            "served": served,
            "columns": total,
            "withheld": withheld,
            # 50%-of-columns reconstruction threshold (the erasure
            # extension doubles the data): at least half the columns
            # makes the block's data recoverable by anyone
            "available": served >= total // 2,
        }
        for other in self._honest_online():
            sampler = getattr(other.node, "das_sampler", None)
            if sampler is not None:
                sampler.observe_block(root_hex, slot)

    # -------------------------------------------------- attestation plane

    def _attest(self, sn: SimNode, slot: int):
        """Every validator this node owns signs a single-bit attestation
        on the node's OWN head and gossips it on its committee subnet."""
        chain = sn.chain
        epoch = self.spec.slot_to_epoch(slot)
        t = chain.t
        try:
            cps = chain.committees_per_slot_at(epoch)
        except Exception as e:
            _LOG.debug("%s committees at %d unavailable: %s",
                       sn.name, slot, e)
            return
        for index in range(cps):
            try:
                data = chain.produce_attestation_data(slot, index)
                committee = chain.committee_for(data)
            except Exception as e:
                _LOG.debug("%s attest (%d,%d) skipped: %s",
                           sn.name, slot, index, e)
                continue
            root = t.AttestationData.hash_tree_root(data)
            for pos, v in enumerate(committee):
                if self._owner(int(v)) != sn.name:
                    continue
                sig = self._sign(
                    self.keypairs[int(v)],
                    self.spec.DOMAIN_BEACON_ATTESTER,
                    int(data.target.epoch),
                    root,
                )
                att = t.Attestation(
                    aggregation_bits=[
                        i == pos for i in range(len(committee))
                    ],
                    data=data,
                    signature=sig,
                )
                sn.node.publish_attestation(att)
                # the attester's own node hears its own vote
                sn.node.processor.submit(
                    "gossip_attestation", (att, sn.name)
                )

    def _self_aggregate(self, sn: SimNode, slot: int):
        """Aggregate-plane stand-in: what the node's naive pool built
        for `slot` becomes op-pool material for ITS next proposal."""
        for agg in sn.chain.naive_pool.aggregates_at_slot(slot):
            sn.chain.op_pool.insert_attestation(agg)

    # ----------------------------------------------------------- timeline

    def _apply_timeline(self, slot: int):
        for f in self.scenario.faults:
            if f.at_slot == slot:
                self._start_fault(f, slot)
            if f.until_slot == slot:
                self._end_fault(f, slot)

    def _start_fault(self, f, slot: int):
        sc = self.scenario
        if f.kind == "partition":
            groups = [
                frozenset(f"node{i}" for i in g) for g in f.groups
            ]
            self.conditioner.set_partition(groups)
            self._emit_all(
                slot, "partition_applied",
                groups="|".join(
                    ",".join(sorted(g)) for g in groups
                ),
            )
        elif f.kind == "eclipse":
            name = sc.node_name(f.node)
            self.eclipse_windows[name] = (f.at_slot, f.until_slot)
            self.conditioner.isolate(name)
            self._emit_all(slot, "eclipse_applied", node=name)
        elif f.kind == "offline":
            self._take_offline(sc.node_name(f.node), slot)
        elif f.kind == "kv_crash":
            self._kv_crash(sc.node_name(f.node), slot)
        elif f.kind.startswith("device_"):
            self._arm_device_fault(f, slot)
        # spam_flood / rpc_flood are windowed actions, driven per slot

    def _end_fault(self, f, slot: int):
        sc = self.scenario
        if f.kind == "partition":
            self.conditioner.clear_partition()
            self._emit_all(slot, "partition_lifted")
        elif f.kind == "eclipse":
            name = sc.node_name(f.node)
            self.conditioner.release(name)
            self._emit_all(slot, "eclipse_lifted", node=name)
            sn = self._by_name(name)
            window = self.eclipse_windows[name]
            produced = [
                s for s in sn.produced_slots if window[0] <= s < window[1]
            ]
            if not produced:
                # a pure stall recovers over req/resp; a victim that
                # built its own fork re-converges through gossip parent
                # chains + attestation weight instead
                sn.node.sync.run_range_sync()
        elif f.kind == "offline":
            self._restart(sc.node_name(f.node), slot)
        elif f.kind.startswith("device_"):
            self._disarm_device_fault(f, slot)

    def _arm_device_fault(self, f, slot: int):
        """Window edge: arm the deterministic device-fault injector for
        this fault's plane and tighten the guarded executor for fast,
        replay-stable breaker dynamics — threshold 2 (two faulted
        dispatches open it), zero cooldown (the first post-disarm
        dispatch probes and closes), canary forced on so flipped
        verdicts are caught on the host backend too."""
        from lighthouse_tpu.device_plane import GUARD, INJECTOR

        kind = f.kind[len("device_"):]
        INJECTOR.arm(
            kind, f.plane, rate=1.0, seed=self.scenario.seed
        )
        GUARD.configure(threshold=2, cooldown_s=0.0, canary="on")
        self._emit_all(
            slot, "device_fault_armed",
            node=self.scenario.node_name(f.node),
            fault=kind, plane=f.plane,
        )

    def _disarm_device_fault(self, f, slot: int):
        from lighthouse_tpu.device_plane import INJECTOR

        kind = f.kind[len("device_"):]
        INJECTOR.disarm(kind=kind, plane=f.plane)
        self._emit_all(
            slot, "device_fault_disarmed",
            node=self.scenario.node_name(f.node),
            fault=kind, plane=f.plane,
        )

    def _by_name(self, name: str) -> SimNode:
        return next(sn for sn in self.nodes if sn.name == name)

    def _take_offline(self, name: str, slot: int):
        sn = self._by_name(name)
        self._emit_all(slot, "node_offline", node=name)
        sn.archive_journal()
        self.conditioner.set_offline(name, True)
        sn.online = False
        sn.api.stop()
        sn.net.close()

    def _restart(self, name: str, slot: int):
        """Bring a node back: checkpoint-sync from a live peer when the
        network has finalized (anchor + forward range sync + history
        backfill), plain re-sync from genesis otherwise. The finality
        read comes from the provider's HEALTH endpoint — even driving
        decisions ride the observability plane where they can."""
        sn = self._by_name(name)
        provider = next(
            (p for p in self._honest_online() if p is not sn), None
        )
        self.conditioner.set_offline(name, False)
        anchor_block = None
        genesis_state = self.genesis.copy()
        if provider is None:
            # nobody to checkpoint from (overlapping fault windows took
            # every other honest node down too): reboot from genesis and
            # let gossip/sync catch the node up once peers return
            _LOG.warning(
                "%s restart at slot %d with no online provider — "
                "genesis reboot", name, slot,
            )
            health = {"head": {"finalized_epoch": 0}}
        else:
            health = self._get_json(
                provider.base_url() + "/lighthouse/health"
            )["data"]
        if health["head"]["finalized_epoch"] >= 1:
            from lighthouse_tpu.http_api.client import fetch_checkpoint

            state, block = fetch_checkpoint(
                provider.base_url(), self.spec
            )
            genesis_state, anchor_block = state, block
            sn.anchor_slot = int(state.slot)
        self._boot_node(sn, genesis_state, anchor_block=anchor_block)
        sn.restart_slots.append(slot)
        self._connect_to_online(sn)
        sn.node.on_slot(slot)
        imported = sn.node.sync.run_range_sync()
        stored = sn.node.sync.run_backfill()
        sn.chain.journal.emit(
            "sim_fault",
            slot=slot,
            outcome="node_restarted",
            node=name,
            anchor_slot=sn.anchor_slot,
            range_synced=imported,
            backfilled=stored,
        )

    def _kv_crash(self, name: str, slot: int):
        """Hard-crash a node mid-write: tear the tail of its WAL (the
        torn-record shape the native kv's replay drops whole), reboot it
        over the SURVIVING kv prefix, and re-sync the difference."""
        sn = self._by_name(name)
        self._emit_all(slot, "kv_crash", node=name)
        sn.archive_journal()
        self.conditioner.set_offline(name, True)
        sn.online = False
        sn.api.stop()
        sn.net.close()
        kv = sn.chain.store.kv
        try:
            kv.close()
        except Exception as e:
            _LOG.debug("kv close during crash: %s", e)
        from lighthouse_tpu.native import kvstore

        native = kvstore.available()
        if native and sn.kv_path and os.path.exists(sn.kv_path):
            # tear the WAL tail: the torn group record must be dropped
            # WHOLE on replay (the kv's batch-atomicity contract)
            size = os.path.getsize(sn.kv_path)
            if size > 16:
                with open(sn.kv_path, "r+b") as fh:
                    fh.truncate(size - 7)
        if native and sn.kv_path:
            new_kv = kvstore.NativeKVStore(sn.kv_path)
        elif sn.kv_path:
            # sqlite fallback: no WAL to tear (its own journal handles
            # torn writes); the crash still exercises reboot + re-sync
            from lighthouse_tpu.store import SqliteStore

            new_kv = SqliteStore(sn.kv_path)
        else:
            new_kv = None
        self.conditioner.set_offline(name, False)
        self._boot_node(sn, self.genesis.copy(), kv=new_kv)
        sn.restart_slots.append(slot)
        self._connect_to_online(sn)
        sn.node.on_slot(slot)
        imported = sn.node.sync.run_range_sync()
        sn.chain.journal.emit(
            "sim_fault",
            slot=slot,
            outcome="kv_replayed",
            node=name,
            range_synced=imported,
        )

    # ---------------------------------------------------------- adversary

    def _junk_sidecar(self, t, slot: int, i: int, bad_index: bool):
        import hashlib

        tag = hashlib.sha256(
            f"{self.scenario.seed}:spam:{slot}:{i}".encode()
        ).digest()
        header = t.SignedBeaconBlockHeader(
            message=t.BeaconBlockHeader(
                slot=slot,
                proposer_index=0,
                parent_root=tag,
                state_root=tag,
                body_root=tag,
            ),
            signature=tag * 3,
        )
        index = (
            self.spec.MAX_BLOBS_PER_BLOCK
            if bad_index
            else i % self.spec.MAX_BLOBS_PER_BLOCK
        )
        return t.BlobSidecar(
            index=index,
            blob=_deterministic_blob(self.spec, slot * 131 + i),
            kzg_commitment=tag + tag[:16],
            kzg_proof=tag + tag[:16],
            signed_block_header=header,
        )

    def _junk_attestation(self, t, slot: int, i: int):
        """Seeded flood attestation (the shared cheap-reject fixture,
        lighthouse_tpu.testing.make_junk_attestation)."""
        import hashlib

        from lighthouse_tpu.testing import make_junk_attestation

        tag = hashlib.sha256(
            f"{self.scenario.seed}:attflood:{slot}:{i}".encode()
        ).digest()
        return make_junk_attestation(t, self.spec, slot, tag)

    def _rest_burst(self, sn: SimNode, slot: int, rate: int):
        """`rate` concurrent REST reads against `sn`'s API, barrier-
        released so they genuinely overlap: a mix of expensive reads
        (admission-limited -> some shed 503) and hot cacheable reads
        (served from the TTL cache after the first store hit). Sheds
        and cache hits land in the registry; nothing touches the
        journal, so the canonical replay surface is unaffected."""
        import threading
        import urllib.error

        base = sn.base_url()
        paths = []
        for i in range(rate):
            if i % 3 == 0:
                # expensive class: whole-validator-set walk
                paths.append("/eth/v1/beacon/states/head/validators")
            else:
                # hot cacheable read: finalized checkpoint document
                paths.append(
                    "/eth/v1/beacon/states/finalized/"
                    "finality_checkpoints"
                )
        barrier = threading.Barrier(len(paths) + 1)

        def fire(path):
            barrier.wait(timeout=10)
            try:
                with urllib.request.urlopen(base + path, timeout=10):
                    pass
            except (urllib.error.HTTPError, OSError) as e:
                # 503 sheds are the POINT; they are counted by the
                # admission plane on the server side
                _LOG.debug("rest flood request refused: %s", e)
            _SPAM_TOTAL.labels("rest_read").inc()

        threads = [
            threading.Thread(target=fire, args=(p,), daemon=True)
            for p in paths
        ]
        for th in threads:
            th.start()
        barrier.wait(timeout=10)
        for th in threads:
            th.join(timeout=15)

    def _run_spam(self, slot: int):
        for f in self.scenario.faults:
            if f.kind not in (
                "spam_flood", "rpc_flood", "att_flood", "rest_flood"
            ):
                continue
            if not f.active(slot):
                continue
            sn = self._by_name(self.scenario.node_name(f.node))
            if not sn.online:
                continue
            if f.kind == "att_flood":
                # junk attestation gossip from the actor: every honest
                # node's attestation queue fills until the shedding
                # policy's window opens (the overload scenario's
                # processor_bounds make that happen within one slot)
                t = sn.chain.t
                for i in range(f.rate):
                    att = self._junk_attestation(t, slot, i)
                    sn.node.publish_attestation(att)
                    _SPAM_TOTAL.labels("gossip_attestation_flood").inc()
            elif f.kind == "rest_flood":
                # `node` names the TARGET here: concurrent REST reads
                # against its HTTP edge
                self._rest_burst(sn, slot, f.rate)
            elif f.kind == "spam_flood":
                t = sn.chain.t
                for i in range(f.rate):
                    # one structurally-invalid sidecar per slot prices
                    # the spammer's score; the rest are candidate-cache
                    # junk (priced by the cache caps, not pairings)
                    bad = i == 0
                    scd = self._junk_sidecar(t, slot, i, bad_index=bad)
                    sn.node.publish_blob_sidecar(scd)
                    _SPAM_TOTAL.labels(
                        "gossip_sidecar_invalid"
                        if bad
                        else "gossip_sidecar"
                    ).inc()
            elif f.kind == "rpc_flood":
                from lighthouse_tpu.network.rpc import (
                    RateLimitExceeded,
                    RpcError,
                )

                for victim in self._honest_online():
                    client = sn.node.sync.peers.get(victim.name)
                    if client is None:
                        continue
                    for _ in range(f.rate):
                        try:
                            client.status(sn.name)
                        except (RateLimitExceeded, RpcError) as e:
                            _LOG.debug("rpc flood bounced: %s", e)
                        _SPAM_TOTAL.labels("rpc_burst").inc()

    # ---------------------------------------------------------------- run

    def run(self) -> dict:
        from lighthouse_tpu.sim import invariants as inv
        from lighthouse_tpu.sim import verdict as vd

        if self.scenario.kind == "vc_http":
            return self._run_vc_http()
        if self.scenario.kind == "lc_serve":
            return self._run_lc_serve()
        snapshot_before = REGISTRY.snapshot()
        self._build()
        if any(
            f.kind in ("att_flood", "rest_flood")
            for f in self.scenario.faults
        ):
            # pre-flood serving budget: the overload_recovery invariant
            # holds every node's POST-flood probes to a multiple of this
            for sn in self._honest_online():
                self.probe_budget[sn.name] = self._probe_latency(sn)
        for slot in range(1, self.scenario.slots + 1):
            self._slot = slot
            _SLOTS_TOTAL.inc()
            self._apply_timeline(slot)
            for sn in self._online():
                sn.node.on_slot(slot)
            self._run_spam(slot)
            for sn in self._online():
                if sn.index is None:
                    continue
                proposer = self._proposer_at(sn, slot)
                if self._owner(int(proposer)) == sn.name:
                    self._propose(sn, slot)
            self._settle()
            self._drain_all()
            for sn in self._online():
                if sn.index is not None:
                    self._attest(sn, slot)
            self._settle()
            self._drain_all()
            for sn in self._honest_online():
                sampler = getattr(sn.node, "das_sampler", None)
                if sampler is not None:
                    sampler.poll(slot)
            for sn in self._online():
                self._self_aggregate(sn, slot)
        snapshot_after = REGISTRY.snapshot()
        ctx = inv.SimContext(
            scenario=self.scenario,
            nodes={
                sn.name: sn for sn in self.nodes
            },
            snapshot_before=snapshot_before,
            snapshot_after=snapshot_after,
            blob_blocks=dict(self.blob_blocks),
            das_blocks=dict(self.das_blocks),
            eclipse_windows=dict(self.eclipse_windows),
            probe_budget=dict(self.probe_budget),
        )
        violations = inv.check_all(ctx, self.scenario.invariants)
        report = vd.build_report(self, ctx, violations)
        _RUNS_TOTAL.labels("violations" if violations else "ok").inc()
        return report

    # -------------------------------------------------------- vc_http kind

    def _run_vc_http(self) -> dict:
        """Satellite scenario: a BN booted exactly like `bn` serves a
        VC that talks ONLY over HTTP (cmd_vc --beacon-node-url wiring,
        with a dead fallback URL exercised first), and the chain
        finalizes from the VC's duties alone."""
        from lighthouse_tpu.cli import build_http_vc
        from lighthouse_tpu.sim import invariants as inv
        from lighthouse_tpu.sim import verdict as vd

        snapshot_before = REGISTRY.snapshot()
        sn = SimNode("node0", 0)
        sn.node = BeaconNode(
            sn.name, self.genesis.copy(), self.spec,
            backend=self.scenario.backend,
        )
        sn.node.chain.journal.configure(
            capacity=self.scenario.journal_capacity
        )
        sn.api = sn.node.start_http_api()
        self.nodes.append(sn)
        # a dead candidate FIRST: the fallback ranking must route every
        # request past it to the live BN
        vc = build_http_vc(
            ["http://127.0.0.1:9", sn.base_url()],
            self.keypairs,
            self.spec,
        )
        for slot in range(1, self.scenario.slots + 1):
            _SLOTS_TOTAL.inc()
            sn.node.on_slot(slot)
            vc.run_slot(slot)
            self._drain(sn)
        snapshot_after = REGISTRY.snapshot()
        ctx = inv.SimContext(
            scenario=self.scenario,
            nodes={sn.name: sn},
            snapshot_before=snapshot_before,
            snapshot_after=snapshot_after,
            blob_blocks={},
            eclipse_windows={},
        )
        violations = inv.check_all(ctx, self.scenario.invariants)
        report = vd.build_report(self, ctx, violations)
        report["vc_metrics"] = dict(vc.metrics)
        _RUNS_TOTAL.labels("violations" if violations else "ok").inc()
        return report

    # -------------------------------------------------------- lc_serve kind

    def _sync_committee_sign(self, sn: SimNode, slot: int):
        """Every distinct validator in the node's current sync
        committee signs a SyncCommitteeMessage over the head root; the
        verified messages aggregate through the naive pool into the
        contribution pool, so the NEXT block's sync aggregate carries
        full participation (the in-process stand-in for the sync-
        committee gossip plane, mirroring _self_aggregate)."""
        chain = sn.chain
        state = chain.head_state
        if not hasattr(state, "current_sync_committee"):
            return
        t = chain.t
        head_root = chain.head_root
        epoch = self.spec.slot_to_epoch(slot)
        msgs = []
        seen = set()
        for pk in state.current_sync_committee.pubkeys:
            idx = chain.pubkey_cache.index_of(bytes(pk))
            if idx is None or idx in seen:
                continue
            seen.add(idx)
            sig = self._sign(
                self.keypairs[idx],
                self.spec.DOMAIN_SYNC_COMMITTEE,
                epoch,
                head_root,
            )
            msgs.append(
                t.SyncCommitteeMessage(
                    slot=slot,
                    beacon_block_root=head_root,
                    validator_index=idx,
                    signature=sig,
                )
            )
        if not msgs:
            return
        chain.process_sync_messages(msgs)
        for sub in range(self.spec.SYNC_COMMITTEE_SUBNET_COUNT):
            c = chain.sync_message_pool.get_contribution(
                slot, head_root, sub
            )
            if c is not None:
                chain.sync_contribution_pool.insert(c)

    def _run_lc_serve(self) -> dict:
        """One full node serves a light-client actor that bootstraps
        from a single trusted finalized root and tracks the honest
        chain through the light_client endpoints alone; its aggregate
        checks ride the node's verification bus under the
        ``light_client`` consumer label. All claims are asserted
        through /lighthouse/events + /lighthouse/health + registry
        diffs, and the canonical journal replays byte-identically."""
        from lighthouse_tpu.sim import invariants as inv
        from lighthouse_tpu.sim import verdict as vd
        from lighthouse_tpu.sim.lc_actor import LightClientActor

        snapshot_before = REGISTRY.snapshot()
        sn = SimNode("node0", 0)
        self._boot_node(sn, self.genesis.copy())
        self.nodes.append(sn)
        actor = LightClientActor(
            sn.base_url(),
            self.spec,
            self.gvr,
            bus=sn.chain.verification_bus,
        )
        for slot in range(1, self.scenario.slots + 1):
            self._slot = slot
            _SLOTS_TOTAL.inc()
            sn.node.on_slot(slot)
            self._propose(sn, slot)
            self._drain(sn)
            self._attest(sn, slot)
            self._drain(sn)
            self._self_aggregate(sn, slot)
            self._sync_committee_sign(sn, slot)
            actor.poll()
        # one final poll so the actor hears the last import's documents
        actor.poll()
        snapshot_after = REGISTRY.snapshot()
        ctx = inv.SimContext(
            scenario=self.scenario,
            nodes={sn.name: sn},
            snapshot_before=snapshot_before,
            snapshot_after=snapshot_after,
            blob_blocks={},
            eclipse_windows={},
            lc_client=actor.summary(),
        )
        violations = inv.check_all(ctx, self.scenario.invariants)
        report = vd.build_report(self, ctx, violations)
        report["lc_client"] = actor.summary()
        _RUNS_TOTAL.labels("violations" if violations else "ok").inc()
        return report

    # ------------------------------------------------------------- teardown

    def close(self):
        for sn in self.nodes:
            if sn.api is not None and sn.online:
                try:
                    sn.api.stop()
                except Exception as e:
                    _LOG.debug("api stop: %s", e)
            if sn.net is not None:
                sn.net.close()

    @staticmethod
    def _get_json(url: str) -> dict:
        with urllib.request.urlopen(url, timeout=10) as r:
            return json.loads(r.read())


class _TypesShim:
    """Duck-typed `self` for Harness.make_blob_sidecars /
    make_data_column_sidecars (which read only `self.t` and
    `self.spec`) so the sidecar-building logic stays in ONE place."""

    def __init__(self, t, spec=None):
        self.t = t
        self.spec = spec
