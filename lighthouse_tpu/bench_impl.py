"""BENCH_IMPL validation + env side effects, shared by every benchmark
config (bench.py configs and lighthouse_tpu.bench_replay) so an impl
added or renamed in one place cannot be silently mislabeled in another."""

import os
import sys

KNOWN_IMPLS = (
    "xla", "mxu", "pallas", "ptail", "txla", "predc", "predcbf", "pw2",
)


def apply_impl_env(impl: str, what: str = "bench") -> None:
    """Validate `impl` and apply its process-env side effects. Exits 4
    on an unknown impl — a typo must not measure the default path under
    its label."""
    if impl not in KNOWN_IMPLS:
        print(f"{what}: unknown BENCH_IMPL {impl!r}", file=sys.stderr)
        sys.exit(4)
    if impl == "mxu":
        os.environ["LIGHTHOUSE_TPU_MXU_CONV"] = "1"
    if impl == "predc":
        # pallas kernels with the static REDC convolutions on the MXU
        os.environ["LIGHTHOUSE_TPU_MXU_REDC"] = "i8"
    if impl == "predcbf":
        os.environ["LIGHTHOUSE_TPU_MXU_REDC"] = "bf16"
    if impl == "pw2":
        # pallas kernels with the windowed-2 RLC ladder
        os.environ["LIGHTHOUSE_TPU_LADDER"] = "w2"
