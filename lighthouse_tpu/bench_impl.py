"""BENCH_IMPL validation + env side effects, shared by every benchmark
config (bench.py configs and lighthouse_tpu.bench_replay) so an impl
added or renamed in one place cannot be silently mislabeled in another.

Since the unified windowed-ladder PR the DEFAULT device path is:
signed-digit window ladders (ops/window_ladder), the FP12_SQR squaring
program, and bf16 MXU-REDC on real TPU. The labels that used to select
those forms as experiments (pw2, predcbf) are RETIRED and exit(4); the
LEGACY forms they replaced are the A/B partners now:

  chain   — legacy per-bit double-add ladders (LIGHTHOUSE_TPU_LADDER)
  vredc   — legacy unrolled-VPU Montgomery REDC (LIGHTHOUSE_TPU_MXU_REDC=0)
  mulsqr  — legacy generic-multiply Fp12 squaring (LIGHTHOUSE_TPU_FP12_SQR)
"""

import os
import sys

KNOWN_IMPLS = (
    "xla", "mxu", "pallas", "ptail", "txla", "predc",
    "chain", "vredc", "mulsqr",
)

# retired labels -> the message explaining the A/B partner that
# replaced them (the old experimental form IS the default now)
RETIRED_IMPLS = {
    "pw2": (
        "the signed-digit window ladder is the DEFAULT now; A/B the"
        " legacy double-add chain with BENCH_IMPL=chain"
    ),
    "predcbf": (
        "bf16 MXU-REDC is the DEFAULT device form on TPU now; A/B the"
        " legacy VPU chain with BENCH_IMPL=vredc"
    ),
}


def validate_impl(impl: str, what: str = "bench") -> None:
    """Exit 4 on an unknown OR retired impl — a typo must not measure
    the default path under its label, and a retired label must not
    silently measure the new default under the old experimental name.
    Called by `apply_impl_env` and ALSO by bench.py's outer watchdog
    stage, whose replay short-circuit would otherwise answer a retired
    label with a recorded measurement before any config validated it."""
    if impl in RETIRED_IMPLS:
        print(
            f"{what}: BENCH_IMPL={impl} is retired — {RETIRED_IMPLS[impl]}",
            file=sys.stderr,
        )
        sys.exit(4)
    if impl not in KNOWN_IMPLS:
        print(f"{what}: unknown BENCH_IMPL {impl!r}", file=sys.stderr)
        sys.exit(4)


def apply_impl_env(impl: str, what: str = "bench") -> None:
    """Validate `impl` and apply its process-env side effects (exits 4
    on unknown/retired — see `validate_impl`)."""
    validate_impl(impl, what)
    if impl == "mxu":
        os.environ["LIGHTHOUSE_TPU_MXU_CONV"] = "1"
    if impl == "predc":
        # pallas kernels with the static REDC convolutions as int8 MXU
        # matmuls (the non-default operand form; bf16 is the default)
        os.environ["LIGHTHOUSE_TPU_MXU_REDC"] = "i8"
    if impl == "ptail":
        # pallas kernels + the fused in-kernel final-exponentiation tail
        os.environ["LIGHTHOUSE_TPU_TAIL"] = "1"
    if impl == "chain":
        os.environ["LIGHTHOUSE_TPU_LADDER"] = "chain"
    if impl == "vredc":
        os.environ["LIGHTHOUSE_TPU_MXU_REDC"] = "0"
    if impl == "mulsqr":
        os.environ["LIGHTHOUSE_TPU_FP12_SQR"] = "mul"
