"""BENCH_CONFIG=slotfuse: serial vs one-dispatch-slot A/B.

Drives the SAME deterministic blob-import schedule through two full
`BeaconNode` stacks — one with `--slot-fuse` off (the serial
settle-then-fold shape, two device round trips per blob import) and
one with it on (the chained slot-program, one round trip) — and
reports both arms side by side:

  * wall p50/p99 per arm and the fused/serial speedup ratio;
  * serial-dispatch counts per import (the fused arm must show
    `serial_dispatches_max == 1` with the settle riding a dispatch of
    kind ``fused``);
  * verdict byte-identity: the two arms' canonical journal
    projections (sim/verdict.py — block_import, da_settle, sidecar,
    ... with volatile fields stripped) must be byte-equal, and the two
    head roots must match. A fused run that is fast but diverges is a
    FAILED measurement, not a win.

Geometry comes from bench_slotpath's env knobs (SLOTPATH_BLOCKS /
SLOTPATH_BLOB_PERIOD / SLOTPATH_BLOBS), so the A/B can be pushed to
heavier blob counts without editing either file. Fake backend off
hardware (the CPU proxy: structure exact, milliseconds not hardware),
tpu backend when the tunnel is up.
"""

import os

from lighthouse_tpu.bench_slotpath import _blob, _build_node, _geometry
from lighthouse_tpu.sim.verdict import canonical_jsonl


def _drive(backend: str, fuse: bool) -> dict:
    """One arm: boot a node, toggle the fuse, import the schedule, and
    return its timing + forensic summary."""
    from lighthouse_tpu.state_processing.per_block import (
        BlockSignatureStrategy,
    )
    from lighthouse_tpu import kzg

    n_imports, blob_period, blobs_per_slot = _geometry()
    h, node = _build_node(backend)
    chain = node.chain
    chain.slot_fuse = fuse
    recorder = chain.slot_budget
    recorder.configure(ring=max(n_imports + 8, 128))
    blob_start = int(h.spec.SLOTS_PER_EPOCH)
    blob_imports = 0
    for slot in range(1, n_imports + 1):
        node.on_slot(slot)
        if slot >= blob_start and slot % blob_period == 0:
            blob_imports += 1
            blobs = [
                _blob(h.spec, slot * 16 + i)
                for i in range(blobs_per_slot)
            ]
            comms = [
                kzg.blob_to_kzg_commitment(b, consumer="bench")
                for b in blobs
            ]
            block = h.produce_block(
                slot, [], blob_kzg_commitments=comms
            )
            h.import_block(
                block, strategy=BlockSignatureStrategy.NO_VERIFICATION
            )
            for sc in h.make_blob_sidecars(block, blobs):
                chain.process_blob_sidecar(sc)
        else:
            block = h.produce_block(slot, [])
            h.import_block(
                block, strategy=BlockSignatureStrategy.NO_VERIFICATION
            )
        chain.process_block(block)

    recs = recorder.recent()
    summary = recorder.summary()
    budget_complete = bool(recs) and all(
        abs(r["union_s"] + r["unattributed_s"] - r["wall_s"]) <= 1e-3
        and r["serial_dispatches"] == len(r["dispatches"])
        for r in recs
    )
    fused_imports = sum(
        1
        for r in recs
        if any(d.get("kind") == "fused" for d in r["dispatches"])
    )
    return {
        "wall_p50_ms": round((summary["wall_p50_s"] or 0.0) * 1e3, 3),
        "wall_p99_ms": round((summary["wall_p99_s"] or 0.0) * 1e3, 3),
        "serial_dispatches_p50": summary["serial_dispatches_p50"],
        "serial_dispatches_max": summary["serial_dispatches_max"],
        "budget_complete": budget_complete,
        "blob_imports": blob_imports,
        "fused_imports": fused_imports,
        "canonical": canonical_jsonl(chain.journal.query()),
        "head_root": chain.head_root.hex(),
    }


def measure(jax, platform):
    on_tpu = platform in ("tpu", "axon")
    backend = os.environ.get(
        "BENCH_SLOTPATH_BACKEND", "tpu" if on_tpu else "fake"
    )
    n_imports, blob_period, blobs_per_slot = _geometry()

    serial = _drive(backend, fuse=False)
    fused = _drive(backend, fuse=True)

    # the byte-identity gate: identical canonical forensic record and
    # identical head — the fused path changed the dispatch shape, not
    # one observable verdict
    identical = (
        serial["canonical"] == fused["canonical"]
        and serial["head_root"] == fused["head_root"]
    )
    speedup = (
        round(serial["wall_p50_ms"] / fused["wall_p50_ms"], 3)
        if fused["wall_p50_ms"] > 0
        else 0.0
    )

    def arm(d):
        return {k: v for k, v in d.items() if k != "canonical"}

    return {
        "metric": "slotfuse_speedup",
        "value": speedup,
        "unit": "x",
        "vs_baseline": 0.0,
        "platform": platform,
        "impl": backend,
        "n_sets": n_imports,
        "blob_period": blob_period,
        "blobs_per_slot": blobs_per_slot,
        "serial": arm(serial),
        "fused": arm(fused),
        "verdicts_identical": identical,
        "fused_single_dispatch": fused["serial_dispatches_max"] <= 1,
        "budget_complete": (
            serial["budget_complete"] and fused["budget_complete"]
        ),
        "valid_for_headline": bool(
            on_tpu and identical and n_imports >= 16
        ),
    }
