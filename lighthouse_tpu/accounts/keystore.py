"""EIP-2335 encrypted BLS keystores (scrypt/PBKDF2 + AES-128-CTR).

Role of the reference's crypto/eth2_keystore (keystore.rs: EIP-2335 JSON
keystores used for every validator key at rest). Encrypt/decrypt round
trips are validated against the EIP's published structure: a KDF module
(scrypt or pbkdf2), sha256 checksum over dk[16:32] || ciphertext, and
AES-128-CTR cipher with the first 16 bytes of the derived key.
"""

import hashlib
import json
import os
import unicodedata
import uuid

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


def _normalize_password(password: str) -> bytes:
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        c for c in norm if ord(c) >= 0x20 and ord(c) != 0x7F
    )
    return stripped.encode("utf-8")


def _aes128ctr(key16: bytes, iv16: bytes, data: bytes) -> bytes:
    cipher = Cipher(algorithms.AES(key16), modes.CTR(iv16))
    enc = cipher.encryptor()
    return enc.update(data) + enc.finalize()


class KeystoreError(ValueError):
    pass


class Keystore:
    def __init__(self, doc: dict):
        self.doc = doc

    # ------------------------------------------------------------ encrypt

    @classmethod
    def encrypt(
        cls,
        secret: bytes,
        password: str,
        path: str = "",
        kdf: str = "scrypt",
        pubkey: bytes | None = None,
    ) -> "Keystore":
        salt = os.urandom(32)
        iv = os.urandom(16)
        pw = _normalize_password(password)
        if kdf == "scrypt":
            dk = hashlib.scrypt(
                pw, salt=salt, n=2**18, r=8, p=1, dklen=32, maxmem=2**31 - 1
            )
            kdf_module = {
                "function": "scrypt",
                "params": {
                    "dklen": 32,
                    "n": 2**18,
                    "r": 8,
                    "p": 1,
                    "salt": salt.hex(),
                },
                "message": "",
            }
        elif kdf == "pbkdf2":
            dk = hashlib.pbkdf2_hmac("sha256", pw, salt, 262144, dklen=32)
            kdf_module = {
                "function": "pbkdf2",
                "params": {
                    "dklen": 32,
                    "c": 262144,
                    "prf": "hmac-sha256",
                    "salt": salt.hex(),
                },
                "message": "",
            }
        else:
            raise KeystoreError(f"unknown kdf {kdf}")
        ciphertext = _aes128ctr(dk[:16], iv, secret)
        checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
        doc = {
            "crypto": {
                "kdf": kdf_module,
                "checksum": {
                    "function": "sha256",
                    "params": {},
                    "message": checksum.hex(),
                },
                "cipher": {
                    "function": "aes-128-ctr",
                    "params": {"iv": iv.hex()},
                    "message": ciphertext.hex(),
                },
            },
            "path": path,
            "pubkey": pubkey.hex() if pubkey else "",
            "uuid": str(uuid.uuid4()),
            "version": 4,
        }
        return cls(doc)

    # ------------------------------------------------------------ decrypt

    def decrypt(self, password: str) -> bytes:
        crypto = self.doc["crypto"]
        kdf = crypto["kdf"]
        pw = _normalize_password(password)
        salt = bytes.fromhex(kdf["params"]["salt"])
        if kdf["function"] == "scrypt":
            p = kdf["params"]
            dk = hashlib.scrypt(
                pw,
                salt=salt,
                n=p["n"],
                r=p["r"],
                p=p["p"],
                dklen=p["dklen"],
                maxmem=2**31 - 1,
            )
        elif kdf["function"] == "pbkdf2":
            p = kdf["params"]
            dk = hashlib.pbkdf2_hmac(
                "sha256", pw, salt, p["c"], dklen=p["dklen"]
            )
        else:
            raise KeystoreError("unknown kdf")
        ciphertext = bytes.fromhex(crypto["cipher"]["message"])
        checksum = hashlib.sha256(dk[16:32] + ciphertext).digest()
        if checksum.hex() != crypto["checksum"]["message"]:
            raise KeystoreError("invalid password (checksum mismatch)")
        iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
        return _aes128ctr(dk[:16], iv, ciphertext)

    # --------------------------------------------------------------- json

    def to_json(self) -> str:
        return json.dumps(self.doc, indent=2)

    @classmethod
    def from_json(cls, payload: str) -> "Keystore":
        doc = json.loads(payload)
        if doc.get("version") != 4:
            raise KeystoreError("unsupported keystore version")
        return cls(doc)

    @property
    def pubkey_hex(self) -> str:
        return self.doc.get("pubkey", "")

    @property
    def path(self) -> str:
        return self.doc.get("path", "")
