"""On-disk validator directory layout.

Role of common/validator_dir + account_utils: each validator gets
`<base>/0x<pubkey>/` holding its EIP-2335 voting keystore and a PID
lockfile guarding against two processes loading the same keys; passwords
live in a sibling secrets dir keyed by pubkey.
"""

import os

from lighthouse_tpu.accounts.keystore import Keystore
from lighthouse_tpu.common.lockfile import Lockfile

VOTING_KEYSTORE_FILE = "voting-keystore.json"
LOCK_FILE = ".lock"


class ValidatorDir:
    def __init__(self, path: str):
        self.path = path
        self.lock = Lockfile(os.path.join(path, LOCK_FILE))

    @property
    def pubkey_hex(self) -> str:
        return os.path.basename(self.path)

    @classmethod
    def create(
        cls,
        base_dir: str,
        keystore: Keystore,
        password: str,
        secrets_dir: str | None = None,
    ) -> "ValidatorDir":
        """Materialize `<base>/0x<pubkey>/voting-keystore.json` (+ the
        password in the secrets dir)."""
        name = "0x" + keystore.pubkey_hex
        path = os.path.join(base_dir, name)
        os.makedirs(path, mode=0o700, exist_ok=True)

        def _write_private(p: str, content: str):
            # 0600: keystores and plaintext passwords must not be
            # world-readable on shared hosts
            fd = os.open(p, os.O_CREAT | os.O_TRUNC | os.O_WRONLY, 0o600)
            with os.fdopen(fd, "w") as f:
                f.write(content)

        _write_private(
            os.path.join(path, VOTING_KEYSTORE_FILE), keystore.to_json()
        )
        if secrets_dir is not None:
            os.makedirs(secrets_dir, mode=0o700, exist_ok=True)
            _write_private(os.path.join(secrets_dir, name), password)
        return cls(path)

    def voting_keystore(self) -> Keystore:
        with open(os.path.join(self.path, VOTING_KEYSTORE_FILE)) as f:
            return Keystore.from_json(f.read())

    def decrypt_voting_key(
        self, password: str | None = None, secrets_dir: str | None = None
    ) -> bytes:
        if password is None:
            if secrets_dir is None:
                raise ValueError("need a password or a secrets dir")
            with open(
                os.path.join(secrets_dir, self.pubkey_hex)
            ) as f:
                # tolerate `echo pw > file`-style provisioning
                password = f.read().rstrip("\n")
        return self.voting_keystore().decrypt(password)


def list_validator_dirs(base_dir: str):
    if not os.path.isdir(base_dir):
        return []
    return [
        ValidatorDir(os.path.join(base_dir, d))
        for d in sorted(os.listdir(base_dir))
        if d.startswith("0x")
        and os.path.isfile(
            os.path.join(base_dir, d, VOTING_KEYSTORE_FILE)
        )
    ]
