"""EIP-2386 hierarchical deterministic wallet.

Role of crypto/eth2_wallet (wallet.rs, 1,029 LoC): a JSON wallet document
holding an encrypted seed (reusing the EIP-2335 crypto module), a type
("hierarchical deterministic"), and a `nextaccount` counter; validator
voting/withdrawal keys derive from the seed at the EIP-2334 paths
m/12381/3600/{i}/0/0 and m/12381/3600/{i}/0.
"""

import json
import uuid

from lighthouse_tpu.accounts.key_derivation import (
    derive_path,
    mnemonic_to_seed,
)
from lighthouse_tpu.accounts.keystore import Keystore


class WalletError(ValueError):
    pass


def voting_key_path(index: int) -> str:
    return f"m/12381/3600/{index}/0/0"


def withdrawal_key_path(index: int) -> str:
    return f"m/12381/3600/{index}/0"


class Wallet:
    """EIP-2386 wallet: encrypted seed + account counter."""

    def __init__(self, doc: dict):
        self.doc = doc

    # ------------------------------------------------------------ create

    @classmethod
    def create(
        cls,
        name: str,
        password: str,
        seed: bytes | None = None,
        mnemonic: str | None = None,
        kdf: str = "pbkdf2",
    ) -> "Wallet":
        if seed is None:
            if mnemonic is None:
                raise WalletError("need a seed or a mnemonic")
            seed = mnemonic_to_seed(mnemonic)
        # reuse the EIP-2335 crypto envelope for the seed ciphertext
        ks = Keystore.encrypt(seed, password, kdf=kdf, pubkey=b"")
        doc = {
            "crypto": ks.doc["crypto"],
            "name": name,
            "nextaccount": 0,
            "type": "hierarchical deterministic",
            "uuid": str(uuid.uuid4()),
            "version": 1,
        }
        return cls(doc)

    # ----------------------------------------------------------- accounts

    def decrypt_seed(self, password: str) -> bytes:
        ks = Keystore({"crypto": self.doc["crypto"], "pubkey": ""})
        return ks.decrypt(password)

    @property
    def name(self) -> str:
        return self.doc["name"]

    @property
    def nextaccount(self) -> int:
        return self.doc["nextaccount"]

    def next_validator(
        self,
        wallet_password: str,
        voting_keystore_password: str,
    ):
        """Derive the next validator's voting + withdrawal keys and bump
        `nextaccount` (wallet.rs next_validator). Returns
        (index, voting_keystore, withdrawal_sk_int)."""
        seed = self.decrypt_seed(wallet_password)
        index = self.doc["nextaccount"]
        voting_sk = derive_path(seed, voting_key_path(index))
        withdrawal_sk = derive_path(seed, withdrawal_key_path(index))
        from lighthouse_tpu import bls

        sk = bls.SecretKey.from_bytes(voting_sk.to_bytes(32, "big"))
        voting_ks = Keystore.encrypt(
            voting_sk.to_bytes(32, "big"),
            voting_keystore_password,
            path=voting_key_path(index),
            kdf="pbkdf2",
            pubkey=sk.public_key().to_bytes(),
        )
        self.doc["nextaccount"] = index + 1
        return index, voting_ks, withdrawal_sk

    # --------------------------------------------------------------- json

    def to_json(self) -> str:
        return json.dumps(self.doc)

    @classmethod
    def from_json(cls, payload: str) -> "Wallet":
        doc = json.loads(payload)
        if doc.get("type") != "hierarchical deterministic":
            raise WalletError("unsupported wallet type")
        if doc.get("version") != 1:
            raise WalletError("unsupported wallet version")
        return cls(doc)
