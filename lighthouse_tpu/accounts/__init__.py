from lighthouse_tpu.accounts.key_derivation import (  # noqa: F401
    derive_child_sk,
    derive_master_sk,
    derive_path,
    mnemonic_to_seed,
)
from lighthouse_tpu.accounts.keystore import Keystore  # noqa: F401
