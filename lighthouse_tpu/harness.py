"""In-process chain harness: deterministic validators driving a real state
transition, block production, and attestation flow.

Role of the reference's `BeaconChainHarness`
(beacon_node/beacon_chain/src/test_utils.rs:47-66): interop-keypair genesis,
manual slot control, block production with full attestation participation,
and import through the real per-block pipeline — the "minimum end-to-end
slice" of SURVEY.md §7. The full BeaconChain runtime (fork choice, stores,
pools) builds on this.
"""

from lighthouse_tpu import bls
from lighthouse_tpu.ssz.cached_hash import (
    cached_state_root,
    carry_tree_cache,
)
from lighthouse_tpu.ssz.hashing import ZERO_BYTES32
from lighthouse_tpu.state_processing.helpers import (
    CommitteeCache,
    get_beacon_proposer_index,
    get_block_root_at_slot,
    get_current_epoch,
    get_domain,
)
from lighthouse_tpu.state_processing.genesis import interop_genesis_state
from lighthouse_tpu.state_processing.per_block import (
    BlockSignatureStrategy,
    per_block_processing,
)
from lighthouse_tpu.state_processing.per_slot import process_slots
from lighthouse_tpu.state_processing.pubkey_cache import PubkeyCache
from lighthouse_tpu.types.containers import types_for
from lighthouse_tpu.types.helpers import compute_signing_root
from lighthouse_tpu.types.spec import Spec
from lighthouse_tpu import ssz


class Harness:
    def __init__(
        self,
        spec: Spec,
        n_validators: int,
        backend: str = "ref",
        genesis_time: int = 0,
    ):
        self.spec = spec
        self.t = types_for(spec)
        self.keypairs = bls.interop_keypairs(n_validators)
        self.state = interop_genesis_state(
            [kp.pk.to_bytes() for kp in self.keypairs], genesis_time, spec
        )
        self.backend = backend
        self.pubkey_cache = PubkeyCache()
        self.pubkey_cache.import_new(self.state)
        self.fork_name = spec.fork_name_at_epoch(0)
        # attestations produced at the previous slot, pending inclusion
        self.pending_attestations = []
        # optional bellatrix payload source: callable(state) -> ExecutionPayload
        # (None = pre-merge default-empty payloads)
        self.payload_builder = None

    # ------------------------------------------------------------ helpers

    def _sign(self, sk, obj_root: bytes, domain: bytes) -> bytes:
        return sk.sign(compute_signing_root(obj_root, domain)).to_bytes()

    def randao_reveal(self, slot: int) -> bytes:
        """The proposer's RANDAO reveal for `slot` on the current state —
        for tests that drive a chain's production path directly."""
        spec = self.spec
        state = self.state.copy()
        if state.slot < slot:
            state = process_slots(state, slot, spec)
        proposer = get_beacon_proposer_index(state, spec)
        epoch = get_current_epoch(state, spec)
        domain = get_domain(state, spec.DOMAIN_RANDAO, epoch, spec)
        return self._sign(
            self.keypairs[proposer].sk,
            ssz.uint64.hash_tree_root(epoch),
            domain,
        )

    def head_block_root(self, state) -> bytes:
        header = state.latest_block_header
        if bytes(header.state_root) == ZERO_BYTES32:
            header = header.copy()
            header.state_root = cached_state_root(state)
        return type(header).hash_tree_root(header)

    # ----------------------------------------------------- attestations

    def make_attestations(self, state, slot: int):
        """Full-participation attestations for `slot` against the current
        head (call right after importing the block at `slot`)."""
        spec = self.spec
        t = self.t
        epoch = spec.slot_to_epoch(slot)
        cache = CommitteeCache(state, epoch, spec)
        head_root = self.head_block_root(state)
        start_slot = spec.epoch_start_slot(epoch)
        if start_slot == slot:
            target_root = head_root
        else:
            target_root = bytes(get_block_root_at_slot(state, start_slot, spec))
        out = []
        for index in range(cache.committees_per_slot):
            committee = cache.get_beacon_committee(slot, index)
            data = t.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=state.current_justified_checkpoint,
                target=t.Checkpoint(epoch=epoch, root=target_root),
            )
            domain = get_domain(
                state, spec.DOMAIN_BEACON_ATTESTER, epoch, spec
            )
            root = t.AttestationData.hash_tree_root(data)
            sigs = [
                bls.Signature.from_bytes(
                    self._sign(self.keypairs[v].sk, root, domain)
                )
                for v in committee
            ]
            out.append(
                t.Attestation(
                    aggregation_bits=[True] * len(committee),
                    data=data,
                    signature=bls.aggregate_signatures(sigs).to_bytes(),
                )
            )
        return out

    def make_sync_aggregate(self, state, block_root: bytes):
        spec = self.spec
        t = self.t
        prev_slot = max(state.slot, 1) - 1
        domain = get_domain(
            state,
            spec.DOMAIN_SYNC_COMMITTEE,
            spec.slot_to_epoch(prev_slot),
            spec,
        )
        signing_root = compute_signing_root(block_root, domain)
        sigs = []
        bits = []
        for pk in state.current_sync_committee.pubkeys:
            idx = self.pubkey_cache.index_of(bytes(pk))
            bits.append(True)
            sigs.append(self.keypairs[idx].sk.sign(signing_root))
        return t.SyncAggregate(
            sync_committee_bits=bits,
            sync_committee_signature=bls.aggregate_signatures(
                sigs
            ).to_bytes(),
        )

    # -------------------------------------------------------- production

    def produce_block(
        self,
        slot: int,
        attestations,
        deposits=(),
        voluntary_exits=(),
        proposer_slashings=(),
        attester_slashings=(),
        sync_aggregate=None,
        blob_kzg_commitments=(),
    ):
        """Produce a signed block for `slot` on top of the current state."""
        spec = self.spec
        t = self.t
        state = self.state.copy()
        carry_tree_cache(state, self.state)
        state = process_slots(state, slot, spec)
        fork_name = spec.fork_name_at_epoch(get_current_epoch(state, spec))

        proposer = get_beacon_proposer_index(state, spec)
        epoch = get_current_epoch(state, spec)
        randao_domain = get_domain(state, spec.DOMAIN_RANDAO, epoch, spec)
        randao_reveal = self._sign(
            self.keypairs[proposer].sk,
            ssz.uint64.hash_tree_root(epoch),
            randao_domain,
        )

        body_cls = t.block_body_classes[fork_name]
        body = body_cls(
            randao_reveal=randao_reveal,
            eth1_data=state.eth1_data,
            graffiti=b"\x00" * 32,
            attestations=list(attestations),
            deposits=list(deposits),
            voluntary_exits=list(voluntary_exits),
            proposer_slashings=list(proposer_slashings),
            attester_slashings=list(attester_slashings),
        )
        parent_root = self.head_block_root(state)
        if fork_name != "phase0":
            prev_root = (
                parent_root
                if state.slot > 0
                else self.head_block_root(state)
            )
            # caller-provided aggregate (e.g. a chain's contribution pool)
            # wins; default is the harness's omniscient full-participation
            # aggregate
            body.sync_aggregate = (
                sync_aggregate
                if sync_aggregate is not None
                else self.make_sync_aggregate(state, prev_root)
            )
        if fork_name == "bellatrix" and self.payload_builder is not None:
            body.execution_payload = self.payload_builder(state)
        if blob_kzg_commitments:
            if fork_name != "bellatrix":
                raise ValueError(
                    "blob commitments need a bellatrix-or-later body"
                )
            body.blob_kzg_commitments = [
                bytes(c) for c in blob_kzg_commitments
            ]

        block_cls = t.block_classes[fork_name]
        block = block_cls(
            slot=slot,
            proposer_index=proposer,
            parent_root=parent_root,
            state_root=ZERO_BYTES32,
            body=body,
        )

        # compute post-state root with signatures skipped
        trial = state.copy()
        carry_tree_cache(trial, state)
        signed_cls = t.signed_block_classes[fork_name]
        trial_signed = signed_cls(message=block, signature=b"\x00" * 96)
        per_block_processing(
            trial,
            trial_signed,
            spec,
            BlockSignatureStrategy.NO_VERIFICATION,
            self.pubkey_cache,
        )
        block.state_root = cached_state_root(trial)

        proposal_domain = get_domain(
            state,
            spec.DOMAIN_BEACON_PROPOSER,
            spec.slot_to_epoch(slot),
            spec,
        )
        signature = self._sign(
            self.keypairs[proposer].sk,
            block_cls.hash_tree_root(block),
            proposal_domain,
        )
        return signed_cls(message=block, signature=signature)

    # ------------------------------------------------------------ import

    def import_block(self, signed_block, strategy=None, consumer=None):
        spec = self.spec
        state = self.state.copy()
        carry_tree_cache(state, self.state)
        state = process_slots(state, signed_block.message.slot, spec)
        per_block_processing(
            state,
            signed_block,
            spec,
            strategy
            if strategy is not None
            else BlockSignatureStrategy.VERIFY_BULK,
            self.pubkey_cache,
            backend=self.backend,
            seed=int(signed_block.message.slot) + 1,
            consumer=consumer,
        )
        # verify the block's claimed post-state root
        post_root = cached_state_root(state)
        assert bytes(signed_block.message.state_root) == post_root, (
            "state root mismatch"
        )
        self.state = state
        return post_root

    # ----------------------------------------------------------- driving

    def advance_slot_with_block(self, slot: int, strategy=None,
                                consumer=None):
        """Produce + import the block for `slot` including all pending
        attestations, then attest at `slot` with every committee.
        `strategy`/`consumer` forward to import_block (e.g.
        NO_VERIFICATION for a builder whose blocks will be verified
        elsewhere; consumer="bench" in measurement harnesses)."""
        capacity = self.spec.MAX_ATTESTATIONS
        atts = self.pending_attestations[:capacity]
        self.pending_attestations = self.pending_attestations[capacity:]
        block = self.produce_block(slot, atts)
        self.import_block(block, strategy=strategy, consumer=consumer)
        self.pending_attestations.extend(
            self.make_attestations(self.state, slot)
        )
        return block

    def make_blob_sidecars(self, signed_block, blobs):
        """Build the sidecars for a block produced with
        blob_kzg_commitments (one per blob, in index order): KZG proofs
        against the dev trusted setup plus the signed header binding
        each sidecar to the block root."""
        from lighthouse_tpu import kzg

        t = self.t
        msg = signed_block.message
        header = t.SignedBeaconBlockHeader(
            message=t.BeaconBlockHeader(
                slot=msg.slot,
                proposer_index=msg.proposer_index,
                parent_root=bytes(msg.parent_root),
                state_root=bytes(msg.state_root),
                body_root=type(msg.body).hash_tree_root(msg.body),
            ),
            signature=bytes(signed_block.signature),
        )
        out = []
        for i, blob in enumerate(blobs):
            commitment = bytes(msg.body.blob_kzg_commitments[i])
            out.append(
                t.BlobSidecar(
                    index=i,
                    blob=bytes(blob),
                    kzg_commitment=commitment,
                    kzg_proof=kzg.compute_blob_kzg_proof(
                        bytes(blob), commitment, consumer="kzg"
                    ),
                    signed_block_header=header,
                )
            )
        return out

    def make_data_column_sidecars(self, signed_block, blobs):
        """Build the FULL column-sidecar set for a block produced with
        blob_kzg_commitments (PeerDAS shape): every blob is RS-extended
        and cut into cells (da/), column k collects cell k of every
        blob plus its per-cell KZG proofs, and the signed header binds
        each column to the block root. Deterministic — re-running over
        the same blobs yields byte-identical sidecars, which is what
        lets reconstruction-regenerated columns re-serve cleanly."""
        from lighthouse_tpu.da import cells as da_cells
        from lighthouse_tpu.da import geometry_for_spec

        geo = geometry_for_spec(self.spec)
        t = self.t
        msg = signed_block.message
        header = t.SignedBeaconBlockHeader(
            message=t.BeaconBlockHeader(
                slot=msg.slot,
                proposer_index=msg.proposer_index,
                parent_root=bytes(msg.parent_root),
                state_root=bytes(msg.state_root),
                body_root=type(msg.body).hash_tree_root(msg.body),
            ),
            signature=bytes(signed_block.signature),
        )
        commitments = [
            bytes(c) for c in msg.body.blob_kzg_commitments
        ]
        per_blob = [
            da_cells.compute_cells_and_kzg_proofs(
                bytes(blob), geo, consumer="da_cells"
            )
            for blob in blobs
        ]
        out = []
        for k in range(geo.num_cells):
            out.append(
                t.DataColumnSidecar(
                    index=k,
                    column=[cells[k] for cells, _ in per_blob],
                    kzg_commitments=commitments,
                    kzg_proofs=[proofs[k] for _, proofs in per_blob],
                    signed_block_header=header,
                )
            )
        return out

    def run_slots(self, n: int):
        start = self.state.slot + 1
        for slot in range(start, start + n):
            self.advance_slot_with_block(slot)

    @property
    def finalized_epoch(self) -> int:
        return self.state.finalized_checkpoint.epoch

    @property
    def justified_epoch(self) -> int:
        return self.state.current_justified_checkpoint.epoch
