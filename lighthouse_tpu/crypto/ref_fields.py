"""Pure-Python reference implementation of the BLS12-381 field tower.

Fp  : ints mod P
Fp2 : (c0, c1)            = c0 + c1*u,        u^2 = -1
Fp6 : (a0, a1, a2)        = a0 + a1*v + a2*v^2, v^3 = xi = 1+u, ai in Fp2
Fp12: (b0, b1)            = b0 + b1*w,        w^2 = v,          bi in Fp6

This is the ground truth used by tests to validate the JAX/TPU limb kernels
in `lighthouse_tpu.ops`. It mirrors the semantics of the reference client's
`blst` backend (crypto/bls/src/impls/blst.rs) at the mathematical level; no
code is shared with it.

Functional style (plain tuples) so every operation has a 1:1 JAX analog.
"""

from .constants import P, XI, FROB_GAMMA

# ---------------------------------------------------------------- Fp


def fp_add(a, b):
    return (a + b) % P


def fp_sub(a, b):
    return (a - b) % P


def fp_mul(a, b):
    return (a * b) % P


def fp_neg(a):
    return (-a) % P


def fp_inv(a):
    return pow(a, -1, P)


def fp_sqrt(a):
    """Square root in Fp (p % 4 == 3). Returns None if no root exists."""
    root = pow(a, (P + 1) // 4, P)
    return root if root * root % P == a % P else None


# ---------------------------------------------------------------- Fp2

FP2_ZERO = (0, 0)
FP2_ONE = (1, 0)


def fp2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fp2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fp2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)


def fp2_sqr(a):
    a0, a1 = a
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    return ((a0 + a1) * (a0 - a1) % P, 2 * a0 * a1 % P)


def fp2_scalar(a, k):
    return (a[0] * k % P, a[1] * k % P)


def fp2_conj(a):
    return (a[0], (-a[1]) % P)


def fp2_inv(a):
    a0, a1 = a
    norm_inv = pow(a0 * a0 + a1 * a1, -1, P)
    return (a0 * norm_inv % P, (-a1) * norm_inv % P)


def fp2_pow(a, e):
    result = FP2_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp2_mul(result, base)
        base = fp2_sqr(base)
        e >>= 1
    return result


def fp2_mul_by_xi(a):
    # (c0 + c1 u)(1 + u) = (c0 - c1) + (c0 + c1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def fp2_sqrt(a):
    """Square root in Fp2 via the p % 4 == 3 method. None if no root."""
    if a == FP2_ZERO:
        return FP2_ZERO
    cand = fp2_pow(a, (P * P + 7) // 16)
    # cand^2 = a * s where s^8 = 1; fix up by multiplying cand with an 8th
    # root of unity t such that (cand*t)^2 == a.
    roots = _eighth_roots_of_unity()
    for t in roots:
        r = fp2_mul(cand, t)
        if fp2_sqr(r) == (a[0] % P, a[1] % P):
            return r
    return None


_EIGHTH_ROOTS = None


def _eighth_roots_of_unity():
    global _EIGHTH_ROOTS
    if _EIGHTH_ROOTS is None:
        # u has order 4 (u^2 = -1); powers of u give the 4th roots of unity.
        roots = [FP2_ONE]
        for _ in range(3):
            roots.append(fp2_mul(roots[-1], (0, 1)))
        # An 8th root: sqrt(u) = (a, -a) with a^2 = -1/2. Since P % 8 == 3,
        # both -1 and 2 are non-residues in Fp, hence -1/2 IS a residue.
        a = pow((-pow(2, -1, P)) % P, (P + 1) // 4, P)
        assert a * a % P == (-pow(2, -1, P)) % P
        eighth = (a, P - a)
        assert fp2_sqr(eighth) == (0, 1)
        roots = roots + [fp2_mul(r, eighth) for r in roots]
        _EIGHTH_ROOTS = roots
    return _EIGHTH_ROOTS


# ---------------------------------------------------------------- Fp6

FP6_ZERO = (FP2_ZERO, FP2_ZERO, FP2_ZERO)
FP6_ONE = (FP2_ONE, FP2_ZERO, FP2_ZERO)


def fp6_add(a, b):
    return tuple(fp2_add(x, y) for x, y in zip(a, b))


def fp6_sub(a, b):
    return tuple(fp2_sub(x, y) for x, y in zip(a, b))


def fp6_neg(a):
    return tuple(fp2_neg(x) for x in a)


def fp6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fp2_mul(a0, b0)
    t1 = fp2_mul(a1, b1)
    t2 = fp2_mul(a2, b2)
    # c0 = t0 + xi*((a1+a2)(b1+b2) - t1 - t2)
    c0 = fp2_add(
        t0,
        fp2_mul_by_xi(
            fp2_sub(fp2_sub(fp2_mul(fp2_add(a1, a2), fp2_add(b1, b2)), t1), t2)
        ),
    )
    # c1 = (a0+a1)(b0+b1) - t0 - t1 + xi*t2
    c1 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a1), fp2_add(b0, b1)), t0), t1),
        fp2_mul_by_xi(t2),
    )
    # c2 = (a0+a2)(b0+b2) - t0 - t2 + t1
    c2 = fp2_add(
        fp2_sub(fp2_sub(fp2_mul(fp2_add(a0, a2), fp2_add(b0, b2)), t0), t2), t1
    )
    return (c0, c1, c2)


def fp6_sqr(a):
    return fp6_mul(a, a)


def fp6_mul_by_v(a):
    # (a0 + a1 v + a2 v^2) * v = xi*a2 + a0 v + a1 v^2
    return (fp2_mul_by_xi(a[2]), a[0], a[1])


def fp6_inv(a):
    a0, a1, a2 = a
    c0 = fp2_sub(fp2_sqr(a0), fp2_mul_by_xi(fp2_mul(a1, a2)))
    c1 = fp2_sub(fp2_mul_by_xi(fp2_sqr(a2)), fp2_mul(a0, a1))
    c2 = fp2_sub(fp2_sqr(a1), fp2_mul(a0, a2))
    norm = fp2_add(
        fp2_mul(a0, c0),
        fp2_mul_by_xi(fp2_add(fp2_mul(a2, c1), fp2_mul(a1, c2))),
    )
    ninv = fp2_inv(norm)
    return (fp2_mul(c0, ninv), fp2_mul(c1, ninv), fp2_mul(c2, ninv))


# ---------------------------------------------------------------- Fp12

FP12_ZERO = (FP6_ZERO, FP6_ZERO)
FP12_ONE = (FP6_ONE, FP6_ZERO)


def fp12_add(a, b):
    return (fp6_add(a[0], b[0]), fp6_add(a[1], b[1]))


def fp12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fp6_mul(a0, b0)
    t1 = fp6_mul(a1, b1)
    c0 = fp6_add(t0, fp6_mul_by_v(t1))
    c1 = fp6_sub(fp6_sub(fp6_mul(fp6_add(a0, a1), fp6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fp12_sqr(a):
    return fp12_mul(a, a)


def fp12_conj(a):
    """Conjugation = Frobenius^6: negates the w-part."""
    return (a[0], fp6_neg(a[1]))


def fp12_inv(a):
    a0, a1 = a
    norm = fp6_sub(fp6_sqr(a0), fp6_mul_by_v(fp6_sqr(a1)))
    ninv = fp6_inv(norm)
    return (fp6_mul(a0, ninv), fp6_neg(fp6_mul(a1, ninv)))


def fp12_pow(a, e):
    if e < 0:
        return fp12_pow(fp12_inv(a), -e)
    result = FP12_ONE
    base = a
    while e > 0:
        if e & 1:
            result = fp12_mul(result, base)
        base = fp12_sqr(base)
        e >>= 1
    return result


def fp12_frobenius(a):
    """a^p on Fp12."""
    (a00, a01, a02), (a10, a11, a12) = a
    # Conjugate every Fp2 coefficient, then scale by gamma powers:
    # coefficient of v^i in c0-part picks up gamma[2i], in c1-part (w v^i)
    # picks up gamma[2i+1].
    c0 = (
        fp2_conj(a00),
        fp2_mul(fp2_conj(a01), FROB_GAMMA[2]),
        fp2_mul(fp2_conj(a02), FROB_GAMMA[4]),
    )
    c1 = (
        fp2_mul(fp2_conj(a10), FROB_GAMMA[1]),
        fp2_mul(fp2_conj(a11), FROB_GAMMA[3]),
        fp2_mul(fp2_conj(a12), FROB_GAMMA[5]),
    )
    return (c0, c1)
