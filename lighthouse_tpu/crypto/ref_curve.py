"""Pure-Python reference implementation of the BLS12-381 groups G1 and G2.

Jacobian coordinates (X, Y, Z): affine (X/Z^2, Y/Z^3); infinity is Z == 0.
Generic over the coordinate field so the exact same formulas serve
G1 (over Fp) and G2 (over Fp2) — and, ported to limb arithmetic, the JAX
device kernels in `lighthouse_tpu.ops.curve`.
"""

from . import ref_fields as ff
from .constants import P, B_G1, B_G2, G1_X, G1_Y, G2_X, G2_Y, R, H1, H2


class FpField:
    zero = 0
    one = 1
    add = staticmethod(ff.fp_add)
    sub = staticmethod(ff.fp_sub)
    mul = staticmethod(ff.fp_mul)
    neg = staticmethod(ff.fp_neg)
    inv = staticmethod(ff.fp_inv)

    @staticmethod
    def sqr(a):
        return a * a % P

    @staticmethod
    def is_zero(a):
        return a % P == 0

    @staticmethod
    def scalar(a, k):
        return a * k % P


class Fp2Field:
    zero = ff.FP2_ZERO
    one = ff.FP2_ONE
    add = staticmethod(ff.fp2_add)
    sub = staticmethod(ff.fp2_sub)
    mul = staticmethod(ff.fp2_mul)
    neg = staticmethod(ff.fp2_neg)
    inv = staticmethod(ff.fp2_inv)
    sqr = staticmethod(ff.fp2_sqr)
    scalar = staticmethod(ff.fp2_scalar)

    @staticmethod
    def is_zero(a):
        return a[0] % P == 0 and a[1] % P == 0


class CurveGroup:
    """Short-Weierstrass y^2 = x^3 + b over field F, Jacobian coordinates."""

    def __init__(self, field, b, gen_affine, name, cofactor):
        self.F = field
        self.b = b
        self.name = name
        self.cofactor = cofactor
        self.generator = (gen_affine[0], gen_affine[1], field.one)

    @property
    def infinity(self):
        return (self.F.one, self.F.one, self.F.zero)

    def is_infinity(self, pt):
        return self.F.is_zero(pt[2])

    def is_on_curve(self, pt):
        F = self.F
        if self.is_infinity(pt):
            return True
        x, y, z = pt
        # y^2 = x^3 + b z^6
        z2 = F.sqr(z)
        z6 = F.mul(F.sqr(z2), z2)
        return F.sub(F.sqr(y), F.add(F.mul(F.sqr(x), x), F.mul(self.b, z6))) == (
            F.zero
        )

    def to_affine(self, pt):
        F = self.F
        if self.is_infinity(pt):
            return None
        x, y, z = pt
        zinv = F.inv(z)
        zinv2 = F.sqr(zinv)
        return (F.mul(x, zinv2), F.mul(y, F.mul(zinv2, zinv)))

    def from_affine(self, aff):
        if aff is None:
            return self.infinity
        return (aff[0], aff[1], self.F.one)

    def eq(self, p, q):
        F = self.F
        if self.is_infinity(p) or self.is_infinity(q):
            return self.is_infinity(p) and self.is_infinity(q)
        # X1 Z2^2 == X2 Z1^2 and Y1 Z2^3 == Y2 Z1^3
        z1s, z2s = F.sqr(p[2]), F.sqr(q[2])
        if F.sub(F.mul(p[0], z2s), F.mul(q[0], z1s)) != F.zero:
            return False
        z1c, z2c = F.mul(z1s, p[2]), F.mul(z2s, q[2])
        return F.sub(F.mul(p[1], z2c), F.mul(q[1], z1c)) == F.zero

    def double(self, pt):
        F = self.F
        x, y, z = pt
        if self.is_infinity(pt) or F.is_zero(y):
            return self.infinity
        a = F.sqr(x)
        b = F.sqr(y)
        c = F.sqr(b)
        # d = 2*((x+b)^2 - a - c)
        d = F.scalar(F.sub(F.sub(F.sqr(F.add(x, b)), a), c), 2)
        e = F.scalar(a, 3)
        f = F.sqr(e)
        x3 = F.sub(f, F.scalar(d, 2))
        y3 = F.sub(F.mul(e, F.sub(d, x3)), F.scalar(c, 8))
        z3 = F.scalar(F.mul(y, z), 2)
        return (x3, y3, z3)

    def add(self, p, q):
        F = self.F
        if self.is_infinity(p):
            return q
        if self.is_infinity(q):
            return p
        x1, y1, z1 = p
        x2, y2, z2 = q
        z1s = F.sqr(z1)
        z2s = F.sqr(z2)
        u1 = F.mul(x1, z2s)
        u2 = F.mul(x2, z1s)
        s1 = F.mul(y1, F.mul(z2s, z2))
        s2 = F.mul(y2, F.mul(z1s, z1))
        if u1 == u2:
            if s1 == s2:
                return self.double(p)
            return self.infinity
        h = F.sub(u2, u1)
        i = F.sqr(F.scalar(h, 2))
        j = F.mul(h, i)
        rr = F.scalar(F.sub(s2, s1), 2)
        v = F.mul(u1, i)
        x3 = F.sub(F.sub(F.sqr(rr), j), F.scalar(v, 2))
        y3 = F.sub(F.mul(rr, F.sub(v, x3)), F.scalar(F.mul(s1, j), 2))
        z3 = F.mul(F.scalar(F.mul(z1, z2), 2), h)
        return (x3, y3, z3)

    def neg(self, pt):
        return (pt[0], self.F.neg(pt[1]), pt[2])

    def mul_scalar(self, pt, k):
        if k < 0:
            return self.mul_scalar(self.neg(pt), -k)
        result = self.infinity
        addend = pt
        while k:
            if k & 1:
                result = self.add(result, addend)
            addend = self.double(addend)
            k >>= 1
        return result

    def msm(self, points, scalars):
        """Reference multi-scalar multiplication (naive)."""
        acc = self.infinity
        for pt, s in zip(points, scalars, strict=True):
            acc = self.add(acc, self.mul_scalar(pt, s))
        return acc

    def in_subgroup(self, pt):
        """[r]P == inf — the deserialization-time subgroup policy check
        (blst.rs key_validate / sig subgroup). Routed through the native
        C ladder (native/g2decomp.c, ~40x the Python scalar mul) with
        this Python path as fallback and ground truth."""
        if self.is_infinity(pt):
            return True
        from lighthouse_tpu.native import g2decomp

        if g2decomp.available():
            aff = self.to_affine(pt)
            got = (
                g2decomp.g1_in_subgroup(aff[0], aff[1])
                if self.name == "G1"
                else g2decomp.g2_in_subgroup(aff[0], aff[1])
            )
            if got is not None:
                return got
        return self.is_infinity(self.mul_scalar(pt, R))

    def clear_cofactor(self, pt):
        return self.mul_scalar(pt, self.cofactor)


G1 = CurveGroup(FpField, B_G1, (G1_X, G1_Y), "G1", H1)
G2 = CurveGroup(Fp2Field, B_G2, (G2_X, G2_Y), "G2", H2)
