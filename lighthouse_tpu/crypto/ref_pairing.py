"""Pure-Python reference implementation of the optimal ate pairing on BLS12-381.

Miller loop over the twist E'(Fp2) with line evaluation at P in G1, followed
by the final exponentiation (easy part + x-addition-chain hard part).

Untwist convention: psi(x', y') = (x'/w^2, y'/w^3) maps E'(Fp2) -> E(Fp12)
with the tower w^2 = v, v^3 = xi = 1+u (so w^6 = xi and the twist equation
y^2 = x^3 + 4*xi maps onto y^2 = x^3 + 4). The tangent/chord line through
T = (a, b) on the twist, evaluated at affine P = (Px, Py) in G1, is (after
scaling by w^3, which lies in the proper subfield Fp4 and is therefore
annihilated by the final exponentiation):

    l(P) * w^3 = (lam*a - b)  -  (lam * Px) * w^2  +  Py * w^3

where lam in Fp2 is the twist-coordinate slope. w^2 = v is the (c0, v^1)
slot and w^3 = w*v the (c1, v^1) slot of Fp12 = Fp6 + Fp6*w.
"""

from . import ref_fields as ff
from .constants import BLS_X, BLS_X_ABS, P, R
from .ref_curve import G1 as G1_GROUP
from .ref_curve import G2 as G2_GROUP
from .ref_curve import Fp2Field

F2 = Fp2Field


def _line_to_fp12(w0_term, w2_term, py_term):
    """Build the sparse Fp12 line element.

    w0_term/w2_term in Fp2 (coefficients of w^0 and w^2); py_term in Fp
    (coefficient of w^3).
    """
    c0 = (w0_term, w2_term, ff.FP2_ZERO)
    c1 = (ff.FP2_ZERO, (py_term % P, 0), ff.FP2_ZERO)
    return (c0, c1)


def _dbl_step(t, p_affine):
    """Double T on the twist; return (2T, line_{T,T}(P)) as Fp12."""
    px, py = p_affine
    a, b = t  # affine twist coords in Fp2
    # lambda = 3a^2 / 2b
    lam = F2.mul(
        F2.scalar(F2.sqr(a), 3),
        F2.inv(F2.scalar(b, 2)),
    )
    a3 = F2.sub(F2.sqr(lam), F2.scalar(a, 2))
    b3 = F2.sub(F2.mul(lam, F2.sub(a, a3)), b)
    line = _line_to_fp12(
        F2.sub(F2.mul(lam, a), b),
        F2.neg(F2.scalar(lam, px)),
        py,
    )
    return (a3, b3), line


def _add_step(t, q, p_affine):
    """Add Q to T on the twist; return (T+Q, line_{T,Q}(P)) as Fp12."""
    px, py = p_affine
    ax, ay = t
    bx, by = q
    lam = F2.mul(F2.sub(by, ay), F2.inv(F2.sub(bx, ax)))
    cx = F2.sub(F2.sub(F2.sqr(lam), ax), bx)
    cy = F2.sub(F2.mul(lam, F2.sub(ax, cx)), ay)
    line = _line_to_fp12(
        F2.sub(F2.mul(lam, ax), ay),
        F2.neg(F2.scalar(lam, px)),
        py,
    )
    return (cx, cy), line


def miller_loop(pairs):
    """Product of Miller loops over [(P_affine_g1, Q_affine_g2), ...].

    P is an affine G1 point (x, y) ints; Q an affine twist point ((..),(..)).
    Pairs where either side is None (infinity) are skipped (contribute 1).
    """
    pairs = [(p, q) for p, q in pairs if p is not None and q is not None]
    if not pairs:
        return ff.FP12_ONE
    f = ff.FP12_ONE
    ts = [q for _, q in pairs]
    bits = bin(BLS_X_ABS)[3:]  # skip leading 1
    for bit in bits:
        f = ff.fp12_sqr(f)
        for i, (p, q) in enumerate(pairs):
            ts[i], line = _dbl_step(ts[i], p)
            f = ff.fp12_mul(f, line)
        if bit == "1":
            for i, (p, q) in enumerate(pairs):
                ts[i], line = _add_step(ts[i], q, p)
                f = ff.fp12_mul(f, line)
    if BLS_X < 0:
        f = ff.fp12_conj(f)
    return f


def _pow_x(f):
    """f^|x| over the fixed 64-bit parameter."""
    return ff.fp12_pow(f, BLS_X_ABS)


def _pow_neg_x(f):
    """f^x for the (negative) BLS parameter x."""
    return ff.fp12_conj(_pow_x(f))


def final_exponentiation(f):
    """f^((p^12-1)/r) — actually f^(3*(p^12-1)/r), equivalent for ==1 tests.

    Easy part: f^((p^6-1)(p^2+1)). Hard part via the decomposition
    3*(p^4-p^2+1)/r = (x-1)^2 * (x+p) * (x^2+p^2-1) + 3, verified
    programmatically in tests against the integer exponent.
    """
    # easy part
    f = ff.fp12_mul(ff.fp12_conj(f), ff.fp12_inv(f))  # f^(p^6 - 1)
    f = ff.fp12_mul(ff.fp12_frobenius(ff.fp12_frobenius(f)), f)  # ^(p^2 + 1)
    # hard part (3x multiple)
    t0 = ff.fp12_mul(_pow_neg_x(f), ff.fp12_conj(f))  # f^(x-1)
    t1 = ff.fp12_mul(_pow_neg_x(t0), ff.fp12_conj(t0))  # f^((x-1)^2)
    t2 = ff.fp12_mul(_pow_neg_x(t1), ff.fp12_frobenius(t1))  # ^(x+p)
    t3 = ff.fp12_mul(
        _pow_neg_x(_pow_neg_x(t2)),
        ff.fp12_mul(
            ff.fp12_frobenius(ff.fp12_frobenius(t2)), ff.fp12_conj(t2)
        ),
    )  # ^(x^2 + p^2 - 1)
    f3 = ff.fp12_mul(ff.fp12_mul(f, f), f)
    return ff.fp12_mul(t3, f3)


def pairing(p_g1, q_g2):
    """Full pairing e(P, Q) for affine P in G1, affine twist Q in G2."""
    return final_exponentiation(miller_loop([(p_g1, q_g2)]))


def multi_pairing_is_one(pairs):
    """Check prod e(P_i, Q_i) == 1 with a single shared final
    exponentiation. Staged under the tracer so every host-side pairing
    check attributes its Miller-loop vs final-exp wall time."""
    from lighthouse_tpu.common.tracing import span

    with span("verify/miller_loop", n_pairs=len(pairs)):
        f = miller_loop(pairs)
    with span("verify/final_exp"):
        return final_exponentiation(f) == ff.FP12_ONE


def pairing_check_points(g1_jacobian_pts, g2_jacobian_pts):
    """Convenience: pairing product check over Jacobian inputs."""
    pairs = [
        (G1_GROUP.to_affine(p), G2_GROUP.to_affine(q))
        for p, q in zip(g1_jacobian_pts, g2_jacobian_pts, strict=True)
    ]
    return multi_pairing_is_one(pairs)
