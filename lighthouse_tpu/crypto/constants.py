"""BLS12-381 curve constants.

This module is the single source of truth for curve parameters used by both
the pure-Python reference implementation (`ref_fields`, `ref_curve`,
`ref_pairing`) and the JAX/TPU device kernels (`lighthouse_tpu.ops`).

Parity note (vs reference implementation being replaced): the reference
client routes all BLS12-381 operations through the `blst` C library behind
`crypto/bls/src/impls/blst.rs`; the constants here correspond to the same
curve (draft-irtf-cfrg-pairing-friendly-curves BLS12-381) with the Ethereum
ciphersuite DST.

All derived constants (Frobenius coefficients, Montgomery parameters) are
computed at import time from first principles rather than embedded as magic
numbers, so they are self-auditing.
"""

# --- Base field / scalar field -------------------------------------------------

# Field modulus p (381 bits)
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB

# Subgroup order r (255 bits) — order of G1, G2, and GT
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# BLS curve parameter x (negative). p = (x-1)^2/3 * r + x, r = x^4 - x^2 + 1.
BLS_X = -0xD201000000010000
BLS_X_ABS = 0xD201000000010000

# Curve equations: E/Fp: y^2 = x^3 + 4;  E'/Fp2: y^2 = x^3 + 4(1+u)
B_G1 = 4
B_G2 = (4, 4)  # 4 + 4u in Fp2, represented as (c0, c1)

# Quadratic non-residue used to build Fp2 = Fp[u]/(u^2 + 1): -1.
# Sextic twist / tower constant: xi = 1 + u (Fp6 = Fp2[v]/(v^3 - xi)).
XI = (1, 1)

# --- Generators -----------------------------------------------------------------

G1_X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
G1_Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1

G2_X = (
    0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
    0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E,
)
G2_Y = (
    0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
    0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE,
)

# --- Cofactors ------------------------------------------------------------------

# G1 cofactor h1 = (x-1)^2 / 3
H1 = (BLS_X - 1) ** 2 // 3
assert (P + 1 - (BLS_X + 1)) == H1 * R, "G1 order sanity: #E(Fp) = h1 * r"

# G2 cofactor (standard constant; sanity-checked in tests by [r]([h2]Q) = inf)
H2 = 0x5D543A95414E7F1091D50792876A202CD91DE4547085ABAA68A205B2E5A7DDFA628F1CB4D9E82EF21537E293A6691AE1616EC6E786F0C70CF1C38E31C7238E5

# Effective cofactor for G2 cofactor clearing via simple scalar multiplication.
# (RFC 9380 h_eff for BLS12-381 G2 uses the Budroni-Pintore method; plain
# multiplication by h2 also lands in the subgroup and is what we use for the
# reference path.)

# --- Ethereum BLS signature ciphersuite ----------------------------------------

# Domain separation tag used by Ethereum consensus (hash-to-G2, SSWU, XMD:SHA-256)
# Matches the DST in the reference client's blst backend.
DST_G2 = b"BLS_SIG_BLS12381G2_XMD:SHA-256_SSWU_RO_POP_"

# --- Derived: Frobenius coefficients (computed, not embedded) -------------------


def _fp2_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    return ((a0 * b0 - a1 * b1) % P, (a0 * b1 + a1 * b0) % P)


def _fp2_pow(a, e):
    result = (1, 0)
    base = a
    while e > 0:
        if e & 1:
            result = _fp2_mul(result, base)
        base = _fp2_mul(base, base)
        e >>= 1
    return result


# xi^((p-1)/6) and its powers: used for Fp12 Frobenius and Fp6 Frobenius.
# gamma[i] = xi^(i*(p-1)/6) for i in 0..5
FROB_GAMMA = [_fp2_pow(XI, i * (P - 1) // 6) for i in range(6)]

# Fp6 Frobenius: v^p = gamma2 * v  (gamma2 = xi^((p-1)/3)),
#                v^2p = gamma4 * v^2 (gamma4 = xi^(2(p-1)/3))
FROB6_C1 = FROB_GAMMA[2]
FROB6_C2 = FROB_GAMMA[4]

# --- Montgomery parameters for the device limb representation -------------------

# Device representation: NLIMBS limbs of LIMB_BITS bits each, little-endian,
# held in int32 lanes. 32 limbs x 12 bits = 384 bits >= 381.
LIMB_BITS = 12
NLIMBS = 32
LIMB_MASK = (1 << LIMB_BITS) - 1
MONT_R = 1 << (LIMB_BITS * NLIMBS)  # 2^384
MONT_R_MOD_P = MONT_R % P
MONT_R2_MOD_P = (MONT_R * MONT_R) % P
# -p^-1 mod 2^LIMB_BITS (per-limb Montgomery factor)
MONT_N0_INV = (-pow(P, -1, 1 << LIMB_BITS)) % (1 << LIMB_BITS)

assert (P * pow(P, -1, MONT_R)) % MONT_R == 1


def int_to_limbs(v: int) -> list[int]:
    """Little-endian base-2^LIMB_BITS decomposition (length NLIMBS)."""
    return [(v >> (LIMB_BITS * i)) & LIMB_MASK for i in range(NLIMBS)]


def limbs_to_int(limbs) -> int:
    acc = 0
    for i, limb in enumerate(limbs):
        acc += int(limb) << (LIMB_BITS * i)
    return acc
